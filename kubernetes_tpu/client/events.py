"""Event recording — user-facing explainability ("FailedScheduling" etc.).

Parity target: staging/src/k8s.io/client-go/tools/record/event.go
(`EventRecorder.Eventf` → Event API objects with involvedObject/reason/message,
count-aggregated). The scheduler must keep emitting per-pod failure reasons even
when plugins fuse into one XLA program (SURVEY §5.5) — the per-plugin unsat
masks feed `reason`/`message` here.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import Mapping

from kubernetes_tpu.api.meta import name_of, namespace_of, new_object, now_iso
from kubernetes_tpu.store.mvcc import MVCCStore, StoreError

logger = logging.getLogger(__name__)
_seq = itertools.count(1)


def _is_decade(n: int) -> bool:
    """True at 1, 10, 100, 1000, ... — the buffer-full drop log fires
    once per decade of drops per (source, reason)."""
    while n >= 10 and n % 10 == 0:
        n //= 10
    return n == 1


class _SpamFilter:
    """Per-(source, reason) token bucket (events_cache.go
    EventSourceObjectSpamFilter, keyed coarser: the reference keys by
    source+object; at scheduler_perf scale per-object buckets never
    fill, so the budget here is per reason FAMILY — a FailedScheduling
    retry storm drains its own bucket without touching "Scheduled"'s)."""

    def __init__(self, burst: int = 512, qps: float = 256.0):
        self.burst = burst
        self.qps = qps
        #: (component, reason) -> [tokens, last_refill_monotonic]
        self._buckets: dict[tuple[str, str], list[float]] = {}

    def allow(self, source: str, reason: str) -> bool:
        now = time.monotonic()
        b = self._buckets.get((source, reason))
        if b is None:
            self._buckets[(source, reason)] = [self.burst - 1.0, now]
            return True
        tokens = min(self.burst, b[0] + (now - b[1]) * self.qps)
        b[1] = now
        if tokens < 1.0:
            b[0] = tokens
            return False
        b[0] = tokens - 1.0
        return True


class EventRecorder:
    """Buffered broadcaster: events are queued synchronously and drained by
    ONE background task (the reference's record.EventBroadcaster watch loop)
    instead of one asyncio task per event — at scheduler_perf scale the
    per-event task + write copies were a top host cost."""

    #: Bounded queue, reference semantics: record.NewBroadcaster(1000)
    #: with DropIfChannelFull — under a scheduling burst the sink cannot
    #: keep up, and events beyond the buffer are dropped (counted), never
    #: allowed to backpressure the scheduling path.
    MAX_PENDING = 1000

    #: Reasons that carry per-pod signal a drop would DESTROY (the
    #: 1000-agent mark-Running shedding fix): "Scheduled" is emitted once
    #: per bind, so unlike a FailedScheduling retry storm no later event
    #: repeats the information. Priority events (a) bypass the spam
    #: filter, (b) ride a deeper bound (MAX_PENDING_PRIORITY), (c) may
    #: evict a buffered non-priority event when the shared bound is hit,
    #: and (d) drain first.
    PRIORITY_REASONS = frozenset({"Scheduled"})

    #: bound for priority-reason events: deep enough to absorb one
    #: scheduler super-batch of binds (bench batch-size 16384 order),
    #: still a hard cap — DropIfChannelFull semantics survive.
    MAX_PENDING_PRIORITY = 16384

    #: create() concurrency per drain window: the wire transport coalesces
    #: a whole window into one multiplexed frame, so draining 128-wide
    #: instead of one-awaited-create-per-tick is what keeps the buffer
    #: ahead of a scheduling burst (the drop-rate fix).
    DRAIN_WINDOW = 128

    #: The window scales with the drained backlog (batch/4, capped here):
    #: a 5000-agent mark-Running burst lands ~5k events in one batch, and
    #: at a fixed 128 the drain takes ~40 sequential gather round trips —
    #: long enough for the NEXT burst to overflow even the priority bound
    #: (the r8 5000Nodes row's residual ≤1.6k drops). Proportional width
    #: keeps round trips per batch roughly constant as agent count grows.
    DRAIN_WINDOW_MAX = 1024

    def __init__(self, store: MVCCStore, component: str):
        self.store = store
        self.component = component
        #: per-(source, reason) token bucket: a repeating reason that
        #: outruns its refill budget sheds EARLY, before it can occupy
        #: buffer slots the priority reasons need.
        self._spam = _SpamFilter()
        self._pending: list[dict] = []
        #: EventCorrelator-lite (record/events_cache.go EventAggregator):
        #: (kind, namespace, name, type, reason) → the pending Event dict,
        #: so a repeat while the first is still buffered bumps `count`
        #: instead of occupying another slot. Aggregation is buffer-local
        #: — once drained, a recurrence creates a fresh Event (the
        #: reference would PATCH the stored one; not worth a read-modify-
        #: write per recurrence here).
        self._pending_by_key: dict[tuple, dict] = {}
        self._draining = False
        self.dropped = 0
        #: every event() call, dropped or not — dropped/emitted is the
        #: drop RATE consumers (the perf harness detail JSON) report.
        self.emitted = 0
        #: event() calls folded into an already-pending Event's count.
        self.aggregated = 0
        #: drops attributable to the per-(source, reason) spam filter
        #: (a subset of `dropped`).
        self.spam_filtered = 0
        #: buffer-full drops per (source component, reason), for log
        #: rate limiting only — one warning per DECADE of drops per key
        #: (1st, 10th, 100th, ...), so a storm of one reason can't bury
        #: the first drop of another. The public counters above are the
        #: accounting; this dict never feeds metrics.
        self._full_drops_by_key: dict[tuple[str, str], int] = {}

    def event(self, obj: Mapping, event_type: str, reason: str, message: str) -> None:
        """Fire-and-forget, like the reference's buffered broadcaster."""
        self.emitted += 1
        agg_key = (obj.get("kind", ""), namespace_of(obj), name_of(obj),
                   event_type, reason)
        pending = self._pending_by_key.get(agg_key)
        if pending is not None:
            pending["count"] = pending.get("count", 1) + 1
            pending["lastTimestamp"] = now_iso()
            self.aggregated += 1
            # Still kick the drainer: the buffer may predate the loop
            # (events recorded before asyncio.run), and an aggregated
            # recurrence must flush it just like a fresh event would.
            self._kick_drain()
            return
        priority = reason in self.PRIORITY_REASONS
        if not priority and not self._spam.allow(self.component, reason):
            # Reason family over its token budget: shed here, before the
            # repeat can occupy a slot (EventSourceObjectSpamFilter).
            self.spam_filtered += 1
            self.dropped += 1
            return
        limit = self.MAX_PENDING_PRIORITY if priority else self.MAX_PENDING
        if len(self._pending) >= limit:
            if priority and self._evict_non_priority():
                self.dropped += 1  # the evicted event
            else:
                self.dropped += 1
                key = (self.component, reason)
                n = self._full_drops_by_key.get(key, 0) + 1
                self._full_drops_by_key[key] = n
                # Log on the 1st, 10th, 100th, ... drop of each
                # (source, reason) — a power-of-ten check, so the log
                # volume is O(log drops) per key however hot the storm.
                if _is_decade(n):
                    logger.warning(
                        "event buffer full (%d pending); dropped %d "
                        "%s/%s events (%d total) so far "
                        "(DropIfChannelFull)",
                        len(self._pending), n, self.component, reason,
                        self.dropped)
                return
        ev = new_object(
            "Event",
            f"{name_of(obj)}.{next(_seq):x}",
            namespace_of(obj) or "default",
            involvedObject={
                "kind": obj.get("kind", ""),
                "name": name_of(obj),
                "namespace": namespace_of(obj),
                "uid": obj.get("metadata", {}).get("uid", ""),
            },
            type=event_type,  # Normal | Warning
            reason=reason,
            message=message,
            source={"component": self.component},
            firstTimestamp=now_iso(),
            count=1,
        )
        self._pending.append(ev)
        self._pending_by_key[agg_key] = ev
        self._kick_drain()

    def _evict_non_priority(self) -> bool:
        """Drop the newest buffered NON-priority event to admit a
        priority one (the drain-priority bump's admission side): under a
        bind burst, "Scheduled" displaces retry noise, never vice versa.
        Scans from the tail — recent entries are the likely noise; runs
        only on the already-degraded buffer-full path."""
        for i in range(len(self._pending) - 1, -1, -1):
            ev = self._pending[i]
            if ev.get("reason") in self.PRIORITY_REASONS:
                continue
            del self._pending[i]
            io = ev.get("involvedObject") or {}
            self._pending_by_key.pop(
                (io.get("kind", ""), io.get("namespace", ""),
                 io.get("name", ""), ev.get("type", ""),
                 ev.get("reason", "")), None)
            return True
        return False

    def _kick_drain(self) -> None:
        if self._draining or not self._pending:
            return
        # Only create the drain coroutine when a loop is actually
        # running — otherwise it would be dropped un-awaited and warn.
        # With no loop (sync unit tests) the buffer flushes with the
        # next event recorded under a loop.
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return
        asyncio.ensure_future(self._drain())
        self._draining = True

    async def _drain(self) -> None:
        try:
            while self._pending:
                batch, self._pending = self._pending, []
                # Batch taken: its entries can no longer aggregate (the
                # writes are in flight); recurrences start fresh Events.
                self._pending_by_key.clear()
                # Drain-priority bump: priority reasons write first, so a
                # mid-drain process exit or store failure loses noise,
                # not per-pod "Scheduled" signal. Stable sort keeps
                # arrival order within each class.
                batch.sort(key=lambda ev:
                           ev.get("reason") not in self.PRIORITY_REASONS)
                window = min(max(self.DRAIN_WINDOW, len(batch) // 4),
                             self.DRAIN_WINDOW_MAX)
                for lo in range(0, len(batch), window):
                    # The recorder built these and never touches them
                    # again (_owned); store rejections are per-event debug
                    # noise (the pre-batch behavior), but a programming
                    # error must stay loud — not vanish into a dropped
                    # gather result.
                    results = await asyncio.gather(
                        *(self.store.create("events", ev, _owned=True,
                                            return_copy=False)
                          for ev in batch[lo:lo + window]),
                        return_exceptions=True)
                    for r in results:
                        if isinstance(r, StoreError):
                            logger.debug("event write failed: %s", r)
                        elif isinstance(r, Exception):
                            logger.exception("event drain error",
                                             exc_info=r)
                        elif isinstance(r, BaseException):
                            raise r  # CancelledError: stop draining
        finally:
            self._draining = False
