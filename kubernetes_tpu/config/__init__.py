from kubernetes_tpu.config.scheduler import (
    ConfigError,
    ProfileConfig,
    SchedulerConfig,
    build_scheduler,
    load_config,
)

__all__ = [
    "ConfigError",
    "ProfileConfig",
    "SchedulerConfig",
    "build_scheduler",
    "load_config",
]
