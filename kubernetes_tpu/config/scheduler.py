"""KubeSchedulerConfiguration — the versioned component-config API.

Parity target: pkg/scheduler/apis/config/types.go +
staging/src/k8s.io/kube-scheduler/config/v1/ (SURVEY §5.6): reference-shaped
YAML loads unchanged — profiles (per-`schedulerName` plugin sets with
per-extension-point enable/disable and score weights), typed per-plugin args
(`NodeResourcesFitArgs.scoringStrategy`, …) via `pluginConfig`, the
`extenders:` list, `percentageOfNodesToScore`, `parallelism`,
`podInitialBackoffSeconds` / `podMaxBackoffSeconds`, `leaderElection`.

North-star seam #3 (SURVEY §5.6): `build_scheduler` hangs the batched TPU
backend off the `TPUScorer` feature gate — default off, flippable with
`--feature-gates=TPUScorer=true`, and removable per-profile with a
`pluginConfig` entry `{name: TPUScorer, args: {enabled: false}}` (our
extension; the reference reserves pluginConfig names for plugins, and
TPUScorer is exactly that: the fused device "plugin set").
"""

from __future__ import annotations

import logging
from typing import Any, Mapping

from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_PLUGINS,
    DEFAULT_SCORE_WEIGHTS,
    IN_TREE,
    build_plugins,
)
from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATES, FeatureGate

logger = logging.getLogger(__name__)

GROUP = "kubescheduler.config.k8s.io"
SUPPORTED_VERSIONS = {f"{GROUP}/v1", f"{GROUP}/v1beta3", f"{GROUP}/v1beta2"}
KIND = "KubeSchedulerConfiguration"

#: YAML field name → framework extension-point name.
POINTS = {
    "queueSort": "QueueSort",
    "preEnqueue": "PreEnqueue",
    "preFilter": "PreFilter",
    "filter": "Filter",
    "postFilter": "PostFilter",
    "preScore": "PreScore",
    "score": "Score",
    "reserve": "Reserve",
    "permit": "Permit",
    "preBind": "PreBind",
    "bind": "Bind",
    "postBind": "PostBind",
}

#: pluginConfig names that configure the harness, not a plugin.
_PSEUDO_PLUGINS = {"TPUScorer"}


class ConfigError(ValueError):
    """Invalid KubeSchedulerConfiguration (strict decoding, like the
    reference's scheme which rejects unknown plugins/fields)."""


def _points_of(name: str) -> tuple[str, ...]:
    cls = IN_TREE.get(name)
    if cls is None:
        raise ConfigError(f"unknown plugin {name!r}")
    return cls.EXTENSION_POINTS


class ProfileConfig:
    """One resolved entry of `profiles:` — per-point plugin name lists,
    score weights, per-plugin args."""

    def __init__(self, raw: Mapping | None = None):
        raw = raw or {}
        self.scheduler_name: str = raw.get("schedulerName", "default-scheduler")
        self.percentage_of_nodes_to_score: int | None = \
            raw.get("percentageOfNodesToScore")
        self.plugin_config: dict[str, Mapping] = {}
        for entry in raw.get("pluginConfig") or []:
            name = entry.get("name")
            if name not in IN_TREE and name not in _PSEUDO_PLUGINS:
                raise ConfigError(f"pluginConfig for unknown plugin {name!r}")
            self.plugin_config[name] = entry.get("args") or {}
        self.weights = dict(DEFAULT_SCORE_WEIGHTS)
        self.active = self._resolve(raw.get("plugins") or {})

    def _resolve(self, plugins_cfg: Mapping) -> dict[str, list[str]]:
        """Reference plugin-resolution semantics: defaults per point →
        multiPoint enable/disable → per-point disable ([{name:"*"}] clears)
        → per-point enable (appended, score weight honored)."""
        active: dict[str, list[str]] = {
            point: [n for n in DEFAULT_PLUGINS if point in _points_of(n)]
            for point in POINTS.values()
        }
        mp = plugins_cfg.get("multiPoint") or {}
        mp_disabled = {d.get("name") for d in mp.get("disabled") or []}
        if "*" in mp_disabled:
            active = {point: [] for point in active}
        else:
            for point in active:
                active[point] = [n for n in active[point]
                                 if n not in mp_disabled]
        for e in mp.get("enabled") or []:
            name = e["name"]
            for point in _points_of(name):
                if name not in active[point]:
                    active[point].append(name)
            if "weight" in e:
                self.weights[name] = e["weight"]
        for yaml_point, point in POINTS.items():
            spec = plugins_cfg.get(yaml_point)
            if not spec:
                continue
            disabled = {d.get("name") for d in spec.get("disabled") or []}
            if "*" in disabled:
                active[point] = []
            else:
                active[point] = [n for n in active[point] if n not in disabled]
            for e in spec.get("enabled") or []:
                name = e["name"]
                if point not in _points_of(name):
                    raise ConfigError(
                        f"plugin {name!r} does not implement {point}")
                if name not in active[point]:
                    active[point].append(name)
                if point == "Score" and "weight" in e:
                    self.weights[name] = e["weight"]
        return active

    def build_framework(self, store=None, metrics=None) -> Framework:
        names: list[str] = []
        for point_names in self.active.values():
            for n in point_names:
                if n not in names:
                    names.append(n)
        plugin_args = {k: v for k, v in self.plugin_config.items()
                       if k not in _PSEUDO_PLUGINS}
        # An explicitly-empty plugin set stays empty (build_plugins treats
        # a falsy list as "use defaults").
        plugins = build_plugins(names, plugin_args, store=store) if names else []
        # Framework filters by EXTENSION_POINTS minus `disabled`; express
        # the resolved per-point sets as the complement.
        disabled: dict[str, set[str]] = {}
        for point, point_names in self.active.items():
            off = {n for n in names
                   if point in _points_of(n) and n not in point_names}
            if off:
                disabled[point] = off
        return Framework(plugins, self.weights,
                         profile_name=self.scheduler_name,
                         metrics=metrics, disabled=disabled)

    def tpu_scorer_override(self) -> bool | None:
        args = self.plugin_config.get("TPUScorer")
        if args is None:
            return None
        return bool(args.get("enabled", True))


class SchedulerConfig:
    """Parsed KubeSchedulerConfiguration."""

    def __init__(self, raw: Mapping | None = None):
        raw = dict(raw or {})
        api_version = raw.get("apiVersion", f"{GROUP}/v1")
        if api_version not in SUPPORTED_VERSIONS:
            raise ConfigError(f"unsupported apiVersion {api_version!r} "
                              f"(want one of {sorted(SUPPORTED_VERSIONS)})")
        kind = raw.get("kind", KIND)
        if kind != KIND:
            raise ConfigError(f"unsupported kind {kind!r} (want {KIND})")
        self.api_version = api_version
        self.parallelism: int = raw.get("parallelism", 16)
        self.percentage_of_nodes_to_score: int = \
            raw.get("percentageOfNodesToScore", 0)
        self.pod_initial_backoff: float = \
            raw.get("podInitialBackoffSeconds", 1.0)
        self.pod_max_backoff: float = raw.get("podMaxBackoffSeconds", 10.0)
        le = raw.get("leaderElection") or {}
        self.leader_elect: bool = le.get("leaderElect", False)
        self.leader_lease_duration: float = _seconds(
            le.get("leaseDuration", "15s"))
        self.leader_renew_deadline: float = _seconds(
            le.get("renewDeadline", "10s"))
        self.leader_retry_period: float = _seconds(
            le.get("retryPeriod", "2s"))
        self.leader_lock_name: str = le.get("resourceName", "kube-scheduler")
        self.extenders: list[Mapping] = list(raw.get("extenders") or [])
        self.feature_gates: dict[str, bool] = dict(raw.get("featureGates") or {})
        profiles_raw = raw.get("profiles") or [{}]
        self.profiles = [ProfileConfig(p) for p in profiles_raw]
        seen = set()
        for p in self.profiles:
            if p.scheduler_name in seen:
                raise ConfigError(
                    f"duplicate profile schedulerName {p.scheduler_name!r}")
            seen.add(p.scheduler_name)


def _seconds(v: Any) -> float:
    """Duration: number = seconds; strings accept s/ms/m/h suffix
    (metav1.Duration YAML form, e.g. "15s")."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suffix, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if s.endswith(suffix) and s[: -len(suffix)].replace(".", "").isdigit():
            return float(s[: -len(suffix)]) * mult
    return float(s)


def load_config(source) -> SchedulerConfig:
    """Load from a YAML string, a path, a parsed mapping, or None
    (all-defaults)."""
    if source is None:
        return SchedulerConfig()
    if isinstance(source, SchedulerConfig):
        return source
    if isinstance(source, Mapping):
        return SchedulerConfig(source)
    import yaml
    text = source
    if "\n" not in str(source):
        try:
            with open(source) as f:
                text = f.read()
        except OSError as e:
            raise ConfigError(f"cannot read config {source!r}: {e}") from e
    data = yaml.safe_load(text)
    if not isinstance(data, Mapping):
        raise ConfigError("config must be a YAML mapping")
    return SchedulerConfig(data)


def build_scheduler(store, config=None, *, feature_gates: FeatureGate | None = None,
                    backend=None, metrics=None, seed: int = 0):
    """Config → running-shape Scheduler.

    The `TPUScorer` feature gate selects the batched device backend per
    profile: gate default (off) < `--feature-gates=TPUScorer=true` <
    per-profile `pluginConfig {name: TPUScorer, args: {enabled: ...}}`.
    Profiles with the gate off keep the reference-shaped host path.
    """
    cfg = load_config(config)
    # Resolve gates per call on a private copy: one config's featureGates
    # must not leak into the process-wide defaults or later builds.
    gates = (feature_gates or DEFAULT_FEATURE_GATES).clone()
    for name, val in cfg.feature_gates.items():
        if name not in gates.known():
            # Reference configs carry gates far beyond the ones registered
            # here; unknown names are registered-as-given, not fatal.
            logger.info("registering unknown feature gate %s=%s from config",
                        name, val)
            gates.add(name, "Alpha", bool(val))
            continue
        try:
            gates.set(name, val)
        except ValueError as e:
            raise ConfigError(f"featureGates: {e}") from e

    from kubernetes_tpu.scheduler.scheduler import Scheduler

    profiles = {}
    for p in cfg.profiles:
        fwk = p.build_framework(store=store, metrics=metrics)
        if p.percentage_of_nodes_to_score is not None:
            # Per-profile override (reference scopes this field to its
            # profile; the global value covers the rest).
            fwk.percentage_of_nodes_to_score = p.percentage_of_nodes_to_score
        profiles[p.scheduler_name] = fwk
    sched = Scheduler(
        store, profiles=profiles,
        percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score,
        seed=seed, metrics=metrics,
        pod_initial_backoff=cfg.pod_initial_backoff,
        pod_max_backoff=cfg.pod_max_backoff,
    )
    from kubernetes_tpu.scheduler.extender import HTTPExtender
    sched.extenders = [HTTPExtender.from_config(e) for e in cfg.extenders]

    gate_default = gates.enabled("TPUScorer")
    backend_profiles = set()
    for p in cfg.profiles:
        override = p.tpu_scorer_override()
        if override if override is not None else gate_default:
            backend_profiles.add(p.scheduler_name)
    if backend_profiles:
        if backend is None:
            from kubernetes_tpu.ops import TPUBackend
            backend = TPUBackend()
        sched.attach_backend(backend)
        sched.backend_profiles = backend_profiles
    if cfg.leader_elect:
        # leaderElection.leaderElect: true → the caller runs the scheduler
        # via sched.run_with_leader_election(sched.leader_elector).
        import uuid

        from kubernetes_tpu.client.leaderelection import LeaderElector
        sched.leader_elector = LeaderElector(
            store, cfg.leader_lock_name,
            identity=f"scheduler-{uuid.uuid4().hex[:8]}",
            lease_duration=cfg.leader_lease_duration,
            renew_deadline=cfg.leader_renew_deadline,
            retry_period=cfg.leader_retry_period)
    sched.config = cfg
    return sched
