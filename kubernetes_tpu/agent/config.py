"""Kubelet configuration sources, merged with documented precedence.

Parity target: the kubelet's config story (KubeletConfiguration from
--config plus the retired DynamicKubeletConfig apiserver source, now
the per-node config object pattern): an agent resolves its runtime
knobs from three layers, LOWEST to HIGHEST precedence —

    built-in defaults  <  config FILE  <  APISERVER object

i.e. a field set in the apiserver's per-node config wins over the
same field in the local file, which wins over the default. Merging is
FIELD-BY-FIELD (a source only overrides the keys it actually sets —
setting `leasePeriodSeconds` in the file does not reset the
apiserver's `deviceZones`), unknown keys are ignored with a warning
(a newer control plane must not brick an older agent), and every
resolved field remembers which source set it — the `/configz`
endpoint (agent/server.py) serves both the values and the
attribution, so "why is this agent heartbeating at 5s" is one curl.

The apiserver source is a `kubeletconfigs` object named after the
node, falling back to the cluster-wide `default` object; neither
existing is normal (defaults + file apply).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Mapping

logger = logging.getLogger(__name__)

#: resolved-config fields and their built-in defaults. Values are
#: plain JSON scalars; topologyCoord is the "x,y"/"x,y,z" string the
#: registration label carries (mesh.parse_coord_label's format).
DEFAULTS: dict[str, Any] = {
    "leasePeriodSeconds": 2.0,
    "deviceDriver": "dra.ktpu",
    "deviceZones": 2,
    "topologyCoord": None,
}

#: per-field value coercions — config files are hand-edited, so "5"
#: for a float field must resolve, not crash the agent.
_COERCE = {
    "leasePeriodSeconds": float,
    "deviceDriver": str,
    "deviceZones": int,
    "topologyCoord": lambda v: None if v is None else str(v),
}


class ResolvedConfig:
    """Merged config: `values` (field -> resolved value) + `sources`
    (field -> name of the source that set it)."""

    __slots__ = ("values", "sources")

    def __init__(self, values: dict[str, Any], sources: dict[str, str]):
        self.values = values
        self.sources = sources

    def __getitem__(self, field: str) -> Any:
        return self.values[field]

    def as_configz(self) -> dict:
        """The /configz payload: values + per-field attribution."""
        return {"kubeletconfig": dict(self.values),
                "sources": dict(self.sources)}


def merge_config(*sources: tuple[str, Mapping[str, Any] | None]) \
        -> ResolvedConfig:
    """Merge (name, fields) layers, LAST one wins per field. Callers
    pass layers in precedence order: defaults, file, apiserver."""
    values = dict(DEFAULTS)
    origin = {f: "default" for f in DEFAULTS}
    for name, fields in sources:
        if not fields:
            continue
        for key, raw in fields.items():
            if key not in DEFAULTS:
                logger.warning("kubelet config source %s: unknown field "
                               "%r ignored", name, key)
                continue
            try:
                values[key] = _COERCE[key](raw)
            except (TypeError, ValueError):
                logger.warning("kubelet config source %s: bad value %r "
                               "for %s ignored", name, raw, key)
                continue
            origin[key] = name
    return ResolvedConfig(values, origin)


def load_file_source(path: str | None) -> dict[str, Any]:
    """The --config file layer: a flat JSON object. A missing or
    malformed file is an empty layer (the agent must come up on
    defaults), logged — never fatal."""
    if not path:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            cfg = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        logger.warning("kubelet config file %s unreadable (%s); "
                       "ignoring", path, e)
        return {}
    if not isinstance(cfg, dict):
        logger.warning("kubelet config file %s is not an object; "
                       "ignoring", path)
        return {}
    return cfg


async def fetch_apiserver_source(store, node_name: str) -> dict[str, Any]:
    """The apiserver layer: the `kubeletconfigs` object named after
    this node, else the cluster-wide `default` object, else empty."""
    from kubernetes_tpu.store.mvcc import NotFound, StoreError
    for name in (node_name, "default"):
        try:
            obj = await store.get("kubeletconfigs", f"default/{name}")
        except NotFound:
            continue
        except StoreError:
            logger.warning("kubelet config fetch for %s failed; "
                           "continuing without the apiserver layer",
                           node_name, exc_info=True)
            return {}
        return (obj.get("spec") or {})
    return {}


async def resolve_config(store, node_name: str,
                         config_file: str | None = None,
                         overrides: Mapping[str, Any] | None = None) \
        -> ResolvedConfig:
    """The full three-layer resolve (plus constructor `overrides` as a
    fourth, highest layer — explicit NodeAgent kwargs beat everything,
    the same way a command-line flag beats the kubelet's config file)."""
    return merge_config(
        ("file", load_file_source(config_file)),
        ("apiserver", await fetch_apiserver_source(store, node_name)),
        ("override", overrides),
    )
