"""kubelet analog: `python -m kubernetes_tpu.agent`.

One process per node (kubemark hollow-kubelet shape): connects to the
apiserver's KTPU wire, registers its Node, then runs the sync loop —
field-filtered pod watch, per-pod workers, DRA device Allocate with a
local checkpoint that survives restart.

    python -m kubernetes_tpu.agent --node n0 \
        --server unix:/tmp/ktpu-wire.sock \
        --checkpoint-dir /var/lib/ktpu-agent \
        --allocatable cpu=4,memory=16Gi,pods=32,ktpu.io/tpu=8

Parity target: cmd/kubelet + cmd/kubemark (SURVEY §2.1 rows 14/18).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def parse_allocatable(spec: str) -> dict:
    out: dict = {}
    for part in spec.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpu-agent", description=__doc__)
    ap.add_argument("--node", required=True, help="this node's name")
    ap.add_argument("--server", required=True,
                    help="apiserver wire target (host:port or unix:PATH)")
    ap.add_argument("--checkpoint-dir", default=".",
                    help="device-allocation checkpoint directory")
    ap.add_argument("--allocatable",
                    default="cpu=4,memory=16Gi,pods=110",
                    help="node allocatable, k=v comma list; extended "
                         "resources (with '/') also publish ResourceSlices")
    ap.add_argument("--token", default=None, help="bearer token")
    ap.add_argument("--lease-period", type=float, default=2.0)
    ap.add_argument("--no-register", action="store_true",
                    help="assume the Node object already exists")
    return ap


async def serve(args) -> None:
    from kubernetes_tpu.agent import NodeAgent
    from kubernetes_tpu.apiserver.wire import WireStore

    store = WireStore(args.server, token=args.token,
                      user_agent=f"ktpu-agent/{args.node}")
    agent = NodeAgent(
        store, args.node,
        checkpoint_dir=args.checkpoint_dir,
        node_template={"allocatable": parse_allocatable(args.allocatable)},
        register=not args.no_register,
        lease_period=args.lease_period)
    await agent.start()
    logging.info("agent %s running against %s", args.node, args.server)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await agent.stop()
    await store.close()


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    asyncio.run(serve(args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
