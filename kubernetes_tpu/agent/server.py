"""Kubelet read-only server: /pods, /healthz, /configz.

Parity target: the kubelet's server (pkg/kubelet/server) read
endpoints — the debugging surface an operator curls at a node:

- `/healthz`  — liveness ("ok" while the sync loop owns the process);
- `/pods`     — the agent's LOCAL view of its bound pods (a PodList of
  what the sync loop has observed, which is the interesting object
  when diagnosing agent/apiserver drift — it can legitimately trail
  the apiserver);
- `/configz`  — the RESOLVED kubelet configuration plus per-field
  source attribution (agent/config.py merge_config), so precedence
  questions ("which layer set this lease period") are answerable
  without reading three files.

Bound to loopback by default, port 0 = ephemeral (tests read
`server.port` after start). Read-only by construction: no mutating
route exists.
"""

from __future__ import annotations

import logging

from aiohttp import web

logger = logging.getLogger(__name__)


class AgentServer:
    """The read-only HTTP surface of one NodeAgent."""

    def __init__(self, agent, host: str = "127.0.0.1", port: int = 0):
        self.agent = agent
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/pods", self._pods)
        app.router.add_get("/configz", self._configz)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        server = site._server
        if server is not None and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        logger.info("agent %s: serving on %s:%d",
                    self.agent.node_name, self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers ----------------------------------------------------------

    async def _healthz(self, request: web.Request) -> web.Response:
        healthy = not getattr(self.agent, "_stopped", False)
        return web.Response(text="ok" if healthy else "stopped",
                            status=200 if healthy else 500)

    async def _pods(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"kind": "PodList", "apiVersion": "v1",
             "items": self.agent.resident_pods()})

    async def _configz(self, request: web.Request) -> web.Response:
        cfg = getattr(self.agent, "kubelet_config", None)
        if cfg is None:
            return web.json_response(
                {"error": "config not resolved yet"}, status=503)
        return web.json_response(cfg.as_configz())
