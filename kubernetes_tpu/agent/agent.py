"""Hollow-kubelet node agent: a per-node process with a real sync loop.

Parity target (SURVEY §2.5): pkg/kubelet/kubelet.go `syncLoop` (watch →
per-pod work), pod_workers.go (serialized per-pod workers, latest update
wins), cm/devicemanager (Allocate against the node's device inventory,
checkpointed locally — agent/ledger.py), nodestatus/lease heartbeats,
and kubemark's hollow kubelet (no container runtime: "running" a pod is
a status transition, same as KWOK staging).

TPU-first shape: the agent is a WATCH CONSUMER of the apiserver wire,
filtered server-side to `spec.nodeName=<me>` (the kubelet's field
selector — store/mvcc.py tracked fields), so N agents cost the control
plane one filtered watch each instead of N full pod streams. Device
allocation consumes the DRA claim status the scheduler persisted at
PreBind (plugins/dynamicresources.py `pre_bind`): the agent performs the
kubelet-side Allocate — claim devices -> local ledger -> checkpoint —
and releases on termination.

Run as a process:  python -m kubernetes_tpu.agent --node n0 \
    --server unix:/tmp/ktpu.sock --checkpoint-dir /var/lib/ktpu-agent
"""

from __future__ import annotations

import asyncio
import logging
import os

from kubernetes_tpu.api.meta import (
    name_of,
    namespace_of,
    namespaced_name,
    new_object,
)
from kubernetes_tpu.api.types import (
    make_node,
    make_resource_slice,
    template_devices,
)
from kubernetes_tpu.agent.config import (
    ResolvedConfig,
    merge_config,
    resolve_config,
)
from kubernetes_tpu.agent.ledger import DeviceLedger
from kubernetes_tpu.topology.mesh import MESH_COORD_LABEL
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Expired,
    NotFound,
    StoreError,
)

logger = logging.getLogger(__name__)

COMPLETE_AFTER_ANN = "kwok.x-k8s.io/complete-after"
AGENT_ANN = "ktpu.io/agent"


class NodeAgent:
    """One node's agent: registers the Node, heartbeats its Lease, syncs
    the pods bound to it, allocates claim devices, checkpoints."""

    def __init__(self, store, node_name: str, *,
                 checkpoint_dir: str = ".",
                 node_template: dict | None = None,
                 register: bool = True,
                 lease_period: float | None = None,
                 device_driver: str | None = None,
                 device_zones: int | None = None,
                 topology_coord: str | None = None,
                 config_file: str | None = None):
        self.store = store
        self.node_name = node_name
        self.node_template = node_template or {}
        self.register = register
        # Config resolution (agent/config.py): explicit constructor
        # kwargs are the highest-precedence layer; the file + apiserver
        # layers join at start() (the store isn't reachable yet here).
        # Until then, defaults + overrides govern — same values the old
        # keyword defaults carried.
        self._config_file = config_file
        self._config_overrides = {k: v for k, v in {
            "leasePeriodSeconds": lease_period,
            "deviceDriver": device_driver,
            "deviceZones": device_zones,
            "topologyCoord": topology_coord,
        }.items() if v is not None}
        self.kubelet_config: ResolvedConfig = merge_config(
            ("override", self._config_overrides))
        self._apply_config(self.kubelet_config)
        self.ledger = DeviceLedger(
            os.path.join(checkpoint_dir,
                         f"devices-{node_name}.checkpoint.json"),
            node_name)
        self._tasks: list[asyncio.Task] = []
        self._watch_task: asyncio.Task | None = None
        self._workers: set[asyncio.Task] = set()
        #: pod key -> latest observed object (None = deleted); per-pod
        #: workers drain this map serially per key, latest state wins
        #: (pod_workers.go UpdatePod semantics).
        self._latest: dict[str, dict | None] = {}
        #: pod key -> last observed object — the agent's LOCAL pod view
        #: the kubelet server's /pods endpoint serves.
        self._pods: dict[str, dict] = {}
        self._active: set[str] = set()
        #: pod keys with a staged-completion timer armed (restart-safe:
        #: _sync_pod re-arms for Running pods found after a relist).
        self._armed: set[str] = set()
        self._stopped = False
        self._ip_seq = 0
        # Pod-IP base: sha256 of the node name — a permutation-sensitive
        # hash ('n01' vs 'n10' must not share a /16; byte-sum collided).
        import hashlib
        self._ip_base = (hashlib.sha256(
            node_name.encode()).digest()[0] % 200) + 16

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self._start_register()
        await self._start_sync()

    @classmethod
    async def start_many(cls, agents, window: int = 512) -> None:
        """Batched cold start for an agent fleet (the r12-identified 50k
        headroom: agent STARTUP cost, not the read path). A per-agent
        `start()` serializes its own handshake — register → LIST →
        watch — so a fleet gathered over start() keeps one loop tick per
        agent per round trip. This runs the fleet in two WIDE phases
        instead: every registration first (a window's node creates
        coalesce into one multiplexed wire frame), then every
        LIST+WATCH establishment (the LISTs read one shared watch-cache
        snapshot; on a sharded control plane the S-shard fan-in serves
        windows concurrently instead of serializing per-agent
        handshakes). Windowed so a mid-boot failure still leaves every
        started agent stoppable."""
        agents = list(agents)
        for lo in range(0, len(agents), window):
            await asyncio.gather(
                *(a._start_register() for a in agents[lo:lo + window]))
        for lo in range(0, len(agents), window):
            await asyncio.gather(
                *(a._start_sync() for a in agents[lo:lo + window]))

    def _apply_config(self, cfg: ResolvedConfig) -> None:
        self.lease_period = float(cfg["leasePeriodSeconds"])
        self.device_driver = cfg["deviceDriver"]
        self.device_zones = max(1, int(cfg["deviceZones"]))
        self.topology_coord = cfg["topologyCoord"]

    def resident_pods(self) -> list[dict]:
        """This agent's local view of its bound pods (the /pods
        endpoint's payload), stable key order."""
        return [self._pods[k] for k in sorted(self._pods)]

    async def _start_register(self) -> None:
        """Phase 1: config resolve + local checkpoint restore + Node
        registration (the config layers must land first: the lease
        period and the topology coordinate both feed registration)."""
        self.kubelet_config = await resolve_config(
            self.store, self.node_name, self._config_file,
            self._config_overrides)
        self._apply_config(self.kubelet_config)
        self.ledger.load()
        if self.register:
            await self._register_node()

    async def _start_sync(self) -> None:
        """Phase 2: startup reconcile LIST, watch + lease establishment."""
        # Startup reconcile (syncLoop HandlePodCleanups): restore the
        # checkpoint against the live bound-pod set, then prime workers.
        lst = await self.store.list(
            "pods", fields={"spec.nodeName": self.node_name})
        live = {namespaced_name(p) for p in lst.items}
        dropped = self.ledger.reconcile(live)
        if dropped:
            logger.info("agent %s: reclaimed devices of %d departed pods",
                        self.node_name, len(dropped))
        # Resume the IP sequence past every already-assigned podIP on this
        # node: _ip_seq resets with the process, but Running pods keep
        # their IPs — restarting from 0 would re-issue them.
        for p in lst.items:
            self._ip_seq = max(self._ip_seq, self._ip_seq_of(p))
        for p in lst.items:
            self._observe(namespaced_name(p), p)
        self._watch_task = asyncio.ensure_future(
            self._watch_loop(lst.resource_version))
        self._tasks.append(self._watch_task)
        self._tasks.append(asyncio.ensure_future(self._lease_loop()))

    async def stop(self, graceful: bool = True) -> None:
        """Stop this agent — and only this agent (a shared store/wire
        keeps serving its siblings).

        graceful=True (orderly shutdown, the default): the watch and
        lease loops are cancelled first, then in-flight per-pod workers
        get a short drain window to land their current status write
        before being cancelled themselves.

        graceful=False (node DEATH — the churn battery's fault
        primitive, SURVEY §5.3): every task is cancelled immediately,
        mid-write, and awaited so nothing leaks; no further store
        writes happen, and the Node and Lease objects are deliberately
        left behind to go STALE — the nodelifecycle controller's grace
        period, not this call, decides when the cluster notices the
        death (lease expiry). Local pod/worker state is dropped too:
        a killed agent cannot be restarted in place."""
        self._stopped = True
        loops = list(self._tasks)
        for t in loops:
            t.cancel()
        workers = list(self._workers)
        if graceful and workers:
            # Drain window: a worker mid-_mark_running finishes its
            # write instead of aborting it (completion timers and other
            # long sleepers are cancelled below when the window lapses).
            _, pending = await asyncio.wait(workers, timeout=0.2)
            workers = list(pending)
        for t in workers:
            t.cancel()
        if loops or workers:
            await asyncio.gather(*loops, *workers,
                                 return_exceptions=True)
        self._tasks.clear()
        self._workers.clear()
        if not graceful:
            self._latest.clear()
            self._pods.clear()
            self._armed.clear()
            self._active.clear()

    async def _register_node(self) -> None:
        node = make_node(self.node_name, **self.node_template)
        node["metadata"].setdefault("annotations", {})[AGENT_ANN] = "true"
        if self.topology_coord:
            # Interconnect position (topology/mesh node_cell contract):
            # an explicit coordinate label beats the scheduler's
            # name-derived fallback.
            node["metadata"].setdefault("labels", {})[
                MESH_COORD_LABEL] = str(self.topology_coord)
        try:
            await self.store.create("nodes", node)
        except AlreadyExists:
            # Restart (or a pre-staged Node): the object survives us,
            # but the coordinate label must still land — the scheduler
            # reads it off the Node, not the agent.
            if self.topology_coord:
                coord = str(self.topology_coord)

                def stamp(existing):
                    labels = existing["metadata"].setdefault("labels", {})
                    if labels.get(MESH_COORD_LABEL) == coord:
                        return None
                    labels[MESH_COORD_LABEL] = coord
                    return existing
                try:
                    await self.store.guaranteed_update(
                        "nodes", self.node_name, stamp, return_copy=False)
                except StoreError:
                    logger.exception(
                        "agent %s: coord label stamp failed",
                        self.node_name)
        await self._publish_devices()

    async def _publish_devices(self) -> None:
        """Device-plugin registration (devicemanager ListAndWatch analog):
        extended resources publish as one ResourceSlice with NUMA-zoned
        device blocks — naming/zoning via api.types.template_devices, the
        convention shared with kwok nodes."""
        devices = template_devices(self.node_template.get("allocatable"),
                                   self.device_zones)
        if not devices:
            return
        try:
            await self.store.create(
                "resourceslices",
                make_resource_slice(self.node_name, self.device_driver,
                                    devices))
        except AlreadyExists:
            pass
        except StoreError:
            logger.exception("agent %s: device publish failed",
                             self.node_name)

    # -- watch loop (syncLoop's config source) -----------------------------

    async def _watch_loop(self, from_rv: int) -> None:
        """The kubelet's apiserver config source: a field-filtered watch.
        On disconnect, resume the watch from the last bookmark/event RV —
        the apiserver's watch cache backfills the gap from its ring — and
        fall back to the full relist ONLY on Expired (410: the server
        says the gap is gone). N agents reconnecting after a blip thus
        cost N ring backfills, not N store LISTs (reflector contract +
        bookmark-driven resync)."""
        rv = from_rv
        fields = {"spec.nodeName": self.node_name}
        while not self._stopped:
            try:
                watch = await self.store.watch(
                    "pods", resource_version=rv, fields=fields)
                async for ev in watch:
                    if ev.type == "BOOKMARK":
                        rv = ev.rv
                        continue
                    rv = max(rv, ev.rv)
                    key = namespaced_name(ev.object)
                    self._observe(
                        key, None if ev.type == "DELETED" else ev.object)
            except asyncio.CancelledError:
                raise
            except Expired:
                if self._stopped:
                    return
                new_rv = await self._relist(fields)
                if new_rv is not None:
                    rv = new_rv
            except StoreError:
                if self._stopped:
                    return
                # Transport error: the RV is (probably) still servable —
                # resume from it instead of amplifying into a LIST storm.
                # (A server that restarted with a reset RV counter makes
                # the resume Expired — the relist branch above — so this
                # cannot strand the agent on a stale RV.)
                await asyncio.sleep(0.5)
            except Exception:
                logger.exception("agent %s: watch loop error",
                                 self.node_name)
                await asyncio.sleep(0.5)

    async def _relist(self, fields: dict) -> int | None:
        """Full LIST + ledger reconcile (the 410/cold-start path).
        Returns the LIST's RV, or None if the LIST failed (after a
        backoff sleep) — callers retry or keep their RV."""
        try:
            lst = await self.store.list("pods", fields=fields)
        except Exception:
            await asyncio.sleep(0.5)
            return None
        seen = set()
        for p in lst.items:
            key = namespaced_name(p)
            seen.add(key)
            self._observe(key, p)
        # Pods that vanished while the watch was down.
        for key in self.ledger.reconcile(seen):
            self._observe(key, None)
        return lst.resource_version

    async def force_relist(self) -> None:
        """Cold-start reconnect, forced: tear down the watch, full LIST +
        reconcile, re-watch from the LIST's RV. The relist-storm
        scenario's per-agent unit (perf/scheduler_perf.py `relistStorm`
        gathers this across every agent at once) — with the watch cache
        active the LIST is a read of the shared snapshot, so the storm
        costs the store one table seed total, not one scan per agent."""
        if self._watch_task is not None:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except (asyncio.CancelledError, Exception):
                pass
            try:
                self._tasks.remove(self._watch_task)
            except ValueError:
                pass
            self._watch_task = None
        fields = {"spec.nodeName": self.node_name}
        # The relist must land before re-watching: watch-from-now with
        # no reconcile would never observe pods deleted while the watch
        # was down (the ledger would hold their devices forever).
        rv = None
        while rv is None and not self._stopped:
            rv = await self._relist(fields)
        if self._stopped:
            return
        self._watch_task = asyncio.ensure_future(self._watch_loop(rv))
        self._tasks.append(self._watch_task)

    # -- pod workers -------------------------------------------------------

    def _observe(self, key: str, obj: dict | None) -> None:
        self._latest[key] = obj
        if obj is None:
            self._pods.pop(key, None)
        else:
            self._pods[key] = obj
        if key in self._active or self._stopped:
            return
        self._active.add(key)
        t = asyncio.ensure_future(self._worker(key))
        self._workers.add(t)
        t.add_done_callback(self._workers.discard)

    async def _worker(self, key: str) -> None:
        """Serialized per-pod worker: processes the LATEST observed state
        until none is pending, then exits (a new event respawns it)."""
        try:
            while key in self._latest:
                obj = self._latest.pop(key)
                try:
                    await self._sync_pod(key, obj)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("agent %s: sync %s failed",
                                     self.node_name, key)
        finally:
            self._active.discard(key)

    async def _sync_pod(self, key: str, pod: dict | None) -> None:
        if pod is None:
            released = self.ledger.release(key)
            if released:
                logger.debug("agent %s: released %s from %s",
                             self.node_name, released, key)
            return
        phase = (pod.get("status") or {}).get("phase")
        if phase in ("Succeeded", "Failed"):
            # Terminal: the kubelet reclaims devices at termination
            # (devicemanager podDevices cleanup), before deletion.
            self.ledger.release(key)
            return
        if phase != "Pending":
            if phase == "Running":
                # Restart recovery: a pod marked Running by a PREVIOUS
                # agent incarnation still owes its staged completion —
                # re-arm with the full delay (conservative; the original
                # start time did not survive the process).
                ann = (pod.get("metadata", {}).get("annotations")
                       or {}).get(COMPLETE_AFTER_ANN)
                if ann is not None and key not in self._armed:
                    self._arm_completion(key, ann)
            return
        if not await self._allocate_devices(key, pod):
            return  # claim not ready yet; the claim update re-syncs us
        await self._mark_running(key, pod)

    def _ip_seq_of(self, pod: dict) -> int:
        """Inverse of _mark_running's podIP formula for OUR base prefix;
        0 for foreign/absent IPs."""
        ip = (pod.get("status") or {}).get("podIP") or ""
        parts = ip.split(".")
        if len(parts) != 4 or parts[0] != "10" \
                or parts[1] != str(self._ip_base):
            return 0
        try:
            hi, lo = int(parts[2]), int(parts[3])
        except ValueError:
            return 0
        return hi * 254 + (lo - 1)

    async def _allocate_devices(self, key: str, pod: dict) -> bool:
        """Kubelet-side DRA Allocate: record the scheduler's persisted
        per-claim device allocation in the local ledger."""
        ns = namespace_of(pod) or "default"
        for ref in (pod.get("spec") or {}).get("resourceClaims") or []:
            claim_name = ref.get("resourceClaimName")
            if not claim_name:
                continue
            try:
                claim = await self.store.get(
                    "resourceclaims", f"{ns}/{claim_name}")
            except NotFound:
                logger.warning("agent %s: pod %s references missing claim "
                               "%s", self.node_name, key, claim_name)
                return False
            alloc = (claim.get("status") or {}).get("allocation") or {}
            if alloc.get("nodeName") != self.node_name:
                # PreBind persists the allocation before binding, so this
                # is transient at worst; the pod re-syncs on claim update.
                return False
            devices = list(alloc.get("devices") or [])
            cname = ref.get("name") or claim_name
            try:
                self.ledger.allocate(key, cname, devices)
            except ValueError:
                # Device clash = OUR ledger is stale (a departed pod's
                # checkpoint entry survived): reconcile against the live
                # bound-pod set and retry once; a second clash is a real
                # double-allocation and the pod must stay Pending,
                # VISIBLY, until the conflicting claim resolves.
                try:
                    lst = await self.store.list(
                        "pods", fields={"spec.nodeName": self.node_name})
                except StoreError:
                    return False
                gone = self.ledger.reconcile(
                    {namespaced_name(p) for p in lst.items})
                try:
                    self.ledger.allocate(key, cname, devices)
                except ValueError:
                    logger.warning(
                        "agent %s: pod %s claim %s devices %s still "
                        "clash after reconcile (%d stale entries "
                        "dropped); leaving Pending until the claim "
                        "resolves", self.node_name, key, cname, devices,
                        len(gone))
                    return False
        return True

    async def _mark_running(self, key: str, pod: dict) -> None:
        from kubernetes_tpu.utils.tracing import (
            DEFAULT_TRACER,
            traceparent_of,
        )
        if DEFAULT_TRACER.enabled:
            # The kubelet-side Running transition joins the pod's create
            # trace via the stamped traceparent — the last hop of the
            # create → schedule → bind → run journey.
            with DEFAULT_TRACER.span("agent.mark_running", pod=key,
                                     node=self.node_name,
                                     traceparent=traceparent_of(pod)):
                return await self._mark_running_inner(key, pod)
        return await self._mark_running_inner(key, pod)

    async def _mark_running_inner(self, key: str, pod: dict) -> None:
        complete_after = [None]

        def mutate(obj):
            if (obj.get("status") or {}).get("phase") != "Pending":
                return None
            self._ip_seq += 1
            hi, lo = divmod(self._ip_seq, 254)
            status = obj.setdefault("status", {})
            status["phase"] = "Running"
            status.setdefault(
                "podIP",
                f"10.{self._ip_base}.{hi % 256}.{lo + 1}")
            conds = status.setdefault("conditions", [])
            if not any(c.get("type") == "Ready" for c in conds):
                conds.append({"type": "Ready", "status": "True"})
            complete_after[0] = (obj["metadata"].get("annotations")
                                 or {}).get(COMPLETE_AFTER_ANN)
            return obj

        # Fast path: the watch just handed us the pod at its current RV,
        # so mutate a selective copy of THAT and CAS once on its RV — one
        # write instead of guaranteed_update's GET+PUT. After Bind nobody
        # else writes the pod, so the CAS nearly always lands; a Conflict
        # (racing controller, stale delivery) falls back to the full RMW
        # loop. Copies only the containers touched (binding_subresource's
        # selective-copy discipline — delivered objects are shared/frozen;
        # spec + tolerations are included because update-time admission
        # defaulting calls setdefault on them).
        spec = dict(pod.get("spec") or {})
        spec["tolerations"] = list(spec.get("tolerations") or [])
        fast = {**pod, "metadata": dict(pod["metadata"]), "spec": spec,
                "status": dict(pod.get("status") or {})}
        fast["status"]["conditions"] = [
            dict(c) for c in fast["status"].get("conditions") or []]
        try:
            if mutate(fast) is not None:
                await self.store.update("pods", fast, _owned=True,
                                        return_copy=False)
        except Conflict:
            complete_after[0] = None
            try:
                await self.store.guaranteed_update(
                    "pods", key, mutate, return_copy=False)
            except StoreError:
                return
        except StoreError:
            return
        if complete_after[0] is not None:
            self._arm_completion(key, complete_after[0])

    def _arm_completion(self, key: str, spec: str) -> None:
        try:
            delay = float(spec)
        except ValueError:
            return
        self._armed.add(key)
        t = asyncio.ensure_future(self._complete_later(key, delay))
        self._workers.add(t)
        t.add_done_callback(self._workers.discard)

    async def _complete_later(self, key: str, delay: float) -> None:
        try:
            await asyncio.sleep(delay)
        finally:
            self._armed.discard(key)

        def mutate(pod):
            if (pod.get("status") or {}).get("phase") != "Running":
                return None
            pod["status"]["phase"] = "Succeeded"
            return pod
        try:
            await self.store.guaranteed_update(
                "pods", key, mutate, return_copy=False)
        except StoreError:
            pass

    # -- heartbeats --------------------------------------------------------

    async def _lease_loop(self) -> None:
        """Heartbeats as exact-key latest-wins writes: the agent is its
        Lease's only writer, so after the first fetch seeds the local
        copy, each renewal is ONE blind update (no RV precondition, no
        read-modify-write GET) — at 1,000 agents this halves the ~200
        heartbeat ops/s riding the control plane. Any surprise (deleted
        lease, transport error) just drops the local copy and re-seeds."""
        key = f"kube-node-lease/{self.node_name}"
        lease: dict | None = None
        # Jittered first tick (client-go wait.Jitter on heartbeats): a
        # fleet cold start must not race its own boot — N first-lease
        # creates landing inside the registration/watch-establishment
        # window were ~half the boot-phase write load (the r12 50k-agent
        # headroom note). Deterministic per node name, so boots replay.
        import zlib
        await asyncio.sleep(
            min(self.lease_period, 2.0)
            * (zlib.crc32(self.node_name.encode()) % 1000) / 1000.0)
        while not self._stopped:
            try:
                if lease is None:
                    lease = await self._fetch_or_create_lease(key)
                if lease is not None:
                    self._renew(lease)
                    lease["metadata"].pop("resourceVersion", None)
                    lease = await self.store.update("leases", lease)
            except asyncio.CancelledError:
                raise
            except NotFound:
                lease = None  # deleted under us: re-seed next tick
            except Exception:
                logger.exception("agent %s: lease renew failed",
                                 self.node_name)
                lease = None
            await asyncio.sleep(self.lease_period)

    async def _fetch_or_create_lease(self, key: str) -> dict | None:
        try:
            return await self.store.get("leases", key)
        except NotFound:
            pass
        try:
            return await self.store.create(
                "leases", new_object("Lease", self.node_name,
                                     "kube-node-lease",
                                     spec={"renewTime": 0}))
        except AlreadyExists:
            return await self.store.get("leases", key)
        except StoreError:
            return None

    @staticmethod
    def _renew(lease: dict) -> dict:
        lease.setdefault("spec", {})
        lease["spec"]["renewTime"] = lease["spec"].get("renewTime", 0) + 1
        return lease
