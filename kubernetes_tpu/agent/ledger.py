"""Kubelet-local device allocation ledger + checkpoint.

Parity target: pkg/kubelet/cm/devicemanager/manager.go (`Allocate`,
`podDevices`, `writeCheckpoint`) + pkg/kubelet/checkpointmanager/
(SURVEY §2.5 resource managers, §5.4 checkpoint/resume): the node agent
records which devices each pod holds and persists the record locally so
a restarted agent never double-allocates devices that survived it.

TPU-first divergence: devices here are DRA ResourceSlice entries (the
only device model this framework ships); the extended-resource counting
path needs no per-device identity, so only claims reach the ledger.

The checkpoint is one JSON document written atomically (tmp + fsync +
rename — the checkpointmanager's atomic-writer contract on one file).
"""

from __future__ import annotations

import json
import logging
import os

logger = logging.getLogger(__name__)

_VERSION = 1


class DeviceLedger:
    """pod key -> claim name -> [device names] with file checkpointing."""

    def __init__(self, path: str, node_name: str):
        self.path = path
        self.node_name = node_name
        self._alloc: dict[str, dict[str, list[str]]] = {}

    # -- checkpoint --------------------------------------------------------

    def load(self) -> None:
        """Restore state from the checkpoint; a missing file is first
        boot, a corrupt one is discarded loudly (the reference rebuilds
        from the runtime in that case — we rebuild from the apiserver
        via reconcile())."""
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError):
            logger.exception(
                "device checkpoint %s unreadable; starting empty "
                "(reconcile() will rebuild from claim status)", self.path)
            return
        if doc.get("node") not in (None, self.node_name):
            logger.warning(
                "device checkpoint %s belongs to node %r, not %r; ignoring",
                self.path, doc.get("node"), self.node_name)
            return
        self._alloc = {
            pod: {c: list(devs) for c, devs in claims.items()}
            for pod, claims in (doc.get("allocations") or {}).items()}

    def _save(self) -> None:
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump({"version": _VERSION, "node": self.node_name,
                       "allocations": self._alloc}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- allocation --------------------------------------------------------

    def in_use(self) -> set[str]:
        return {d for claims in self._alloc.values()
                for devs in claims.values() for d in devs}

    def get(self, pod_key: str) -> dict[str, list[str]]:
        return {c: list(d) for c, d in self._alloc.get(pod_key, {}).items()}

    def allocate(self, pod_key: str, claim_name: str,
                 devices: list[str]) -> None:
        """Idempotent: re-syncing a pod re-records the same devices."""
        cur = self._alloc.setdefault(pod_key, {})
        if cur.get(claim_name) == devices:
            return
        taken = self.in_use() - set(cur.get(claim_name) or [])
        clash = taken & set(devices)
        if clash:
            # Double-allocation would corrupt the node's device state —
            # refuse; the claim's scheduler-side allocation is the source
            # of truth and the conflict means OUR ledger is stale.
            raise ValueError(
                f"devices {sorted(clash)} already allocated on this node")
        cur[claim_name] = list(devices)
        self._save()

    def release(self, pod_key: str) -> list[str]:
        claims = self._alloc.pop(pod_key, None)
        if not claims:
            return []
        self._save()
        return sorted({d for devs in claims.values() for d in devs})

    def reconcile(self, live_pod_keys: set[str]) -> list[str]:
        """Drop allocations for pods that no longer exist on this node
        (restart recovery: the checkpoint may outlive its pods)."""
        gone = [k for k in self._alloc if k not in live_pod_keys]
        for k in gone:
            self._alloc.pop(k, None)
        if gone:
            self._save()
        return gone
