"""Hollow-kubelet node agent (SURVEY §2.5): per-node sync loop, pod
workers, device Allocate with a local checkpoint, heartbeats."""

from kubernetes_tpu.agent.agent import NodeAgent
from kubernetes_tpu.agent.ledger import DeviceLedger

__all__ = ["NodeAgent", "DeviceLedger"]
