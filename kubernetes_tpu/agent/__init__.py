"""Hollow-kubelet node agent (SURVEY §2.5): per-node sync loop, pod
workers, device Allocate with a local checkpoint, heartbeats, merged
config sources (config), and the read-only kubelet server (server)."""

from kubernetes_tpu.agent.agent import NodeAgent
from kubernetes_tpu.agent.config import ResolvedConfig, merge_config
from kubernetes_tpu.agent.ledger import DeviceLedger
from kubernetes_tpu.agent.server import AgentServer

__all__ = ["AgentServer", "DeviceLedger", "merge_config", "NodeAgent",
           "ResolvedConfig"]
