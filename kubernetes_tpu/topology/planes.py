"""Per-node interconnect coordinate planes (the tensorized topology).

`TopologyPlanes` is the topology sibling of the r14 class planes:
ClusterTensors grows a `.topology` attribute carrying, for every node
row of the padded node axis, its mesh cell index and (x, y, z)
coordinates — plus the inverse cell→node map the slice allocator
walks. Like the taint interning, the planes are STATIC per node-set:
they are rebuilt only when the mesh flags or the (name, spec_epoch)
node fingerprint move, and reused (shared arrays, `rebuilt=False`)
otherwise; `topology_plane_rebuilds_total` counts the real rebuilds.

Cell collisions (two nodes claiming one coordinate — a mislabeled
agent) resolve deterministically: the LOWEST node index keeps the
cell, later claimants go off-mesh. Off-mesh nodes (cell -1) schedule
normally as flat capacity but never host slice members.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

import numpy as np

from kubernetes_tpu.topology.mesh import MeshSpec, node_cell, parse_mesh_shape

if TYPE_CHECKING:  # import cycle: scheduler.types pulls in ops.tensorize
    from kubernetes_tpu.scheduler.types import NodeInfo


class TopologyPlanes:
    """Node-axis coordinate planes for one mesh spec + node set."""

    def __init__(self, spec: MeshSpec, nodes: "Sequence[NodeInfo]",
                 n_pad: int, fingerprint: tuple):
        self.spec = spec
        self.fingerprint = fingerprint
        self.rebuilt = True
        #: (n_pad,) int32 — row-major mesh cell per node row, -1 off-mesh
        #: (padding rows included).
        self.cell_of_node = np.full((n_pad,), -1, dtype=np.int32)
        #: (cells,) int32 — node row per mesh cell, -1 = hole (no node).
        self.node_of_cell = np.full((spec.cells,), -1, dtype=np.int32)
        #: (n_pad, 3) int32 — (x, y, z) per node row, -1 off-mesh.
        self.coords = np.full((n_pad, 3), -1, dtype=np.int32)
        for i, ni in enumerate(nodes):
            cell = node_cell(ni.name, ni.labels, spec)
            if cell is None or self.node_of_cell[cell] >= 0:
                continue  # off-mesh, or a later claimant of a taken cell
            self.cell_of_node[i] = cell
            self.node_of_cell[cell] = i
            self.coords[i] = spec.coord_of(cell)
        #: nodes actually on the mesh (drives the holes-are-never-free rule).
        self.on_mesh = int(np.count_nonzero(self.cell_of_node >= 0))

    def free_cells(self, node_free: np.ndarray) -> np.ndarray:
        """(cells,) bool free mask from a node-axis free mask: a cell is
        free iff a node occupies it AND that node is free. Holes and
        off-mesh nodes are never free (they can't host slice members)."""
        has_node = self.node_of_cell >= 0
        idx = np.where(has_node, self.node_of_cell, 0)
        return has_node & np.asarray(node_free, dtype=np.bool_)[idx]


def build_topology_planes(nodes: "Sequence[NodeInfo]", n_pad: int,
                          prev: TopologyPlanes | None) -> TopologyPlanes:
    """Build (or reuse) the planes for the current mesh flags + node
    set. Reuse keys on (raw flag values, (name, spec_epoch) per node):
    label moves bump spec_epoch, so a re-stamped coordinate rebuilds."""
    from kubernetes_tpu.utils import flags

    raw_shape = flags.get("KTPU_MESH_SHAPE")
    fingerprint = (raw_shape, n_pad,
                   tuple((ni.name, ni.spec_epoch) for ni in nodes))
    if prev is not None and prev.fingerprint == fingerprint:
        prev.rebuilt = False
        return prev
    spec = parse_mesh_shape(raw_shape, len(nodes))
    return TopologyPlanes(spec, nodes, n_pad, fingerprint)
