"""Device-side slice alignment Filter/Score (the oracle's jax twin).

Where the host oracle (topology/slices.py) loops per placement, the
kernel evaluates EVERY (orientation, anchor) placement of the whole
mesh at once with separable shifted reductions:

- feasibility: a box of shape (s0,s1,s2) anchored at `a` is free iff
  the per-axis window-ANDs of the free grid hold at `a` — s0+s1+s2
  shifts instead of prod(shape) gathers, wraparound via jnp.roll on a
  torus and zero-filled shifts on a walled mesh (a window crossing a
  wall reads False, which is exactly "infeasible anchor");
- fragmentation: the exposed-free-boundary count is a sum over the 6
  box faces, each face a window-sum of the free grid over the two
  orthogonal axes shifted one past the box along the third — the same
  halo cells the oracle walks, as three reused 2-axis prefix products;
- selection: score and the lowest-id tie rule pack into ONE int32 key,
  `(FRAG_CAP - frag) * A + (A-1 - pid)` for feasible placements and
  -1 otherwise, so the winner is a plain max — and the sharded
  variant is a shard-local max + `lax.pmax` over the placement axis,
  associative and therefore bit-identical at any shard count (the
  solver's cross-shard argmax contract, SURVEY §5.8).

Bit-identity with the oracle on (feasible, frag·feasible) and on the
selected placement is the differential contract
(tests/test_topology_slices.py); frag is reported 0 where infeasible
on both sides.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_tpu.parallel.mesh import SLICE_AXIS
from kubernetes_tpu.topology.mesh import MeshSpec, orientations

try:  # jax>=0.8 top-level; fall back for older versions
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

import inspect as _inspect

_params = _inspect.signature(shard_map).parameters
_SHARD_MAP_KW = {"check_vma": False} if "check_vma" in _params else (
    {"check_rep": False} if "check_rep" in _params else {})

#: compiled scan per (dims, wrap, orientations) signature.
_SCAN_CACHE: dict = {}
#: compiled sharded max per shard count.
_SHARDED_MAX_CACHE: dict = {}


def frag_cap(shape: Sequence[int]) -> int:
    """Exclusive upper bound on any placement's frag score (the box
    surface): the key packing needs it static."""
    s = tuple(shape) + (1,) * (3 - len(tuple(shape)))
    return 2 * (s[0] * s[1] + s[1] * s[2] + s[0] * s[2]) + 1


def _shift(g, k: int, axis: int, wrap: bool):
    """out[c] = g[c + k·e_axis]; torus wraps, mesh fills with zero
    (False) so windows crossing a wall read infeasible/absent."""
    if k == 0:
        return g
    r = jnp.roll(g, -k, axis=axis)
    if wrap:
        return r
    d = g.shape[axis]
    idx = jnp.arange(d)
    ok = (idx + k >= 0) & (idx + k < d)
    shape = [1, 1, 1]
    shape[axis] = d
    return jnp.where(ok.reshape(shape), r, jnp.zeros((), r.dtype))


def _win_and(g, s: int, axis: int, wrap: bool):
    acc = g
    for i in range(1, s):
        acc = acc & _shift(g, i, axis, wrap)
    return acc


def _win_sum(g, s: int, axis: int, wrap: bool):
    acc = g
    for i in range(1, s):
        acc = acc + _shift(g, i, axis, wrap)
    return acc


def _win_or_back(g, s: int, axis: int, wrap: bool):
    """OR over backward shifts: out[c] = OR_{i<s} g[c - i·e_axis]
    (the box dilation the coverage union needs)."""
    acc = g
    for i in range(1, s):
        acc = acc | _shift(g, -i, axis, wrap)
    return acc


def _build_scan(dims: tuple[int, int, int], wrap: bool,
                orients: tuple[tuple[int, int, int], ...], cap: int):
    cells = dims[0] * dims[1] * dims[2]
    A = len(orients) * cells

    def scan(free):
        """free: (d0,d1,d2) bool → (key (A,), covered (cells,) bool)."""
        free_i = free.astype(jnp.int32)
        keys = []
        covered = jnp.zeros(dims, dtype=jnp.bool_)
        for oi, (s0, s1, s2) in enumerate(orients):
            feas = _win_and(_win_and(_win_and(
                free, s0, 0, wrap), s1, 1, wrap), s2, 2, wrap)
            frag = jnp.zeros(dims, dtype=jnp.int32)
            # +x/-x faces: window-sum over (y,z), shifted past the box.
            ws_yz = _win_sum(_win_sum(free_i, s1, 1, wrap), s2, 2, wrap)
            if not (wrap and s0 == dims[0]):
                frag = frag + _shift(ws_yz, s0, 0, wrap) \
                    + _shift(ws_yz, -1, 0, wrap)
            ws_xz = _win_sum(_win_sum(free_i, s0, 0, wrap), s2, 2, wrap)
            if not (wrap and s1 == dims[1]):
                frag = frag + _shift(ws_xz, s1, 1, wrap) \
                    + _shift(ws_xz, -1, 1, wrap)
            ws_xy = _win_sum(_win_sum(free_i, s0, 0, wrap), s1, 1, wrap)
            if not (wrap and s2 == dims[2]):
                frag = frag + _shift(ws_xy, s2, 2, wrap) \
                    + _shift(ws_xy, -1, 2, wrap)
            pid = oi * cells + jnp.arange(cells, dtype=jnp.int32) \
                .reshape(dims)
            key = jnp.where(feas, (cap - frag) * A + (A - 1 - pid),
                            jnp.int32(-1))
            keys.append(key.reshape(-1))
            covered = covered | _win_or_back(_win_or_back(_win_or_back(
                feas, s0, 0, wrap), s1, 1, wrap), s2, 2, wrap)
        return jnp.concatenate(keys), covered.reshape(-1)

    return jax.jit(scan)


def device_scan(free_cells: np.ndarray, spec: MeshSpec,
                shape: Sequence[int]):
    """Run the kernel over one free mask. Returns
    (key (A,) int32, feas (A,) bool, frag (A,) int32, covered (cells,))
    as host arrays — None when the shape has no valid orientation or
    the int32 key packing would overflow (caller falls back to the
    host oracle; meshes that large are outside the device contract)."""
    orients = orientations(shape, spec)
    if not orients:
        return None
    cap = frag_cap(shape)
    A = len(orients) * spec.cells
    if cap * (A + 1) >= 2**31:
        return None
    sig = (spec.dims, spec.wrap, orients, cap)
    fn = _SCAN_CACHE.get(sig)
    if fn is None:
        fn = _SCAN_CACHE[sig] = _build_scan(
            spec.dims, spec.wrap, orients, cap)
    grid = jnp.asarray(
        np.asarray(free_cells, dtype=np.bool_).reshape(spec.dims))
    key_dev, covered_dev = fn(grid)
    key = np.asarray(key_dev)
    covered = np.asarray(covered_dev)
    feas = key >= 0
    frag = np.where(feas, cap - np.where(feas, key, 0) // A, 0) \
        .astype(np.int32)
    return key, feas, frag, covered


def decode_key(best_key: int, spec: MeshSpec,
               shape: Sequence[int]) -> tuple[int, int]:
    """Packed winner key → (placement id, frag); (-1, 0) = infeasible."""
    if best_key < 0:
        return -1, 0
    orients = orientations(shape, spec)
    A = len(orients) * spec.cells
    return A - 1 - int(best_key) % A, frag_cap(shape) - int(best_key) // A


def best_key(key: np.ndarray, shards: int | None = None) -> int:
    """Winner selection over the packed keys — shard-local max +
    cross-shard pmax when `shards` > 1 (parity-tested at {1,4,8})."""
    if len(key) == 0:
        return -1
    S = int(shards or 1)
    if S <= 1:
        return int(np.max(key))
    if S > len(jax.devices()):
        raise ValueError(
            f"requested {S} shards, have {len(jax.devices())} devices")
    pad = (-len(key)) % S
    padded = np.pad(key, (0, pad), constant_values=-1)
    fn = _SHARDED_MAX_CACHE.get(S)
    if fn is None:
        mesh = Mesh(np.array(jax.devices()[:S]), (SLICE_AXIS,))

        def local_max(block):
            return lax.pmax(jnp.max(block), SLICE_AXIS)

        fn = _SHARDED_MAX_CACHE[S] = jax.jit(shard_map(
            local_max, mesh=mesh, in_specs=P(SLICE_AXIS), out_specs=P(),
            **_SHARD_MAP_KW))
    return int(fn(jnp.asarray(padded)))


def fragmentation_pct(free_cells: np.ndarray,
                      covered: np.ndarray) -> float:
    """Stranded-for-this-shape free capacity: the percentage of free
    cells no feasible placement covers (100 = every free cell is
    stranded; 0 = all free capacity still coalesces into slices)."""
    total = int(np.count_nonzero(free_cells))
    if total == 0:
        return 0.0
    return 100.0 * (1.0 - int(np.count_nonzero(covered)) / total)
