"""Interconnect coordinate model: mesh shape + node→coordinate mapping.

A cluster's interconnect is a rectangular 2D/3D mesh or torus
(`KTPU_MESH_SHAPE`). Internally every shape is padded to 3D with
trailing size-1 axes so the oracle and the device kernel share one
code path; a 2D `4x8` torus is the (4, 8, 1) mesh with wraparound.

Node→coordinate contract (agent and scheduler must agree):

1. the `ktpu.io/topology-coord` label ("x,y" / "x,y,z") a NodeAgent
   stamps at registration wins;
2. otherwise the trailing integer in the node name is taken as the
   row-major cell index (kwok `node-17` staging works untouched);
3. a node with neither, or whose coordinate falls outside the mesh,
   is OFF-MESH: it schedules normally as flat capacity but can never
   host a slice member.

Orientations of a requested shape are the distinct axis permutations
of its padded 3-tuple (the rotations/reflections of an axis-aligned
box on a grid), lexicographically ordered — the enumeration order is
part of the placement-id contract shared by `slices` (oracle) and
`device` (kernel); only orientations that fit the mesh per-axis
(s <= d on every axis) are kept, on a torus a window equal to the
ring uses the whole ring exactly once.
"""

from __future__ import annotations

import itertools
import math
import re
from dataclasses import dataclass
from typing import Mapping, Sequence

#: node label carrying "x,y" / "x,y,z" interconnect coordinates
#: (NodeAgent stamps it at registration; see agent/agent.py).
MESH_COORD_LABEL = "ktpu.io/topology-coord"

_NAME_INDEX_RE = re.compile(r"(\d+)$")


@dataclass(frozen=True)
class MeshSpec:
    """One interconnect mesh: padded 3D dims + wraparound flag."""

    dims: tuple[int, int, int]
    wrap: bool = True

    @property
    def cells(self) -> int:
        d0, d1, d2 = self.dims
        return d0 * d1 * d2

    def coord_of(self, index: int) -> tuple[int, int, int]:
        """Row-major cell index → (x, y, z)."""
        _, d1, d2 = self.dims
        return (index // (d1 * d2), (index // d2) % d1, index % d2)

    def index_of(self, coord: Sequence[int]) -> int:
        _, d1, d2 = self.dims
        return (coord[0] * d1 + coord[1]) * d2 + coord[2]

    def contains(self, coord: Sequence[int]) -> bool:
        return all(0 <= c < d for c, d in zip(coord, self.dims))


def parse_mesh_shape(raw: str | None, n_nodes: int) -> MeshSpec:
    """KTPU_MESH_SHAPE → MeshSpec. `auto` (and any malformed value —
    a typo'd shape must not crash a control plane, the flags-registry
    posture) derives a near-square 2D torus covering `n_nodes`; cells
    beyond the node count are holes, never free."""
    wrap = True
    text = (raw or "auto").strip().lower()
    if text.endswith(":mesh"):
        wrap = False
        text = text[: -len(":mesh")]
    if text and text != "auto":
        try:
            dims = tuple(int(p) for p in text.split("x"))
        except ValueError:
            dims = ()
        if dims and 1 <= len(dims) <= 3 and all(d >= 1 for d in dims):
            padded = dims + (1,) * (3 - len(dims))
            return MeshSpec(dims=padded, wrap=wrap)
    d0 = max(1, math.isqrt(max(1, n_nodes - 1)) + 1)  # ceil(sqrt(n))
    d1 = max(1, -(-max(1, n_nodes) // d0))
    return MeshSpec(dims=(d0, d1, 1), wrap=True)


def parse_coord_label(value: str) -> tuple[int, int, int] | None:
    """"x,y" / "x,y,z" → padded 3-tuple (None on malformed input)."""
    try:
        parts = tuple(int(p) for p in value.split(","))
    except (ValueError, AttributeError):
        return None
    if not 1 <= len(parts) <= 3:
        return None
    return parts + (0,) * (3 - len(parts))


def node_cell(name: str, labels: Mapping[str, str] | None,
              spec: MeshSpec) -> int | None:
    """Flat cell index of one node (None = off-mesh). Label wins;
    trailing name integer is the row-major fallback."""
    coord = None
    if labels:
        value = labels.get(MESH_COORD_LABEL)
        if value is not None:
            coord = parse_coord_label(value)
            if coord is None or not spec.contains(coord):
                return None  # explicit but bad coordinate: off-mesh
            return spec.index_of(coord)
    m = _NAME_INDEX_RE.search(name or "")
    if m is None:
        return None
    index = int(m.group(1))
    return index if index < spec.cells else None


def normalize_shape(shape: Sequence[int]) -> tuple[int, int, int]:
    """Requested sliceShape → padded 3-tuple (dims >= 1 enforced)."""
    dims = tuple(int(s) for s in shape)[:3]
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"bad slice shape {shape!r}")
    return dims + (1,) * (3 - len(dims))


def orientations(shape: Sequence[int],
                 spec: MeshSpec) -> tuple[tuple[int, int, int], ...]:
    """Distinct valid axis permutations of the padded shape, lex order
    (the placement-id enumeration contract — see module docstring)."""
    padded = normalize_shape(shape)
    seen = sorted(set(itertools.permutations(padded)))
    return tuple(o for o in seen
                 if all(s <= d for s, d in zip(o, spec.dims)))
