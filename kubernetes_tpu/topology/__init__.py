"""Topology-aware TPU-slice placement (ROADMAP #5, SURVEY §2.5).

The scheduler's nodes stop being flat capacity vectors: every node
carries interconnect coordinates — its position in a configurable
2D/3D mesh or torus — and multi-host gangs request *shapes*, not
counts. The subsystem splits host/device the same way the solver does:

- `mesh`    — the coordinate model: KTPU_MESH_SHAPE parsing, the
  node→coordinate mapping (label first, name-derived fallback), and
  the orientation enumeration shared by oracle and kernel.
- `slices`  — the HOST ORACLE: naive per-placement feasibility +
  fragmentation scoring, the semantic reference the device kernel is
  differential-tested against (tests/test_topology_slices.py).
- `device`  — the jax twin: separable shifted-AND feasibility and
  face-sum fragmentation over the whole anchor grid at once,
  bit-identical to the oracle, with the sharded argmax reduction.
- `planes`  — per-node coordinate planes tensorized alongside the r14
  class planes (ops/tensorize.ClusterTensors.topology), rebuilt only
  when the node set / mesh spec moves.

Everything rides `KTPU_TOPOLOGY` (kill switch): off restores the exact
flat-capacity call graph.
"""

from kubernetes_tpu.topology.mesh import (
    MESH_COORD_LABEL,
    MeshSpec,
    node_cell,
    orientations,
    parse_mesh_shape,
)
from kubernetes_tpu.topology.planes import TopologyPlanes
from kubernetes_tpu.topology.slices import (
    best_placement,
    is_contiguous_slice,
    oracle_scan,
    placement_members,
)

__all__ = [
    "MESH_COORD_LABEL", "MeshSpec", "node_cell", "orientations",
    "parse_mesh_shape", "TopologyPlanes", "best_placement",
    "is_contiguous_slice", "oracle_scan", "placement_members",
]
