"""Host oracle for contiguous sub-mesh (slice) placement.

Naive, loop-per-placement reference semantics — deliberately written
in a different style from the vectorized device kernel
(topology/device.py) so the randomized differential suite compares two
independent derivations of the same contract:

Placement enumeration (the id contract, shared with the kernel):
  id = orientation_index * spec.cells + row-major anchor index,
with orientations from `mesh.orientations` (lex-ordered valid axis
permutations). On a torus every cell anchors every orientation; on a
non-wrap mesh an anchor whose box crosses a wall is infeasible.

Feasibility: every member cell of the anchored box is free.

Fragmentation score (lower = better): the count of (free outside
cell, direction) adjacency pairs pointing into the box — the free
boundary the placement would expose. Packing a slice snugly against
occupied cells / mesh walls minimizes it, which preserves large
contiguous free regions for future slices (the bin-packing contact
heuristic lifted to sub-meshes). Axes the box spans entirely on a
torus have no outside neighbor and contribute nothing. Infeasible
placements carry frag = 0 by convention (both implementations mask,
so the differential compare is exact).

Ties break to the LOWEST placement id — the same
first-feasible-wins determinism as the solver's node-index tie rule.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from kubernetes_tpu.topology.mesh import MeshSpec, orientations

#: (axis, direction) pairs of the 6-neighborhood, enumeration order
#: fixed (it is summed, so order only matters for readability).
_FACES = tuple((axis, sign) for axis in range(3) for sign in (+1, -1))


def _anchor_ok(anchor: tuple[int, int, int], orient: tuple[int, int, int],
               spec: MeshSpec) -> bool:
    if spec.wrap:
        return True
    return all(a + s <= d
               for a, s, d in zip(anchor, orient, spec.dims))


def _member_cells(anchor: tuple[int, int, int],
                  orient: tuple[int, int, int],
                  spec: MeshSpec) -> list[int]:
    d0, d1, d2 = spec.dims
    out = []
    for i in range(orient[0]):
        for j in range(orient[1]):
            for k in range(orient[2]):
                out.append(spec.index_of((
                    (anchor[0] + i) % d0,
                    (anchor[1] + j) % d1,
                    (anchor[2] + k) % d2)))
    return out


def _frag_of(anchor: tuple[int, int, int], orient: tuple[int, int, int],
             spec: MeshSpec, free: np.ndarray) -> int:
    """Exposed-free-boundary count of one feasible placement (see
    module docstring); walls (non-wrap out-of-range halo cells) and
    holes/occupied cells contribute nothing."""
    frag = 0
    for axis, sign in _FACES:
        s, d = orient[axis], spec.dims[axis]
        if spec.wrap and s == d:
            continue  # box spans the ring: no outside cell on this axis
        off = [0, 0, 0]
        off[axis] = s if sign > 0 else -1
        spans = [range(orient[a]) if a != axis else (0,) for a in range(3)]
        for i in spans[0]:
            for j in spans[1]:
                for k in spans[2]:
                    c = [anchor[0] + i + off[0], anchor[1] + j + off[1],
                         anchor[2] + k + off[2]]
                    if spec.wrap:
                        c = [v % dd for v, dd in zip(c, spec.dims)]
                    elif not spec.contains(c):
                        continue
                    if free[spec.index_of(c)]:
                        frag += 1
    return frag


def oracle_scan(free: np.ndarray, spec: MeshSpec,
                shape: Sequence[int]) -> tuple[np.ndarray, np.ndarray]:
    """(feasible (A,), frag (A,) int32) over every placement id.
    `free` is a (spec.cells,) bool mask; frag is 0 where infeasible."""
    orients = orientations(shape, spec)
    cells = spec.cells
    feas = np.zeros((len(orients) * cells,), dtype=np.bool_)
    frag = np.zeros((len(orients) * cells,), dtype=np.int32)
    for oi, orient in enumerate(orients):
        for a in range(cells):
            anchor = spec.coord_of(a)
            if not _anchor_ok(anchor, orient, spec):
                continue
            members = _member_cells(anchor, orient, spec)
            if all(free[m] for m in members):
                pid = oi * cells + a
                feas[pid] = True
                frag[pid] = _frag_of(anchor, orient, spec, free)
    return feas, frag


def best_placement(feas: np.ndarray, frag: np.ndarray) -> int:
    """Lowest-id placement among the minimum-frag feasible ones
    (-1 when nothing is feasible)."""
    best, best_frag = -1, None
    for pid in range(len(feas)):
        if feas[pid] and (best_frag is None or frag[pid] < best_frag):
            best, best_frag = pid, int(frag[pid])
    return best


def placement_members(pid: int, spec: MeshSpec,
                      shape: Sequence[int]) -> list[int]:
    """Member cell indices of one placement id (sorted ascending —
    the member→coordinate assignment order the gang plan uses)."""
    orients = orientations(shape, spec)
    oi, a = divmod(pid, spec.cells)
    return sorted(_member_cells(spec.coord_of(a), orients[oi], spec))


def coverage(feas: np.ndarray, spec: MeshSpec,
             shape: Sequence[int]) -> np.ndarray:
    """(cells,) bool: cells belonging to >= 1 feasible placement. The
    complement over free cells is the stranded-for-this-shape capacity
    `scheduler_slice_fragmentation_pct` reports."""
    orients = orientations(shape, spec)
    covered = np.zeros((spec.cells,), dtype=np.bool_)
    for oi, orient in enumerate(orients):
        for a in range(spec.cells):
            if feas[oi * spec.cells + a]:
                covered[_member_cells(spec.coord_of(a), orient, spec)] = True
    return covered


def is_contiguous_slice(cells: Iterable[int], spec: MeshSpec,
                        shape: Sequence[int]) -> bool:
    """Do `cells` form EXACTLY one anchored box of `shape` (any valid
    orientation, torus wraparound included)? The Permit-time contract
    for slice-shaped gangs: every anchor candidate is a member cell
    (offset 0 is in every box), so the check is O(|cells|^2 · |O|)."""
    want = set(int(c) for c in cells)
    if not want or any(not 0 <= c < spec.cells for c in want):
        return False
    for orient in orientations(shape, spec):
        if orient[0] * orient[1] * orient[2] != len(want):
            continue
        for a in want:
            anchor = spec.coord_of(a)
            if not _anchor_ok(anchor, orient, spec):
                continue
            if set(_member_cells(anchor, orient, spec)) == want:
                return True
    return False
