"""Pass 3 — the KTPU_* flag registry is the ONLY read path.

`utils/flags.py` is the single source of truth for environment flags:
name, default, parser, doc line, kill-switch bool. This pass keeps the
contract honest:

- FL301 unrouted read: `os.environ.get("KTPU_…")`, `os.environ[…]` or
  `os.getenv(…)` anywhere in the package or bench.py outside
  utils/flags.py itself. WRITES stay legal — the bench and PerfRunner
  export overrides for child code to read through the registry (and
  `flags.scoped_set` is the save/restore idiom) — only reads bypass
  the contract.
- FL302 unknown flag: a `KTPU_*` string referenced in the tree that the
  registry doesn't know. Catches typos before they become silent
  no-op kill switches.
- FL303 undocumented flag: a registry entry with an empty doc line.
- FL304 untested flag: a registry flag named nowhere under tests/ —
  every knob needs at least one test that mentions it (the flags
  round-trip test names them all explicitly, so adding a flag without
  touching tests fails here).
- FL305 README drift: the README's generated flag table no longer
  matches `flags.render_markdown_table()` (regenerate with
  `python -m kubernetes_tpu.analysis --write-readme-flags`).

Tests are exempt from FL301: they monkeypatch env wholesale, and
conftest must read `KTPU_TEST_PLATFORM` before jax (or anything that
imports it) loads.
"""

from __future__ import annotations

import ast
import os
import re

from kubernetes_tpu.analysis.engine import Finding, Module, dotted
from kubernetes_tpu.utils import flags as flags_registry

PASS_ID = "flag-registry"

#: the one module allowed to read KTPU_* env directly.
ALLOWED_READERS = ("kubernetes_tpu/utils/flags.py",)

README_BEGIN = "<!-- ktpu-flags:begin (generated: python -m kubernetes_tpu.analysis --write-readme-flags) -->"
README_END = "<!-- ktpu-flags:end -->"


def _env_reads(mod: Module):
    """(flag name, line) for every KTPU_* environ READ in the module."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            n = dotted(node.func)
            if n and (n.endswith("environ.get") or n.endswith(".getenv")
                      or n == "getenv" or n.endswith("environ.setdefault")):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and node.args[0].value.startswith("KTPU_"):
                    yield node.args[0].value, node.lineno
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            n = dotted(node.value)
            if n and n.endswith("environ") \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and node.slice.value.startswith("KTPU_"):
                yield node.slice.value, node.lineno


def _referenced_flags(mod: Module) -> set[str]:
    """Every KTPU_* identifier in string literals (typo guard input)."""
    return set(re.findall(r"\bKTPU_[A-Z0-9_]+\b", mod.source))


def run(modules: list[Module], root: str | None = None) -> list[Finding]:
    from kubernetes_tpu.analysis.engine import repo_root
    root = root or repo_root()
    findings: list[Finding] = []
    registry = flags_registry.FLAGS

    referenced: set[str] = set()
    for mod in modules:
        referenced |= _referenced_flags(mod)
        if mod.rel in ALLOWED_READERS:
            continue
        for name, line in _env_reads(mod):
            findings.append(Finding(
                pass_id=PASS_ID, code="FL301", path=mod.rel, line=line,
                symbol=name,
                message=f"environ read of {name} bypasses the flag "
                        "registry — use kubernetes_tpu.utils.flags.get"
                        f"({name!r})"))

    for name in sorted(referenced - set(registry)):
        # find one referencing module for the report location
        where = next((m for m in modules if name in m.source), None)
        line = 0
        if where is not None:
            for i, ln in enumerate(where.source.splitlines(), 1):
                if name in ln:
                    line = i
                    break
        findings.append(Finding(
            pass_id=PASS_ID, code="FL302",
            path=where.rel if where else "kubernetes_tpu/utils/flags.py",
            line=line, symbol=name,
            message=f"{name} is referenced but not registered in "
                    "utils/flags.py — register it (or fix the typo)"))

    # registry hygiene: docs + tests
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for fn in sorted(os.listdir(tests_dir)):
            if fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn),
                          encoding="utf-8") as f:
                    tests_text += f.read()
    for name, flag in registry.items():
        if not flag.doc.strip():
            findings.append(Finding(
                pass_id=PASS_ID, code="FL303",
                path="kubernetes_tpu/utils/flags.py", line=0,
                symbol=name,
                message=f"registry flag {name} has no doc line"))
        if tests_text and name not in tests_text:
            findings.append(Finding(
                pass_id=PASS_ID, code="FL304",
                path="kubernetes_tpu/utils/flags.py", line=0,
                symbol=name,
                message=f"registry flag {name} is exercised by no test "
                        "under tests/ — name it in at least one"))

    # README table sync
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            text = f.read()
        current = _readme_table(text)
        want = flags_registry.render_markdown_table()
        if current is None:
            findings.append(Finding(
                pass_id=PASS_ID, code="FL305", path="README.md", line=0,
                symbol="flag-table",
                message="README has no generated flag table (markers "
                        f"{README_BEGIN!r} … {README_END!r}); add one "
                        "with --write-readme-flags"))
        elif current.strip() != want.strip():
            findings.append(Finding(
                pass_id=PASS_ID, code="FL305", path="README.md", line=0,
                symbol="flag-table",
                message="README flag table drifted from the registry — "
                        "regenerate with python -m kubernetes_tpu."
                        "analysis --write-readme-flags"))
    return findings


def _readme_table(text: str) -> str | None:
    b = text.find(README_BEGIN)
    e = text.find(README_END)
    if b < 0 or e < 0 or e < b:
        return None
    return text[b + len(README_BEGIN):e]


def write_readme_table(root: str | None = None) -> bool:
    """Regenerate the README's flag table in place (returns True when
    the file changed)."""
    from kubernetes_tpu.analysis.engine import repo_root
    root = root or repo_root()
    readme = os.path.join(root, "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    b = text.find(README_BEGIN)
    e = text.find(README_END)
    if b < 0 or e < 0:
        return False
    new = (text[: b + len(README_BEGIN)] + "\n"
           + flags_registry.render_markdown_table() + "\n"
           + text[e:])
    if new != text:
        with open(readme, "w", encoding="utf-8") as f:
            f.write(new)
        return True
    return False
