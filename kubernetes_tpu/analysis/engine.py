"""Shared infrastructure for the ktpu-lint passes.

The engine owns everything pass-agnostic: discovering and parsing the
tree's modules, the `Finding` record and its stable suppression key,
the triaged baseline, and a handful of AST helpers (decorator / call
target resolution, import-alias tables, an intra-package call graph)
the passes share.

Design constraints:

- **zero dependencies**: stdlib `ast` only — the container bakes no
  linters, and the passes are repo-SPECIFIC (jit purity of the solve
  path, the KTPU_* flag registry) in a way generic tools can't be.
- **stable finding keys**: baseline entries must survive unrelated
  edits, so keys are `(pass, code, relpath, symbol)` — no line
  numbers. `symbol` is the enclosing function's qualname plus a short
  detail anchor (the flagged call or name), which moves with the code
  it describes.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

#: files never analyzed (generated descriptors, vendored bytes).
EXCLUDE_RELPATHS = frozenset((
    "kubernetes_tpu/apiserver/proto/ktpu_pb2.py",
))


def repo_root() -> str:
    """The repo checkout containing this package (…/kubernetes_tpu/..)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclass
class Finding:
    pass_id: str      # "jit-purity" | "lock-discipline" | "flag-registry" | "metrics-lint"
    code: str         # e.g. "JP101"
    path: str         # repo-relative, forward slashes
    line: int
    symbol: str       # enclosing qualname + detail anchor (key material)
    message: str

    @property
    def key(self) -> str:
        return f"{self.pass_id}:{self.code}:{self.path}:{self.symbol}"

    def as_dict(self) -> dict:
        return {"pass": self.pass_id, "code": self.code, "path": self.path,
                "line": self.line, "symbol": self.symbol,
                "message": self.message, "key": self.key}


@dataclass
class Module:
    path: str                 # absolute
    rel: str                  # repo-relative, forward slashes
    tree: ast.Module
    source: str
    #: import aliases visible anywhere in the module (module-level AND
    #: function-local imports): alias -> dotted module path. Covers
    #: `import x.y as z`, `from kubernetes_tpu.ops import kernels`.
    aliases: dict[str, str] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str, root: str) -> "Module":
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        mod = cls(path=path, rel=rel, tree=ast.parse(src, filename=path),
                  source=src)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    # `from pkg.sub import name` — name may be a module
                    # (the call-graph resolver checks) or an object.
                    mod.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        return mod


def load_modules(root: str | None = None,
                 extra: tuple[str, ...] = ("bench.py",)) -> list[Module]:
    """Every analyzable module: kubernetes_tpu/**/*.py plus `extra`
    top-level files. Tests are deliberately NOT loaded — they monkeypatch
    env and exercise kill switches in ways the hygiene rules exempt."""
    root = root or repo_root()
    out: list[Module] = []
    pkg = os.path.join(root, "kubernetes_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel in EXCLUDE_RELPATHS:
                continue
            out.append(Module.load(path, root))
    for fn in extra:
        path = os.path.join(root, fn)
        if os.path.exists(path):
            out.append(Module.load(path, root))
    return out


# -- AST helpers -------------------------------------------------------------

def dotted(node: ast.expr) -> str | None:
    """`a.b.c` attribute/name chain as a string, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's target (None for computed targets)."""
    return dotted(node.func)


def decorator_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    """Dotted names of a function's decorators; `partial(jax.jit, ...)`
    and `jax.jit(...)` call-form decorators contribute BOTH the outer
    name and the inner callable's name (so `@partial(jax.jit, ...)`
    yields ["partial", "jax.jit"])."""
    names: list[str] = []
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            n = call_name(dec)
            if n:
                names.append(n)
            for arg in dec.args:
                a = dotted(arg)
                if a:
                    names.append(a)
        else:
            n = dotted(dec)
            if n:
                names.append(n)
    return names


class FunctionIndex:
    """Per-module table of every function/method (nested included),
    keyed by qualname, with parent links — the call-graph substrate."""

    def __init__(self, module: Module):
        self.module = module
        #: qualname -> FunctionDef/AsyncFunctionDef
        self.functions: dict[str, ast.AST] = {}
        #: id(node) -> qualname
        self.qualname_of: dict[int, str] = {}
        #: last-segment name -> [qualnames] (bare-name call resolution)
        self.by_name: dict[str, list[str]] = {}
        self._walk(module.tree, ())

    def _walk(self, node: ast.AST, scope: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = ".".join(scope + (child.name,))
                self.functions[qn] = child
                self.qualname_of[id(child)] = qn
                self.by_name.setdefault(child.name, []).append(qn)
                self._walk(child, scope + (child.name,))
            elif isinstance(child, ast.ClassDef):
                self._walk(child, scope + (child.name,))
            else:
                self._walk(child, scope)


def own_statements(fn: ast.AST):
    """Walk a function's body EXCLUDING nested function/class bodies —
    nested defs are separate graph nodes (and separately reachable)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# -- baseline ----------------------------------------------------------------

def baseline_path(root: str | None = None) -> str:
    return os.path.join(root or repo_root(),
                        "kubernetes_tpu", "analysis", "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, str]:
    """{finding key: triage reason}. Missing file = empty baseline."""
    path = path or baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for entry in data.get("suppressions", []):
        out[entry["key"]] = entry.get("reason", "")
    return out


def apply_baseline(findings: list[Finding], baseline: dict[str, str]
                   ) -> tuple[list[Finding], list[Finding], list[str]]:
    """(unsuppressed, suppressed, stale keys). Stale = baseline entries
    matching nothing — reported as warnings so triage rot is visible,
    but non-fatal (a fixed defect must not break the gate)."""
    keys = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in baseline]
    suppressed = [f for f in findings if f.key in baseline]
    stale = [k for k in baseline if k not in keys]
    return unsuppressed, suppressed, stale
