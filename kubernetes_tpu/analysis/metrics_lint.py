"""Pass 4 — Prometheus conventions over the metric registrations.

The metric names are a dashboard contract (README Observability table;
SURVEY §5.5 pins the scheduler family to the reference's names), so
convention violations are API bugs, not style. The pass reads every
registration in the WHOLE tree — `r.counter(...)` / `r.gauge(...)` /
`r.histogram(...)` and direct `Counter(...)` / `Gauge(...)` /
`Histogram(...)` constructions with a literal name. (Originally it
only read `metrics/registry.py`; the audit sinks register their own
counters in `policy/audit.py` and the policy engine in `policy/vap.py`,
so ISSUE 15 widened the scan — a counter is a counter wherever it is
constructed.) It enforces:

- MT401 invalid metric name (Prometheus `[a-zA-Z_:][a-zA-Z0-9_:]*`).
- MT402 counter without the `_total` suffix.
- MT403 non-counter WITH a `_total` suffix (a gauge named `_total`
  reads as a counter on every dashboard).
- MT404 non-base unit in the name: `_ms`/`_millis`/`_micros`/`_kb`/
  `_mb`/… — Prometheus units are seconds and bytes, full stop. (The
  pass's first real catch: `scheduler_admission_window_ms`.)
- MT405 unbounded label cardinality: a label named after a per-object
  identifier (`pod`, `node`, `name`, `key`, `id`, `uid`) — each value
  mints a new series, and a 200k-node preset would mint 200k.
- MT406 time-named histogram (`*_duration*`/`*_latency*`/`*_time*`/
  `*_wait*`) whose name doesn't end in `_seconds`.
- MT407 invalid label name (`[a-zA-Z_][a-zA-Z0-9_]*`).
"""

from __future__ import annotations

import ast
import re

from kubernetes_tpu.analysis.engine import Finding, Module, call_name

PASS_ID = "metrics-lint"

#: kept for the fixture tests' narrow-scan mode; the default run scans
#: every module (registrations live in policy/audit.py etc. too).
REGISTRY_SUFFIX = "metrics/registry.py"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_BAD_UNIT_TOKENS = frozenset((
    "ms", "msec", "msecs", "millis", "milliseconds",
    "us", "usec", "micros", "microseconds", "nanos", "nanoseconds",
    "kb", "mb", "gb", "kib", "mib", "gib", "minutes", "hours",
))
_HIGH_CARDINALITY_LABELS = frozenset((
    "pod", "pod_name", "node", "node_name", "name", "key", "id", "uid",
    "container", "image",
))
_TIME_HINTS = ("duration", "latency", "_time", "_wait")

_KIND_METHODS = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}
_KIND_CTORS = {"Counter": "counter", "Gauge": "gauge",
               "Histogram": "histogram"}


def _registrations(mod: Module):
    """(kind, name, labels, line) for every literal registration."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        n = call_name(node)
        kind = None
        if n:
            last = n.split(".")[-1]
            if isinstance(node.func, ast.Attribute) \
                    and last in _KIND_METHODS:
                kind = _KIND_METHODS[last]
            elif last in _KIND_CTORS:
                kind = _KIND_CTORS[last]
        if kind is None or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        labels: list[str] = []
        label_args = [kw.value for kw in node.keywords
                      if kw.arg == "labels"]
        if len(node.args) >= 3:
            label_args.append(node.args[2])
        for la in label_args:
            if isinstance(la, (ast.Tuple, ast.List)):
                for el in la.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        labels.append(el.value)
        yield kind, first.value, labels, node.lineno


def run(modules: list[Module],
        registry_suffix: str | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if registry_suffix is not None \
                and not mod.rel.endswith(registry_suffix):
            continue
        for kind, name, labels, line in _registrations(mod):
            def emit(code, msg, anchor=None):
                findings.append(Finding(
                    pass_id=PASS_ID, code=code, path=mod.rel, line=line,
                    symbol=anchor or name, message=msg))

            if not _NAME_RE.match(name):
                emit("MT401", f"invalid metric name {name!r}")
                continue
            tokens = name.split("_")
            if kind == "counter" and not name.endswith("_total"):
                emit("MT402", f"counter {name!r} must end in `_total`")
            if kind != "counter" and name.endswith("_total"):
                emit("MT403", f"{kind} {name!r} ends in `_total` — that "
                              "suffix means counter on every dashboard")
            bad = sorted(set(tokens) & _BAD_UNIT_TOKENS)
            if bad:
                emit("MT404", f"{kind} {name!r} uses non-base unit "
                              f"{bad} — Prometheus units are seconds "
                              "and bytes")
            if kind == "histogram" \
                    and any(h in name for h in _TIME_HINTS) \
                    and not name.endswith("_seconds"):
                emit("MT406", f"time-named histogram {name!r} must end "
                              "in `_seconds`")
            for lbl in labels:
                if not _LABEL_RE.match(lbl):
                    emit("MT407", f"{name!r}: invalid label name "
                                  f"{lbl!r}", anchor=f"{name}:{lbl}")
                elif lbl in _HIGH_CARDINALITY_LABELS:
                    emit("MT405", f"{name!r}: label {lbl!r} is a "
                                  "per-object identifier — unbounded "
                                  "series cardinality",
                         anchor=f"{name}:{lbl}")
    return findings
