"""`python -m kubernetes_tpu.analysis` — the ktpu-lint CLI."""

import sys

from kubernetes_tpu.analysis import main

if __name__ == "__main__":
    sys.exit(main())
