"""ktpu-lint: repo-invariant static analysis, enforced in tier-1.

Five load-bearing contracts in this tree existed only as prose and
runtime differential tests: solve-path bit-identity, structural
kill-switch degradation, jit-purity of everything the fused programs
close over, lock discipline across the apiserver/informer/serving
threads, and a sprawl of `KTPU_*` env reads. This package turns them
into machine-checked invariants — the analog of the reference shipping
`go vet` + race-detector gates on the scheduling cycle — so the Pallas
kernel work can rewrite the hottest path with regressions caught at
analysis time, not after a 200k-preset bench run.

Four passes (each a module, each with its own finding codes):

- `jit_purity` (JP1xx) — host syncs, wall-clock/randomness, and Python
  branching on traced values, in everything reachable from the
  jitted/scan entry points.
- `locks` (LK2xx) — static lock-order graph (cycles), locks held
  across await/device-fetch/wire-send, guarded state iterated without
  its lock. Cross-validated at runtime by `utils/locking.py`
  (`KTPU_LOCK_CHECK=1`).
- `flags_pass` (FL3xx) — every `KTPU_*` env read routes through
  `utils/flags.py`; registry entries carry docs and tests; the README
  flag table is generated, not hand-maintained.
- `metrics_lint` (MT4xx) — Prometheus naming/unit/label-cardinality
  conventions over `metrics/registry.py`.

Findings resolve against `analysis/baseline.json` — a triaged
suppression list keyed by (pass, code, path, symbol), no line numbers,
each entry carrying a reason string. The tier-1 gate
(tests/test_static_analysis.py) asserts zero UNSUPPRESSED findings.

CLI (`python -m kubernetes_tpu.analysis`, also `bench.py --lint`):
exit 0 = clean, 1 = findings, 2 = internal error (ruff-style, so the
gate is scriptable). `--json` emits machine-readable findings.
"""

from __future__ import annotations

import json
import sys
import traceback

from kubernetes_tpu.analysis.engine import (
    Finding,
    apply_baseline,
    load_baseline,
    load_modules,
)

__all__ = ["Finding", "run_all", "main"]

#: pass registry: id -> runner(modules) -> [Finding]
def _passes():
    from kubernetes_tpu.analysis import (
        flags_pass,
        jit_purity,
        locks,
        metrics_lint,
    )
    return (
        (jit_purity.PASS_ID, jit_purity.run),
        (locks.PASS_ID, locks.run),
        (flags_pass.PASS_ID, flags_pass.run),
        (metrics_lint.PASS_ID, metrics_lint.run),
    )


def run_all(root: str | None = None,
            baseline: dict[str, str] | None = None):
    """Run every pass over the tree. Returns
    (unsuppressed, suppressed, stale_keys, per_pass_counts)."""
    modules = load_modules(root)
    findings: list[Finding] = []
    per_pass: dict[str, int] = {}
    for pass_id, runner in _passes():
        got = runner(modules)
        per_pass[pass_id] = len(got)
        findings.extend(got)
    if baseline is None:
        baseline = load_baseline()
    unsup, sup, stale = apply_baseline(findings, baseline)
    return unsup, sup, stale, per_pass


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppressed or not")
    ap.add_argument("--baseline", default=None,
                    help="alternate baseline file")
    ap.add_argument("--write-readme-flags", action="store_true",
                    help="regenerate the README's generated flag table "
                         "from utils/flags.py and exit")
    args = ap.parse_args(argv)

    try:
        if args.write_readme_flags:
            from kubernetes_tpu.analysis.flags_pass import (
                write_readme_table,
            )
            changed = write_readme_table()
            print("README flag table "
                  + ("updated" if changed else "already current"))
            return 0
        baseline = {} if args.no_baseline \
            else load_baseline(args.baseline)
        unsup, sup, stale, per_pass = run_all(baseline=baseline)
    except Exception:
        traceback.print_exc()
        return 2

    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in unsup],
            "suppressed": [f.as_dict() for f in sup],
            "stale_suppressions": stale,
            "per_pass": per_pass,
        }, indent=2))
    else:
        for f in unsup:
            print(f"{f.path}:{f.line}: {f.code} [{f.pass_id}] "
                  f"{f.message}")
        print(f"ktpu-lint: {sum(per_pass.values())} finding(s) across "
              f"{len(per_pass)} passes "
              f"({', '.join(f'{k}={v}' for k, v in per_pass.items())}); "
              f"{len(sup)} suppressed by baseline, "
              f"{len(unsup)} unsuppressed")
        if stale:
            print(f"warning: {len(stale)} stale baseline suppression(s) "
                  "match nothing — prune analysis/baseline.json:")
            for k in stale:
                print(f"  - {k}")
    return 1 if unsup else 0
