"""Pass 2 — static lock discipline across the control-plane threads.

The tree is asyncio-first, but three thread populations really do share
state: the event loop, the backend's `to_thread` solve-fetch workers,
and XLA's own callback threads. The locks guarding that shared state
(today: the metrics registry's per-metric locks, via
`utils/locking.new_lock`) and the asyncio conditions coordinating the
queues are what this pass audits:

- **LK201 lock-order cycle**: the acquisition graph (edges outer→inner
  from nested `with` blocks, plus one level of same-class method calls
  under a held lock) contains a cycle — the static ABBA.
- **LK202 await under a lock**: `await` inside `with <threading lock>`
  (impossible to be correct — the loop thread blocks every other
  holder) or an `asyncio.sleep`/fetch/send await inside `async with
  <condition>`. `cond.wait()` / `cond.wait_for()` on the HELD condition
  is the sanctioned pattern (it releases the lock) and is exempt, also
  when wrapped in `asyncio.wait_for`.
- **LK203 device fetch under a lock**: `np.asarray` / `.item()` /
  `block_until_ready` / `jax.device_get` while holding any lock — a
  device round-trip (up to ~100 ms on a relay) stalls every other
  holder. The runtime twin is `locking.check_dispatch_seam` at the
  sanctioned fetch seams.
- **LK204 wire send under a lock**: `transport.write` / `.sendall` /
  `writer.drain` while holding a lock.
- **LK205 guarded state read without the lock**: an attribute written
  under `with self.<lock>` in one method of a class is ITERATED (for
  loop, comprehension, `sorted`/`list`/`tuple`/`dict` call) in another
  method with no lock held. This is the race that motivated the pass:
  `Counter._render` iterated `self._values` lock-free while to_thread
  fetch workers `inc()`ed — "dictionary changed size during iteration"
  on the serving seam. Applies to THREADING locks only; asyncio
  conditions serialize on the loop and don't need read-side locking.
- **LK206 file I/O under a lock**: `open()` / `os.rename` / `os.replace`
  / `os.remove` / `os.unlink` while holding any lock. Added for the
  audit sink workers (ISSUE 15): the rotation sink's segment shuffle and
  batch append are disk I/O — milliseconds on a loaded box — and a lock
  held across them stalls every emitter. The runtime twin is the
  `check_dispatch_seam` guard in `policy/audit.py`'s `_write_batch` /
  webhook `_send`.
- **LK207 process spawn/join under a lock**: `subprocess.run`/`Popen`/
  `call`/`check_call`/`check_output`, `os.waitpid`/`os.fork`,
  `multiprocessing.Process(...)`, or a `.start()`/`.join()`/`.wait()`/
  `.terminate()`/`.kill()` on a process-ish receiver (`*proc*`,
  `*process*`, `*child*`, `*worker*`) while holding any lock. Added for
  the multi-process control plane (ISSUE r22): an interpreter spawn is
  hundreds of milliseconds and a join is unbounded — either one under
  the shared RV counter's lock (or any registry lock) stalls every
  shard's write path.

Lock identity is the attribute site (`module.Class.attr`); anything
assigned from `threading.Lock/RLock/Condition`, `asyncio.Lock/
Condition/Semaphore` or `new_lock(...)` counts, as does any `with
self.<name>` whose attribute LOOKS like a lock (`*lock*`, `*cond*`,
`*mutex*`) — so a lock the detector didn't see constructed still
participates.
"""

from __future__ import annotations

import ast

from kubernetes_tpu.analysis.engine import (
    Finding,
    Module,
    call_name,
    dotted,
)

PASS_ID = "lock-discipline"

_THREAD_LOCK_CALLS = ("threading.Lock", "threading.RLock",
                      "threading.Condition", "Lock", "RLock",
                      "new_lock", "locking.new_lock")
_ASYNC_LOCK_CALLS = ("asyncio.Lock", "asyncio.Condition",
                     "asyncio.Semaphore", "asyncio.BoundedSemaphore")
_LOCKISH_FRAGMENTS = ("lock", "cond", "mutex", "_mu")

_FETCH_ATTRS = ("item", "block_until_ready")
_FETCH_CALLS = ("np.asarray", "numpy.asarray", "np.array",
                "jax.device_get")
_SEND_ATTRS = ("sendall", "send_bytes", "drain")
_SEND_CALLS = ("self.transport.write", "transport.write")
_FILE_CALLS = ("open", "os.rename", "os.replace", "os.remove",
               "os.unlink")
_PROC_CALLS = ("subprocess.run", "subprocess.Popen", "subprocess.call",
               "subprocess.check_call", "subprocess.check_output",
               "os.waitpid", "os.fork", "multiprocessing.Process")
_PROC_ATTRS = ("start", "join", "wait", "terminate", "kill")
#: receiver fragments that make a bare `.join()`/`.wait()` process-ish
#: (so `",".join(...)` and `cond.wait()` never match).
_PROC_RECEIVERS = ("proc", "process", "child", "worker")


def _lockish_attr(name: str) -> bool:
    low = name.lower()
    return any(f in low for f in _LOCKISH_FRAGMENTS)


class _ClassLocks(ast.NodeVisitor):
    """Collect declared lock attributes per class: {class: {attr: kind}}
    with kind in {"thread", "async"}."""

    def __init__(self):
        self.locks: dict[str, dict[str, str]] = {}
        self._cls: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls.append(node.name)
        self.locks.setdefault(node.name, {})
        self.generic_visit(node)
        self._cls.pop()

    def visit_Assign(self, node: ast.Assign):
        if self._cls and isinstance(node.value, ast.Call):
            n = call_name(node.value)
            kind = None
            if n in _THREAD_LOCK_CALLS:
                kind = "thread"
            elif n in _ASYNC_LOCK_CALLS:
                kind = "async"
            if kind:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        self.locks[self._cls[-1]][tgt.attr] = kind
        self.generic_visit(node)


def _with_lock_attr(item: ast.withitem) -> str | None:
    """`with self.X:` / `async with self.X:` — X when lock-ish."""
    expr = item.context_expr
    d = dotted(expr)
    if d and d.startswith("self.") and d.count(".") == 1:
        attr = d.split(".", 1)[1]
        if _lockish_attr(attr):
            return attr
    return None


def _held_cond_wait(call: ast.Call, held: list[tuple[str, str, bool]]
                    ) -> bool:
    """`self.<heldcond>.wait()` / `.wait_for()` (possibly inside
    asyncio.wait_for(...)) — the sanctioned release-and-wait."""
    held_attrs = {attr for attr, _kind, _async in held}
    for sub in ast.walk(call):
        if isinstance(sub, ast.Call):
            n = call_name(sub)
            if n and n.startswith("self.") and (
                    n.endswith(".wait") or n.endswith(".wait_for")):
                attr = n.split(".")[1]
                if attr in held_attrs:
                    return True
    return False


def run(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    #: name-level acquisition edges across the whole tree:
    #: (outer "mod.Class.attr", inner ...) -> (rel, line)
    edges: dict[tuple[str, str], tuple[str, int]] = {}

    for mod in modules:
        decl = _ClassLocks()
        decl.visit(mod.tree)
        modbase = mod.rel.rsplit("/", 1)[-1][:-3]

        for cls_node in [n for n in ast.walk(mod.tree)
                         if isinstance(n, ast.ClassDef)]:
            cls_locks = decl.locks.get(cls_node.name, {})
            thread_locks = {a for a, k in cls_locks.items()
                            if k == "thread"}

            #: attrs written while holding each thread lock, and
            #: (attr-iterated, method, line) sites with no lock held.
            guarded_writes: dict[str, set[str]] = {}
            bare_iterations: list[tuple[str, str, int]] = []

            for meth in [n for n in cls_node.body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]:
                qn = f"{cls_node.name}.{meth.name}"
                _scan_body(
                    mod, modbase, qn, meth.body, [], cls_locks,
                    findings, edges, guarded_writes, bare_iterations)

            # LK205: iterate-without-lock on state some method guards.
            guarded_attrs = set()
            for lock_attr in thread_locks:
                guarded_attrs |= guarded_writes.get(lock_attr, set())
            for attr, qn, line in bare_iterations:
                if attr in guarded_attrs:
                    findings.append(Finding(
                        pass_id=PASS_ID, code="LK205", path=mod.rel,
                        line=line, symbol=f"{qn}:{attr}",
                        message=f"`{qn}` iterates `self.{attr}` without "
                                "a lock, but other methods mutate it "
                                "under one — racing writers can resize "
                                "the dict mid-iteration"))

    # LK201: cycle detection on the name-level edge graph (pairwise
    # inversions plus longer cycles via DFS).
    adj: dict[str, set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    state: dict[str, int] = {}

    def dfs(node: str, path: list[str]) -> list[str] | None:
        state[node] = 1
        for nxt in adj.get(node, ()):
            if state.get(nxt) == 1:
                return path[path.index(nxt):] + [nxt] \
                    if nxt in path else [node, nxt]
            if state.get(nxt, 0) == 0:
                cyc = dfs(nxt, path + [nxt])
                if cyc:
                    return cyc
        state[node] = 2
        return None

    for start in sorted(adj):
        if state.get(start, 0) == 0:
            cyc = dfs(start, [start])
            if cyc:
                rel, line = edges.get((cyc[0], cyc[1]), ("", 0))
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK201", path=rel, line=line,
                    symbol="->".join(cyc),
                    message="lock-order cycle in the static acquisition "
                            f"graph: {' -> '.join(cyc)} — an ABBA "
                            "deadlock candidate"))
                break
    return findings


def _scan_body(mod, modbase, qn, body, held, cls_locks, findings,
               edges, guarded_writes, bare_iterations):
    """Walk one method body tracking the held-lock stack.

    held: [(attr, lock_id, is_async_with)]. Statements are visited
    exactly once: a compound statement contributes its OWN expressions
    (test / iter / value) at the current held depth, then its nested
    statements recurse — `with` blocks push onto the stack."""
    cls_name = qn.split(".")[0]

    def lock_id(attr: str) -> str:
        return f"{modbase}.{cls_name}.{attr}"

    def kind_of(attr: str) -> str:
        # undeclared lock-ish attrs default to "thread" (conservative).
        return cls_locks.get(attr, "thread")

    def handle_exprs(stmt: ast.stmt) -> None:
        own = [c for c in ast.iter_child_nodes(stmt)
               if isinstance(c, ast.expr)]
        for expr in own:
            if held:
                _check_held(mod, qn, expr, held, cls_locks, findings)
            else:
                for attr, line in _iterated_self_attrs(expr):
                    bare_iterations.append((attr, qn, line))
        if not held and isinstance(stmt, (ast.For, ast.AsyncFor)):
            # `for k in self.attr:` — the iter expr alone, no call.
            a = _src_attr(stmt.iter)
            if a:
                bare_iterations.append((a, qn, stmt.lineno))

    for node in body:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                if held or acquired:
                    # `with open(...)`-style context expressions execute
                    # while the outer locks are held — hazard-check them
                    # (the rotation sink's file-I/O shape, LK206).
                    # `acquired` covers the one-statement form
                    # `with self._lock, open(...)`: items to the left
                    # are already held when this item's expr runs.
                    _check_held(mod, qn, item.context_expr,
                                held + acquired, cls_locks, findings)
                attr = _with_lock_attr(item)
                if attr is not None:
                    for outer_attr, outer_id, _a in held:
                        if outer_attr != attr:
                            edges[(outer_id, lock_id(attr))] = \
                                (mod.rel, node.lineno)
                    acquired.append(
                        (attr, lock_id(attr),
                         isinstance(node, ast.AsyncWith)))
                    if kind_of(attr) == "thread":
                        guarded_writes.setdefault(attr, set()).update(
                            _written_attrs(node.body))
            _scan_body(mod, modbase, qn, node.body, held + acquired,
                       cls_locks, findings, edges, guarded_writes,
                       bare_iterations)
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        handle_exprs(node)
        # nested statements (if/for/try bodies, except handlers …)
        inner: list[ast.stmt] = []
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                inner.append(child)
            elif isinstance(child, ast.excepthandler):
                inner.extend(child.body)
        if inner:
            _scan_body(mod, modbase, qn, inner, held, cls_locks,
                       findings, edges, guarded_writes, bare_iterations)


def _check_held(mod, qn, node, held, cls_locks, findings):
    """Hazards inside a statement while locks are held (LK202-204)."""
    any_thread = any(cls_locks.get(a, "thread") == "thread"
                     for a, _i, _aw in held)
    held_names = [i for _a, i, _aw in held]

    for sub in ast.walk(node):
        if isinstance(sub, ast.Await):
            if isinstance(sub.value, ast.Call) \
                    and _held_cond_wait(sub.value, held):
                continue
            n = call_name(sub.value) if isinstance(sub.value, ast.Call) \
                else None
            hazardous = any_thread or (
                n is not None and (n.startswith("asyncio.sleep")
                                   or n in _FETCH_CALLS
                                   or n in _SEND_CALLS))
            if hazardous:
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK202", path=mod.rel,
                    line=sub.lineno, symbol=f"{qn}:await",
                    message=f"`{qn}` awaits while holding "
                            f"{held_names} — the lock is held across "
                            "the suspension"))
        elif isinstance(sub, ast.Call):
            n = call_name(sub)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _FETCH_ATTRS) \
                    or n in _FETCH_CALLS:
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK203", path=mod.rel,
                    line=sub.lineno,
                    symbol=f"{qn}:{n or sub.func.attr}",
                    message=f"`{qn}` performs a device fetch while "
                            f"holding {held_names} — a device "
                            "round-trip stalls every other holder"))
            elif (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _SEND_ATTRS) \
                    or n in _SEND_CALLS:
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK204", path=mod.rel,
                    line=sub.lineno,
                    symbol=f"{qn}:{n or sub.func.attr}",
                    message=f"`{qn}` sends on a wire while holding "
                            f"{held_names}"))
            elif n in _FILE_CALLS:
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK206", path=mod.rel,
                    line=sub.lineno, symbol=f"{qn}:{n}",
                    message=f"`{qn}` performs file I/O while holding "
                            f"{held_names} — disk latency stalls every "
                            "other holder (rotate/append outside the "
                            "lock)"))
            elif n in _PROC_CALLS or _procish_call(sub):
                findings.append(Finding(
                    pass_id=PASS_ID, code="LK207", path=mod.rel,
                    line=sub.lineno,
                    symbol=f"{qn}:{n or sub.func.attr}",
                    message=f"`{qn}` spawns or joins an OS process "
                            f"while holding {held_names} — interpreter "
                            "boot is ~100s of ms and a join is "
                            "unbounded; every other holder stalls"))


def _procish_call(call: ast.Call) -> bool:
    """`<receiver>.start()/join()/wait()/terminate()/kill()` where the
    dotted receiver names a process (`self._procs[i].join()`,
    `worker.terminate()`); plain `",".join()` / `cond.wait()` don't."""
    if not isinstance(call.func, ast.Attribute) \
            or call.func.attr not in _PROC_ATTRS:
        return False
    recv = call.func.value
    if isinstance(recv, ast.Subscript):
        recv = recv.value
    low = (dotted(recv) or "").lower()
    return any(f in low for f in _PROC_RECEIVERS)


def _written_attrs(body) -> set[str]:
    """self.<attr> targets mutated anywhere in these statements."""
    out: set[str] = set()
    for stmt in body:
        for sub in ast.walk(stmt):
            tgt = None
            if isinstance(sub, (ast.Assign,)):
                for t in sub.targets:
                    tgt = t
                    out |= _self_attr_of_target(tgt)
            elif isinstance(sub, ast.AugAssign):
                out |= _self_attr_of_target(sub.target)
    return out


def _self_attr_of_target(t: ast.expr) -> set[str]:
    # self.attr = / self.attr[k] = / self.attr[k] +=
    if isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return {t.attr}
    return set()


def _src_attr(e: ast.expr) -> str | None:
    """self.attr | self.attr.items()/keys()/values() → attr."""
    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
            and e.func.attr in ("items", "keys", "values"):
        e = e.func.value
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


def _iterated_self_attrs(node: ast.AST):
    """(attr, line) for self.<attr> iterated anywhere in this expression:
    comprehension sources and materializing calls (sorted/list/…) over
    self.<attr> or self.<attr>.items() and friends."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                            ast.GeneratorExp)):
            for gen in sub.generators:
                a = _src_attr(gen.iter)
                if a:
                    out.append((a, sub.lineno))
        elif isinstance(sub, ast.Call):
            n = call_name(sub)
            if n in ("sorted", "list", "tuple", "set", "dict", "max",
                     "min", "sum", "itertools.accumulate"):
                for arg in sub.args:
                    a = _src_attr(arg)
                    if a:
                        out.append((a, sub.lineno))
    return out
