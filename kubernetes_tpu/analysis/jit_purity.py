"""Pass 1 — jit-purity of everything the fused programs close over.

Entry points are discovered, not configured: every function decorated
with `jax.jit` / `partial(jax.jit, ...)` / `jax.vmap` / `shard_map`,
plus every named function passed as the first argument to `lax.scan`,
`jax.vmap`, `lax.cond`, `jax.jit` or `shard_map`, inside the solve-path
modules (ops/, parallel/, serving/fastpath). The pass then walks the
intra-package call graph from those entries (bare-name calls resolve
within the module; `alias.name(...)` calls resolve through the import
table into sibling modules) and flags, inside any reachable function:

- JP101 host sync: `.item()` / `.tolist()` / `.block_until_ready()`,
  `np.asarray` / `np.array` / `jax.device_get` — a traced value forced
  to host mid-program is a device round-trip per trace at best and a
  tracer leak at worst. The sanctioned fetch seams (`_fetch_assign`,
  the fast path's post-solve fetch) are host drivers, not jit-reachable,
  so they never enter the walk.
- JP102 wall-clock / randomness / IO: `time.*`, `random.*`,
  `np.random.*`, `datetime.now`, `print`, `os.environ` — values baked
  in at trace time and re-used on every later call of the compiled
  program (the classic "why is my timestamp frozen" bug).
- JP103 Python branching on a traced value: an `if`/`while`/`assert`
  whose test contains a direct `jnp.*` / `lax.*` call — under trace
  this raises `TracerBoolConversionError` on good days and silently
  specializes on bad ones (`bool()` on a jnp call is the same defect
  spelled differently, and is flagged too, as are `float()`/`int()`).

Heuristic boundaries, stated honestly: the pass has no type inference,
so it flags *syntactically certain* host ops rather than guessing at
tracer-hood of every name — `int(x.shape[0])` stays legal, `if
jnp.any(mask):` does not. That is exactly the precision the solve-path
invariants need: every genuine violation class above is syntactically
visible, and the differential suites own the semantic rest.
"""

from __future__ import annotations

import ast

from kubernetes_tpu.analysis.engine import (
    Finding,
    FunctionIndex,
    Module,
    call_name,
    decorator_names,
    own_statements,
)

PASS_ID = "jit-purity"

#: modules whose functions can be jit entry points (the solve path).
ENTRY_MODULE_SUFFIXES = (
    "kubernetes_tpu/ops/solver.py",
    "kubernetes_tpu/ops/kernels.py",
    "kubernetes_tpu/ops/pallas_kernel.py",
    "kubernetes_tpu/ops/backend.py",
    "kubernetes_tpu/ops/affinity.py",
    "kubernetes_tpu/parallel/sharded.py",
    "kubernetes_tpu/parallel/mesh.py",
    "kubernetes_tpu/serving/fastpath.py",
    "kubernetes_tpu/topology/device.py",
)

_JIT_DECORATORS = ("jax.jit", "jit", "jax.vmap", "shard_map",
                   "jax.named_call")
_TRACE_WRAPPERS = ("lax.scan", "jax.lax.scan", "jax.vmap", "vmap",
                   "lax.cond", "jax.lax.cond", "jax.jit", "jit",
                   "shard_map", "lax.while_loop", "jax.lax.while_loop",
                   "lax.fori_loop", "jax.checkpoint", "jax.remat",
                   "pl.pallas_call", "pallas_call")

_HOST_SYNC_ATTRS = ("item", "tolist", "block_until_ready")
_HOST_SYNC_CALLS = ("np.asarray", "numpy.asarray", "np.array",
                    "numpy.array", "jax.device_get", "onp.asarray")
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "datetime.", "os.environ", "os.getenv")
_IMPURE_CALLS = ("print", "input", "open")
_TRACED_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")


def _is_traced_expr(node: ast.expr) -> ast.Call | None:
    """A direct jnp./lax. call anywhere inside the expression."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            n = call_name(sub)
            if n and n.startswith(_TRACED_PREFIXES):
                return sub
    return None


def _entry_functions(index: FunctionIndex) -> set[str]:
    """Qualnames of jit/scan entry points in one module."""
    entries: set[str] = set()
    for qn, fn in index.functions.items():
        for dec in decorator_names(fn):
            if dec in _JIT_DECORATORS or dec.endswith(".jit"):
                entries.add(qn)
    # Named functions handed to trace wrappers: lax.scan(step, ...),
    # jax.vmap(one)(...), jax.jit(body), lax.cond(pred, f, g, ...).
    for node in ast.walk(index.module.tree):
        if not isinstance(node, ast.Call):
            continue
        n = call_name(node)
        if n not in _TRACE_WRAPPERS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id in index.by_name:
                entries.update(index.by_name[arg.id])
    return entries


def _reachable(indices: dict[str, FunctionIndex],
               entry_map: dict[str, set[str]]) -> set[tuple[str, str]]:
    """Closure of (module rel, qualname) reachable from the entries.

    A reachable function pulls in (a) its own nested defs — they execute
    under the same trace — and (b) every call target resolvable within
    the package: bare names in the same module, `alias.fn` through the
    import table into a sibling module's index."""
    # module path -> index, for alias resolution
    by_modpath: dict[str, FunctionIndex] = {}
    for rel, idx in indices.items():
        modpath = rel[:-3].replace("/", ".")
        if modpath.endswith(".__init__"):
            modpath = modpath[: -len(".__init__")]
        by_modpath[modpath] = idx

    seen: set[tuple[str, str]] = set()
    work: list[tuple[str, str]] = [
        (rel, qn) for rel, qns in entry_map.items() for qn in qns]
    while work:
        rel, qn = work.pop()
        if (rel, qn) in seen:
            continue
        seen.add((rel, qn))
        idx = indices[rel]
        fn = idx.functions.get(qn)
        if fn is None:
            continue
        # nested defs trace with their parent
        for sub_qn in idx.functions:
            if sub_qn.startswith(qn + ".") and (rel, sub_qn) not in seen:
                work.append((rel, sub_qn))
        for node in own_statements(fn):
            if not isinstance(node, ast.Call):
                continue
            n = call_name(node)
            if not n:
                continue
            head, _, tail = n.partition(".")
            if not tail and n in idx.by_name:
                for cand in idx.by_name[n]:
                    work.append((rel, cand))
            elif tail:
                target_mod = idx.module.aliases.get(head)
                if target_mod and target_mod.startswith("kubernetes_tpu"):
                    tgt = by_modpath.get(target_mod)
                    if tgt is not None:
                        for cand in tgt.by_name.get(
                                tail.split(".")[-1], ()):
                            work.append((tgt.module.rel, cand))
    return seen


def run(modules: list[Module]) -> list[Finding]:
    entry_mods = [m for m in modules
                  if m.rel.endswith(ENTRY_MODULE_SUFFIXES)
                  or any(m.rel == s for s in ENTRY_MODULE_SUFFIXES)]
    indices = {m.rel: FunctionIndex(m) for m in entry_mods}
    entry_map = {rel: _entry_functions(idx)
                 for rel, idx in indices.items()}
    reachable = _reachable(indices, entry_map)

    findings: list[Finding] = []

    def emit(code, rel, node, qn, anchor, msg):
        findings.append(Finding(
            pass_id=PASS_ID, code=code, path=rel,
            line=getattr(node, "lineno", 0),
            symbol=f"{qn}:{anchor}", message=msg))

    for rel, qn in sorted(reachable):
        idx = indices[rel]
        fn = idx.functions.get(qn)
        if fn is None:
            continue
        for node in own_statements(fn):
            if isinstance(node, ast.Call):
                n = call_name(node)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _HOST_SYNC_ATTRS:
                    emit("JP101", rel, node, qn, node.func.attr,
                         f"host sync `.{node.func.attr}()` inside "
                         f"jit-reachable `{qn}` — forces a device "
                         "round-trip / tracer leak under trace")
                elif n in _HOST_SYNC_CALLS:
                    emit("JP101", rel, node, qn, n,
                         f"host materialization `{n}(...)` inside "
                         f"jit-reachable `{qn}`")
                elif n and (n.startswith(_IMPURE_PREFIXES)
                            or n in _IMPURE_CALLS):
                    emit("JP102", rel, node, qn, n,
                         f"impure call `{n}(...)` inside jit-reachable "
                         f"`{qn}` — the value is frozen at trace time")
                elif n in ("float", "bool", "int") and node.args:
                    traced = _is_traced_expr(node.args[0])
                    if traced is not None:
                        emit("JP103", rel, node, qn, f"{n}()",
                             f"`{n}()` on a traced expression "
                             f"(`{call_name(traced)}`) inside "
                             f"jit-reachable `{qn}` — concretizes a "
                             "tracer")
            elif isinstance(node, (ast.If, ast.While, ast.Assert)):
                test = node.test
                traced = _is_traced_expr(test)
                if traced is not None:
                    kind = type(node).__name__.lower()
                    emit("JP103", rel, node, qn, kind,
                         f"Python `{kind}` on a traced expression "
                         f"(`{call_name(traced)}`) inside jit-reachable "
                         f"`{qn}` — branch on device values with "
                         "jnp.where/lax.cond")
    return findings
