"""Sharded control plane: hash-partitioned per-shard mvcc stores.

The single-process control plane tops out where one mvcc store has to
absorb every node's writes, serve every LIST off one snapshot, and run
one watch-dispatch loop for the whole cluster (the r12 headroom note:
at 50k+ the bound stops being the device solve and becomes the store
around it). This module is the scale-out half SURVEY §5.7 leaves to the
control plane: partition the NODE axis over S shards the way the device
mesh partitions it over chips.

Design:

- **Partitioning.** Node-keyed resources (`PARTITIONED_RESOURCES`:
  nodes, leases, noderesourcetopologies, resourceslices — objects whose
  name IS a node name) route to shard `crc32(name) % S`. Everything
  else (pods, events, config objects, CRDs) lives on the *meta* shard
  (shard 0), so pod scheduling traffic and policy objects keep the
  single-store semantics they had.
- **One RV counter.** All shards share one `RVCounter` (mvcc.py), so
  ResourceVersions stay globally monotonic: a merged LIST's RV can
  resume a watch on ANY shard, pinned continue tokens (`"<rv>:<key>"`)
  roll every shard's cacher back to the same global snapshot, and the
  per-key event order any single watcher observes is the cluster-wide
  commit order — the etcd-revision contract, kept under partitioning.
- **Per-shard serving tiers.** Each shard owns its own watch-cache tier
  (store/cacher.py) and event ring: a node-churn storm on one shard
  cannot age another shard's backfill window, and the O(table) costs of
  snapshot maintenance (sorted-key insort at ingest) divide by S.
- **Reads.** LIST of a partitioned resource fans out to every shard and
  merge-sorts by key — bit-identical to the single-store scan (same
  sort order, same paging, same RV semantics; differential-tested).
  WATCH takes an optional `shard=` to consume one shard's stream (the
  per-shard informer path — client/informer.ShardedInformer); with no
  shard it multiplexes all shards into one stream with conservative
  merged bookmarks (min across shards), so unsharded-client wires
  (HTTP, gRPC) keep working unchanged.

Activation: `new_cluster_store(shards=S)`; bench.py resolves S flagless
from the node count (`control_plane_shards`: ≥ KTPU_SHARD_THRESHOLD
nodes → KTPU_SHARDS or 8). `KTPU_SHARDS=1` is the kill switch — S=1
is the plain single `MVCCStore` (new_cluster_store doesn't construct
this facade at all), so degradation is structural, not a code path.
"""

from __future__ import annotations

import asyncio
import json
import zlib
from typing import Any, AsyncIterator, Callable, Mapping

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.metrics.registry import WatchMetrics
from kubernetes_tpu.utils import flags
from kubernetes_tpu.store.mvcc import (
    DEFAULT_EVENT_WINDOW,
    Event,
    ListResult,
    MVCCStore,
    RVCounter,
)

#: Resources whose object NAME is a node name; these partition.
PARTITIONED_RESOURCES = (
    "nodes", "leases", "noderesourcetopologies", "resourceslices")

#: Flagless activation threshold (node count) and default shard count.
DEFAULT_SHARD_THRESHOLD = 100_000
DEFAULT_SHARDS = 8


def shard_of(name: str, shards: int) -> int:
    """Stable hash partition (crc32 — process-independent, unlike
    `hash()`): which shard owns the node-keyed object `name`."""
    if shards <= 1:
        return 0
    return zlib.crc32(name.encode()) % shards


def control_plane_shards(n_nodes: int, override: int | None = None) -> int:
    """The flagless shard-count policy shared by bench.py and the host
    prep: explicit override > KTPU_SHARDS env > node-count threshold
    (KTPU_SHARD_THRESHOLD, default 100k → 8 shards; below it 1 — the
    5k/50k presets keep the r12 single-store path bit-for-bit)."""
    if override is not None:
        return max(1, int(override))
    env = flags.get("KTPU_SHARDS")
    if env is not None:
        # 0 clamps to 1 like every other value ≤ 1 (the single-store
        # kill switch), matching new_cluster_store's `or 1` — falling
        # through to the threshold policy here would hand an 8-shard
        # prep accounting to a 1-shard store.
        return max(1, env)
    threshold = flags.get("KTPU_SHARD_THRESHOLD")
    return DEFAULT_SHARDS if n_nodes >= threshold else 1


def _name_of_key(key: str) -> str:
    """Object name from a store key ('ns/name' or 'name')."""
    return key.rsplit("/", 1)[-1]


class ShardedNodeStore:
    """S per-shard MVCCStores behind the MVCCStore public surface.

    Pods and other unpartitioned resources live on `self.meta`
    (shard 0); node-keyed resources hash across `self.shards`. All
    shards share one RV counter, one WatchMetrics, and one
    WatchCacheMetrics, so the facade's observability reads like one
    store's."""

    def __init__(self, shards: int = DEFAULT_SHARDS,
                 event_window: int = DEFAULT_EVENT_WINDOW):
        self.node_shards = max(2, int(shards))
        self._rv_counter = RVCounter()
        self.shards: list[MVCCStore] = [
            MVCCStore(event_window, rv_source=self._rv_counter)
            for _ in range(self.node_shards)]
        self.meta = self.shards[0]
        self.partitioned_resources = PARTITIONED_RESOURCES
        # One metrics instance across shards: counters sum naturally.
        self.watch_metrics = WatchMetrics()
        for s in self.shards:
            s.watch_metrics = self.watch_metrics
        if self.meta.cacher is not None:
            shared = self.meta.cacher.metrics
            for s in self.shards[1:]:
                s.cacher.metrics = shared

    # -- routing -----------------------------------------------------------

    def _check_shard(self, shard: int) -> int:
        """Validate a CLIENT-supplied shard index (the wire passes it
        through verbatim): negatives must not silently alias shard S-1
        and out-of-range must be a clean 422, not an IndexError."""
        from kubernetes_tpu.store.mvcc import Invalid
        s = int(shard)
        if not 0 <= s < self.node_shards:
            raise Invalid(
                f"shard {s} out of range (store has {self.node_shards})")
        return s

    def shard_index(self, resource: str, name: str) -> int:
        if resource not in self.partitioned_resources:
            return 0
        return shard_of(name, self.node_shards)

    def _store_for(self, resource: str, name: str) -> MVCCStore:
        return self.shards[self.shard_index(resource, name)]

    def _store_for_key(self, resource: str, key: str) -> MVCCStore:
        return self._store_for(resource, _name_of_key(key))

    def _store_for_obj(self, resource: str, obj: Mapping) -> MVCCStore:
        name = (obj.get("metadata") or {}).get("name", "")
        return self._store_for(resource, name)

    # -- facade properties the harness/servers read ------------------------

    @property
    def resource_version(self) -> int:
        return self._rv_counter.value

    @property
    def _rv(self) -> int:
        return self._rv_counter.value

    @property
    def cacher(self):
        """The meta shard's cacher — its metrics object is shared by
        every shard's tier, so hits/misses read cluster-wide."""
        return self.meta.cacher

    @property
    def list_direct_total(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for s in self.shards:
            for r, n in s.list_direct_total.items():
                out[r] = out.get(r, 0) + n
        return out

    @property
    def custom_kinds(self) -> dict[str, str]:
        return self.meta.custom_kinds

    @property
    def custom_cluster_scoped(self) -> set[str]:
        return self.meta.custom_cluster_scoped

    @property
    def _tracked_fields(self):
        return self.meta._tracked_fields

    def _table(self, resource: str) -> dict[str, dict]:
        """Single-shard resources return the live table; partitioned
        resources return a merged COPY (read-only uses: admission's
        config scans, server diagnostics)."""
        if resource not in self.partitioned_resources:
            return self.meta._table(resource)
        merged: dict[str, dict] = {}
        for s in self.shards:
            merged.update(s._table(resource))
        return merged

    def resource_for_kind(self, kind: str) -> str | None:
        return self.meta.resource_for_kind(kind)

    def is_cluster_scoped(self, resource: str) -> bool:
        return self.meta.is_cluster_scoped(resource)

    def kind_map(self) -> dict[str, str]:
        return self.meta.kind_map()

    # -- registration fans out (resource-routed at call time) --------------

    def register_subresource(self, resource: str, sub: str, handler) -> None:
        for s in self.shards:
            s.register_subresource(resource, sub, handler)

    def register_validator(self, resource: str, fn) -> None:
        for s in self.shards:
            s.register_validator(resource, fn)

    def register_mutator(self, resource: str, fn, *,
                         on: tuple[str, ...] = ("create", "update")) -> None:
        for s in self.shards:
            s.register_mutator(resource, fn, on=on)

    def _admit(self, resource: str, obj: dict, op: str = "create") -> None:
        """Mutators + validators without a write (the apiserver's
        ?dryRun=All path): registration fans out identically to every
        shard, so shard 0 is authoritative."""
        self.meta._admit(resource, obj, op)

    def add_event_sink(self, sink) -> None:
        for s in self.shards:
            s.add_event_sink(sink)

    def remove_event_sink(self, sink) -> None:
        for s in self.shards:
            s.remove_event_sink(sink)

    # -- CRUD (routed) -----------------------------------------------------

    async def create(self, resource: str, obj: Mapping, *,
                     _owned: bool = False, return_copy: bool = True):
        return await self._store_for_obj(resource, obj).create(
            resource, obj, _owned=_owned, return_copy=return_copy)

    async def get(self, resource: str, key: str) -> dict:
        return await self._store_for_key(resource, key).get(resource, key)

    async def update(self, resource: str, obj: Mapping, *,
                     _owned: bool = False, return_copy: bool = True):
        return await self._store_for_obj(resource, obj).update(
            resource, obj, _owned=_owned, return_copy=return_copy)

    async def guaranteed_update(self, resource: str, key: str,
                                mutate: Callable[[dict], dict | None],
                                max_retries: int = 16,
                                return_copy: bool = True):
        return await self._store_for_key(resource, key).guaranteed_update(
            resource, key, mutate, max_retries=max_retries,
            return_copy=return_copy)

    async def delete(self, resource: str, key: str, *,
                     uid: str | None = None) -> dict:
        return await self._store_for_key(resource, key).delete(
            resource, key, uid=uid)

    async def apply(self, resource: str, obj: Mapping, *,
                    field_manager: str, force: bool = False) -> dict:
        from kubernetes_tpu.store.apply import server_side_apply
        return await server_side_apply(
            self._store_for_obj(resource, obj), resource, obj,
            field_manager=field_manager, force=force)

    async def subresource(self, resource: str, key: str, sub: str,
                          body: Mapping) -> dict:
        return await self._store_for_key(resource, key).subresource(
            resource, key, sub, body)

    # -- LIST (merged or shard-scoped) -------------------------------------

    async def list(
        self,
        resource: str,
        namespace: str | None = None,
        selector: Selector | None = None,
        limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        *,
        resource_version: int | None = None,
        resource_version_match: str | None = None,
        copy: bool = True,
        shard: int | None = None,
    ) -> ListResult:
        """Merged LIST: fan out, merge-sort by key, re-apply the limit.
        Bit-identical to the single-store scan (same sort order, same
        continue semantics — keys are globally comparable and the
        shared RV counter makes pinned tokens mean one global snapshot
        on every shard). `shard=` scopes to one shard (the per-shard
        informer's relist path)."""
        kw: dict[str, Any] = dict(
            resource_version=resource_version,
            resource_version_match=resource_version_match, copy=copy)
        if resource not in self.partitioned_resources:
            return await self.meta.list(
                resource, namespace, selector, limit, continue_key,
                fields, **kw)
        if shard is not None:
            return await self.shards[self._check_shard(shard)].list(
                resource, namespace, selector, limit, continue_key,
                fields, **kw)
        # ATOMIC fan-out: per-shard list bodies contain no suspension
        # point (cacher.list / list_direct are sync-bodied), so plain
        # sequential awaits run in ONE loop tick — no write can
        # interleave, every shard serves the same global RV, and the
        # merged result is a true point-in-time snapshot. (gather()
        # would wrap each coroutine in a task and tick the loop between
        # shards, letting a write land mid-scan — an event a watcher
        # resuming from the merged RV would then never see.)
        results = [await s.list(resource, namespace, selector, limit,
                                continue_key, fields, **kw)
                   for s in self.shards]
        items = [it for lst in results for it in lst.items]
        items.sort(key=lambda o: _sort_key(o))
        rv = results[0].resource_version
        assert all(r.resource_version == rv for r in results), \
            "shard lists diverged within one loop tick"
        cont = None
        if limit and len(items) >= limit:
            items = items[:limit]
            # Pin the merged page at the (shared) serve RV — the same
            # token shape each shard's cacher emits, so later pages
            # roll every shard back to this one global snapshot.
            from kubernetes_tpu.store.cacher import make_continue
            cont = make_continue(rv, _sort_key(items[-1]))
        return ListResult(items=items, resource_version=rv, cont=cont)

    async def list_direct(self, resource: str, *args, **kw) -> ListResult:
        if resource not in self.partitioned_resources:
            return await self.meta.list_direct(resource, *args, **kw)
        # Sequential awaits of sync-bodied coroutines: atomic (see list).
        results = [await s.list_direct(resource, *args, **kw)
                   for s in self.shards]
        items = [it for lst in results for it in lst.items]
        items.sort(key=lambda o: _sort_key(o))
        return ListResult(
            items=items,
            resource_version=max(r.resource_version for r in results))

    # -- WATCH (per-shard or multiplexed) ----------------------------------

    async def watch(
        self,
        resource: str,
        resource_version: int = 0,
        namespace: str | None = None,
        selector: Selector | None = None,
        *,
        fields: Mapping[str, str] | None = None,
        bookmarks: bool = True,
        shard: int | None = None,
    ) -> AsyncIterator[Event]:
        """`shard=` consumes one shard's stream (per-shard informers —
        the scale path: S independent streams, S independent backfill
        rings). Without it, all shards multiplex into one stream so
        single-stream consumers (HTTP/gRPC wires, controllers) work
        unchanged; merged bookmarks advance at the MINIMUM of the
        per-shard bookmark RVs so a resume-from-bookmark can never skip
        an event still queued on a slower shard."""
        if resource not in self.partitioned_resources:
            return await self.meta.watch(
                resource, resource_version, namespace, selector,
                fields=fields, bookmarks=bookmarks)
        if shard is not None:
            return await self.shards[self._check_shard(shard)].watch(
                resource, resource_version, namespace, selector,
                fields=fields, bookmarks=bookmarks)
        # Sequential establishment (sync-bodied, one loop tick — see
        # list()): all S channels register before any write can land,
        # so an rv=0 "from now" merged watch has one consistent "now".
        watches = [await s.watch(resource, resource_version, namespace,
                                 selector, fields=fields, bookmarks=True)
                   for s in self.shards]
        return self._multiplex(watches, bookmarks)

    def _multiplex(self, watches: list, bookmarks: bool
                   ) -> AsyncIterator[Event]:
        return multiplex_watches(watches, bookmarks)

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        for s in self.shards:
            s.stop()

    def dump(self) -> str:
        """Merged snapshot checkpoint (tables unioned per resource)."""
        tables: dict[str, dict] = {}
        for s in self.shards:
            for r, t in s._tables.items():
                tables.setdefault(r, {}).update(t)
        return json.dumps({"rv": self.resource_version, "tables": tables})


async def multiplex_watches(watches: list, bookmarks: bool
                            ) -> AsyncIterator[Event]:
    """Fan S shard streams into one. Per-key ordering is exact (a
    key lives on one shard); cross-key ordering is arrival order
    with globally-valid RVs. Shared by the in-process facade above
    and the cross-process one (multiproc/client.py) — merged
    bookmarks advance at the MINIMUM of the per-shard bookmark RVs
    in both."""
    queue: asyncio.Queue = asyncio.Queue()
    marks = [0] * len(watches)
    sent_mark = 0
    _END = object()  # per-pump end-of-stream sentinel

    async def pump(i: int, w) -> None:
        try:
            async for ev in w:
                await queue.put((i, ev))
            await queue.put((i, _END))
        except Exception as e:
            await queue.put((i, e))

    tasks = [asyncio.ensure_future(pump(i, w))
             for i, w in enumerate(watches)]
    live = len(watches)
    try:
        while live:
            i, ev = await queue.get()
            if ev is _END:
                # A shard's stream ended (store stopped): the merged
                # stream ends when every shard's has — matching the
                # single-store watch, which terminates on stop().
                live -= 1
                continue
            if isinstance(ev, Exception):
                raise ev
            if ev.type == "BOOKMARK":
                marks[i] = max(marks[i], ev.rv)
                low = min(marks)
                if bookmarks and low > sent_mark:
                    sent_mark = low
                    yield Event("BOOKMARK", {"metadata": {
                        "resourceVersion": str(low)}}, low)
                continue
            marks[i] = max(marks[i], ev.rv)
            yield ev
    finally:
        for t in tasks:
            t.cancel()
        for w in watches:
            aclose = getattr(w, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass


def _sort_key(obj: Mapping) -> str:
    ns = (obj.get("metadata") or {}).get("namespace")
    name = (obj.get("metadata") or {}).get("name", "")
    return f"{ns}/{name}" if ns else name
