"""Watch-cache serving tier: RV-snapshotted reads in front of the mvcc core.

Parity target: `storage/cacher/cacher.go` + `watch_cache.go` (SURVEY §L0).
The reference apiserver never serves LISTs or watch backfill from storage —
a dedicated watch cache fans ONE store watch out to N clients and answers
LIST/initial-sync from RV-snapshotted memory. This module is that tier for
the TPU build: every committed mvcc event flows through `Cacher.ingest`
(the single fan-in point `MVCCStore._record` calls — the in-process analog
of the cacher's one etcd watch), which maintains, per resource:

- a **snapshot**: key → stored object (refs shared with the store — the
  watch-event immutability discipline already covers them), plus a sorted
  key list and a tracked-field exact-value index, so a kubelet-shaped
  LIST (`spec.nodeName=<me>`) is O(matching) instead of an O(table)
  scan-and-copy per agent — the cold-start relist storm of N agents
  becomes N reads of one shared snapshot;
- an **event ring**: the last `ring_capacity` events with their
  pre-update objects, so watch backfill ("start at RV") is a bisect +
  slice instead of a scan over the store's global history, and LIST *at
  any cached RV* is a roll-back of the current snapshot — which is what
  pins paginated `continue` tokens to one snapshot RV across pages on
  every wire.

RV-semantics contract (served identically on HTTP, KTPU and gRPC —
documented in the README architecture section):

- LIST with no resourceVersion: the current snapshot, stamped with the
  store RV (the cacher is sink-fed, so it is always exactly fresh —
  the reference's waitUntilFreshAndList degenerates to a direct read).
- LIST resourceVersion=N + resourceVersionMatch=Exact: the snapshot as
  of RV N, rolled back through the ring; RVs older than the ring raise
  Expired (410), the client relists — same contract as watch backfill.
- LIST resourceVersion=N (NotOlderThan / legacy): the current snapshot
  (always ≥ N here); N beyond the store RV is Invalid.
- continue tokens are `"<rv>:<last-key>"`: every page of one paginated
  LIST is served at the first page's snapshot RV, on whichever wire the
  token comes back on (gRPC needs no new proto field — the token IS the
  exact-RV transport).
- WATCH from RV: backfill from the ring when the RV is retained;
  otherwise the request falls back to the mvcc core's global replay
  (`watch_direct`), which enforces the 410 window — so expiry behavior
  is exactly the store's.

The r8 interned selector index (`_ResourceWatchers`) remains the live
dispatch structure; with the cacher active every event reaches it through
this tier's fan-in, and watch *establishment* (the backfill scan) no
longer touches the store's global event list. `KTPU_WATCH_CACHE=0`
disables the tier entirely (MVCCStore then routes straight to its direct
paths).
"""

from __future__ import annotations

import logging
from bisect import bisect_right, insort
from collections import OrderedDict
from typing import Any, Mapping

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.api.meta import deep_copy, namespace_of
from kubernetes_tpu.metrics.registry import WatchCacheMetrics

logger = logging.getLogger(__name__)

#: Per-resource replay-ring depth. Unlike the store's single global
#: event window, the ring is per resource: lease-heartbeat churn cannot
#: age pod backfill out of reach.
DEFAULT_RING_CAPACITY = 100_000

#: Rolled-back historical snapshots memoized per (resource, rv): a
#: paginated LIST's continue pages all hit the same entry, so a storm of
#: same-RV pages materializes the snapshot once.
_SNAPSHOT_MEMO_SLOTS = 4


def make_continue(rv: int, last_key: str) -> str:
    """Snapshot-pinned continue token: `"<rv>:<last-key>"`. Keys are
    `ns/name` / `name` (DNS-ish, never containing ':'), so the split is
    unambiguous; legacy bare-key tokens parse as unpinned."""
    return f"{rv}:{last_key}"


def parse_continue(token: str | None) -> tuple[int | None, str | None]:
    """(pinned rv | None, continue key | None). Accepts legacy bare-key
    tokens (no pin) and the `"<rv>:"` empty-key form gRPC clients use to
    request an exact-RV first page without a proto field."""
    if not token:
        return None, None
    head, sep, rest = token.partition(":")
    if sep and head.isdigit():
        return int(head), rest or None
    return None, token


class _ResourceCache:
    """One resource's snapshot + ring (watch_cache.go watchCache)."""

    __slots__ = ("resource", "snapshot", "keys", "ring", "ring_floor",
                 "tracked", "field_index", "_ring_key")

    def __init__(self, resource: str, store):
        self.resource = resource
        table = store._table(resource)
        # Shared refs with the store: the one cold table read per
        # resource (the "≤1 mvcc LIST per resource" seed).
        self.snapshot: dict[str, dict] = dict(table)
        self.keys: list[str] = sorted(table.keys())
        #: ring entries (rv, key, Event, prev_obj|None), rv-monotonic.
        self.ring: list[tuple[int, str, Any, dict | None]] = []
        #: every event with rv > ring_floor is retained in the ring;
        #: requests below it fall back to the mvcc core.
        self.ring_floor = store.resource_version
        self.tracked: tuple[str, ...] = \
            store._tracked_fields.get(resource, ())
        self.field_index: dict[str, dict[str, set[str]]] = \
            {f: {} for f in self.tracked}
        if self.tracked:
            from kubernetes_tpu.store.mvcc import _field_value
            for key, obj in table.items():
                for f in self.tracked:
                    self.field_index[f].setdefault(
                        _field_value(obj, f), set()).add(key)
        self._ring_key = (resource,)  # cached gauge label tuple


class Cacher:
    """The serving tier for one MVCCStore. Owned by the store
    (`MVCCStore.cacher`); `list()`/`watch()` are what the store's routed
    public methods delegate to when the tier is active."""

    def __init__(self, store, ring_capacity: int = DEFAULT_RING_CAPACITY):
        self._store = store
        self._ring_capacity = ring_capacity
        self._caches: dict[str, _ResourceCache] = {}
        self.metrics = WatchCacheMetrics()
        #: (resource, rv) -> (snapshot dict, sorted keys) LRU.
        self._memo: OrderedDict[tuple[str, int],
                                tuple[dict, list[str]]] = OrderedDict()

    # -- cache maintenance -------------------------------------------------

    def _cache(self, resource: str) -> _ResourceCache:
        c = self._caches.get(resource)
        if c is None:
            # Cold read of a never-written resource (writes seed their
            # resource in `ingest`): the table is empty or pre-seeded
            # state, one read, and the request is served from the tier
            # — not a miss; misses count requests handed to the core.
            c = self._caches[resource] = _ResourceCache(
                resource, self._store)
        return c

    def ingest(self, resource: str, ev) -> None:
        """Apply one committed event (called by `MVCCStore._record` for
        every write, before watch dispatch — the single fan-in). A
        resource's first write seeds its cache (the reference cacher
        runs from server start, so ring coverage spans the store's
        lifetime): the table copy already includes this event, so the
        seed absorbs it and coverage begins at `ev.rv`."""
        c = self._caches.get(resource)
        if c is None:
            self._caches[resource] = _ResourceCache(resource, self._store)
            return
        key = self._store._key(ev.object)
        prev = c.snapshot.get(key)
        if ev.type == "DELETED":
            if prev is not None:
                del c.snapshot[key]
                i = bisect_right(c.keys, key) - 1
                if 0 <= i < len(c.keys) and c.keys[i] == key:
                    del c.keys[i]
                self._index_move(c, key, prev, None)
        else:
            c.snapshot[key] = ev.object
            if prev is None:
                insort(c.keys, key)
            self._index_move(c, key, prev, ev.object)
        ring = c.ring
        ring.append((ev.rv, key, ev, prev))
        # Capped at the store's own event window too: a per-resource ring
        # must never serve an RV the store has contractually compacted
        # (the 410 window is API surface clients relist on).
        cap = min(self._ring_capacity, self._store._event_window)
        if len(ring) > cap:
            drop = len(ring) - cap
            c.ring_floor = ring[drop - 1][0]
            del ring[:drop]
        self.metrics.ring_len.set_key(c._ring_key, len(ring))

    @staticmethod
    def _index_move(c: _ResourceCache, key: str,
                    old: dict | None, new: dict | None) -> None:
        if not c.tracked:
            return
        from kubernetes_tpu.store.mvcc import _field_value
        for f in c.tracked:
            idx = c.field_index[f]
            ov = _field_value(old, f) if old is not None else None
            nv = _field_value(new, f) if new is not None else None
            if ov == nv:
                continue
            if ov is not None:
                bucket = idx.get(ov)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del idx[ov]
            if nv is not None:
                idx.setdefault(nv, set()).add(key)

    # -- historical snapshots ----------------------------------------------

    def _at(self, c: _ResourceCache,
            rv: int | None) -> tuple[dict, list[str]]:
        """(snapshot, sorted keys) as of `rv` (None = current). Rolls the
        current snapshot back through the ring's pre-update objects;
        memoized so paginated pages at one RV share the materialization.
        Caller has already range-checked rv against the ring floor."""
        if rv is None or rv >= self._store.resource_version:
            return c.snapshot, c.keys
        memo_key = (c.resource, rv)
        hit = self._memo.get(memo_key)
        if hit is not None:
            self._memo.move_to_end(memo_key)
            return hit
        snap = dict(c.snapshot)
        for erv, key, ev, prev in reversed(c.ring):
            if erv <= rv:
                break
            if prev is None:
                snap.pop(key, None)     # undo ADDED
            else:
                snap[key] = prev        # undo MODIFIED / DELETED
        keys = sorted(snap)
        self._memo[memo_key] = (snap, keys)
        while len(self._memo) > _SNAPSHOT_MEMO_SLOTS:
            self._memo.popitem(last=False)
        return snap, keys

    # -- LIST --------------------------------------------------------------

    async def list(
        self,
        resource: str,
        namespace: str | None = None,
        selector: Selector | None = None,
        limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        resource_version: int | None = None,
        exact: bool = False,
        copy: bool = True,
    ):
        """LIST from the snapshot — bit-identical to the mvcc scan at the
        same RV (same sort order, same filters, same paging), without
        touching the store table. `exact` pins to the historical snapshot
        at `resource_version`; otherwise any cached RV means "current".
        `copy=False` skips the per-item deep copy for callers that only
        encode the result (the serving wires)."""
        from kubernetes_tpu.store.mvcc import (
            Expired,
            Invalid,
            ListResult,
            _fields_match,
        )
        c = self._cache(resource)
        cur_rv = self._store.resource_version
        target: int | None = None
        if resource_version:
            if resource_version > cur_rv:
                raise Invalid(
                    f"resourceVersion {resource_version} is ahead of the "
                    f"store (current: {cur_rv})")
            if exact and resource_version != cur_rv:
                if resource_version < c.ring_floor:
                    raise Expired(
                        f"resourceVersion {resource_version} is too old "
                        f"(oldest retained: {c.ring_floor + 1})")
                target = resource_version
        self.metrics.hits.inc()
        snap, keys = self._at(c, target)
        out_rv = target if target is not None else cur_rv

        # Tracked-field exact-value candidates: the kubelet LIST shape
        # (`spec.nodeName=<me>`) reads its own keys off the index instead
        # of scanning the table — only on the live snapshot (historical
        # rollbacks carry no index and just scan).
        scan_keys = keys
        rest_fields = fields
        if fields and target is None:
            f = next((f for f in fields if f in c.tracked), None)
            if f is not None:
                scan_keys = sorted(c.field_index[f].get(fields[f], ()))
        if continue_key:
            scan_keys = scan_keys[bisect_right(scan_keys, continue_key):]

        has_sel = selector is not None and selector.requirements
        items: list[dict] = []
        last_key = None
        for k in scan_keys:
            obj = snap[k]
            if namespace and namespace_of(obj) != namespace:
                continue
            if has_sel and not selector.matches(
                    obj.get("metadata", {}).get("labels")):
                continue
            if rest_fields and not _fields_match(rest_fields, obj):
                continue
            items.append(deep_copy(obj) if copy else obj)
            last_key = k
            if limit and len(items) >= limit:
                break
        cont = None
        if limit and len(items) >= limit and last_key is not None:
            cont = make_continue(out_rv, last_key)
        return ListResult(items=items, resource_version=out_rv, cont=cont)

    # -- WATCH establishment -----------------------------------------------

    async def watch(
        self,
        resource: str,
        resource_version: int = 0,
        namespace: str | None = None,
        selector: Selector | None = None,
        *,
        fields: Mapping[str, str] | None = None,
        bookmarks: bool = True,
    ):
        """Watch with ring-served backfill: events after `resource_version`
        come from this resource's ring (bisect + slice) instead of a scan
        over the store's global history. RVs older than the ring fall back
        to the mvcc core's replay path, which owns the 410 contract. Live
        dispatch (the interned selector index) is shared with the core."""
        from kubernetes_tpu.store.mvcc import Expired
        c = self._cache(resource)
        if resource_version and resource_version > self._store.resource_version:
            # A future RV means the client's view predates a store
            # restart (RV counter regressed): resuming would silently
            # drop every event until the counter catches up. Expired
            # forces the relist that actually recovers.
            raise Expired(
                f"resourceVersion {resource_version} is ahead of the "
                f"store (current: {self._store.resource_version}); relist")
        if resource_version and resource_version < c.ring_floor:
            self.metrics.misses.inc()
            return await self._store.watch_direct(
                resource, resource_version, namespace, selector,
                fields=fields, bookmarks=bookmarks)
        self.metrics.hits.inc()
        replay = []
        if resource_version:
            ring = c.ring
            i = bisect_right(ring, resource_version,
                             key=lambda e: e[0])
            replay = [e[2] for e in ring[i:]]
        return self._store._open_watch(
            resource, resource_version, namespace, selector,
            fields=fields, bookmarks=bookmarks, replay=replay)
