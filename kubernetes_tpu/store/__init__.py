"""The object store: the hub of the control plane (SURVEY §1: everything is
hub-and-spoke through the store; components communicate only by reading/writing
objects and watching for changes)."""

from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Event,
    Expired,
    Invalid,
    ListResult,
    MVCCStore,
    NotFound,
    StoreError,
    binding_subresource,
    new_cluster_store,
)
from kubernetes_tpu.store.sharded import (
    PARTITIONED_RESOURCES,
    ShardedNodeStore,
    control_plane_shards,
    shard_of,
)
from kubernetes_tpu.store.apply import ApplyConflict, server_side_apply
from kubernetes_tpu.store.durable import (
    DurabilityManager,
    WriteAheadLog,
    recover_store,
)
from kubernetes_tpu.store.validation import install_core_validation

__all__ = [
    "ApplyConflict",
    "server_side_apply",
    "DurabilityManager",
    "WriteAheadLog",
    "recover_store",
    "AlreadyExists",
    "Conflict",
    "Event",
    "Expired",
    "Invalid",
    "ListResult",
    "MVCCStore",
    "NotFound",
    "StoreError",
    "binding_subresource",
    "new_cluster_store",
    "install_core_validation",
    "PARTITIONED_RESOURCES",
    "ShardedNodeStore",
    "control_plane_shards",
    "shard_of",
]
