"""Store durability: WAL + periodic snapshot + crash recovery.

Parity target (SURVEY §5.4 "Build: store WAL+snapshot"): etcd's raft
log + snapshot cycle, scaled to the in-process store. Every committed
event appends one line to an append-only log BEFORE watch dispatch; a
periodic (or size-triggered) snapshot writes the full `dump()` and
starts a fresh log segment; recovery loads the newest snapshot and
replays its segment's tail.

Files in the durability directory:
    snapshot-<rv>.json      full store state as of <rv>
    wal-<rv>.log            events with rv > <rv>, one JSON line each:
                            [rv, TYPE, resource, object]

Semantics proved by tests/test_durability.py:
- recovered stores keep RESOURCEVERSION CONTINUITY: the next write gets
  the next rv, uids survive, CAS preconditions keep working;
- watches resume across restart: replayed WAL events re-seed the watch
  ring, so `watch(resource_version=rv_before_crash)` streams the writes
  the watcher missed; rv older than the newest snapshot → 410 Expired
  (the relist signal), exactly the informer contract;
- fsync policy: "always" (fsync per commit — the reference's default
  etcd posture) or "batch" (fsync on flush ticks — group commit).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import time
from typing import Iterable

from kubernetes_tpu.store.mvcc import Event, MVCCStore

logger = logging.getLogger(__name__)

_SNAP_RE = re.compile(r"^snapshot-(\d+)\.json$")
_WAL_RE = re.compile(r"^wal-(\d+)\.log$")


class WriteAheadLog:
    """Append-only event log attached to a store via add_event_sink."""

    def __init__(self, store: MVCCStore, directory: str, *,
                 fsync: str | None = None, metrics=None):
        from kubernetes_tpu.metrics.registry import DurabilityMetrics
        from kubernetes_tpu.utils import flags
        self.store = store
        self.dir = directory
        #: fsync policy: explicit argument wins, else KTPU_WAL_FSYNC
        #: ("batch" group commit / "always" per-commit).
        self.fsync = fsync or flags.get("KTPU_WAL_FSYNC")
        self.metrics = metrics or DurabilityMetrics()
        os.makedirs(directory, exist_ok=True)
        self._base_rv = store.resource_version
        self._fh = open(self._wal_path(self._base_rv), "a",
                        encoding="utf-8")
        self._dirty = False
        #: set on the first append/flush failure: the log stops growing
        #: (a HOLE in the log would be worse than a shorter durable
        #: prefix) and the health flag surfaces the degradation.
        self.broken = False
        #: KTPU_WAL=0 structural kill switch: snapshot-only durability
        #: (the r16 shape) — the sink never attaches, so commits cost
        #: zero and recovery replays nothing between snapshots.
        self.enabled = bool(flags.get("KTPU_WAL"))
        if self.enabled:
            store.add_event_sink(self._on_event)

    def _wal_path(self, base_rv: int) -> str:
        return os.path.join(self.dir, f"wal-{base_rv}.log")

    def _snap_path(self, rv: int) -> str:
        return os.path.join(self.dir, f"snapshot-{rv}.json")

    # -- appending ---------------------------------------------------------

    def _on_event(self, resource: str, ev: Event) -> None:
        if ev.type == "BOOKMARK" or self.broken:
            return
        record = [ev.rv, ev.type, resource, ev.object]
        if ev.prev_labels is not None or ev.prev_fields is not None:
            # Label/field-transition info survives replay, so selector and
            # field watches resuming across restart still see synthesized
            # ADDED/DELETED transitions (cacher prevObject semantics).
            record.append(ev.prev_labels)
            if ev.prev_fields is not None:
                record.append(ev.prev_fields)
        try:
            self._fh.write(json.dumps(record, separators=(",", ":"))
                           + "\n")
            self.metrics.appends.inc()
            if self.fsync == "always":
                # Synchronous durability (the etcd posture): the commit
                # is not acknowledged cheaper than the disk. "batch"
                # trades a flush-interval durability window for keeping
                # fsync off the commit path.
                self._fh.flush()
                t0 = time.perf_counter()
                os.fsync(self._fh.fileno())
                self.metrics.fsync_seconds.observe(
                    time.perf_counter() - t0)
            else:
                self._dirty = True
        except (OSError, ValueError, TypeError):
            # TypeError: unserializable object — skipping just one record
            # would punch a silent hole in the log, so freeze instead.
            self.broken = True
            logger.exception(
                "WAL append failed; log is now FROZEN at a consistent "
                "prefix (durability degraded, store stays live)")

    def flush(self) -> None:
        """Group commit (fsync="batch"), synchronous: python buffer → OS
        → disk. Safe only from the event loop (TextIOWrapper is not
        thread-safe against concurrent writes)."""
        if self._dirty and not self.broken:
            try:
                self._fh.flush()
                t0 = time.perf_counter()
                os.fsync(self._fh.fileno())
                self.metrics.fsync_seconds.observe(
                    time.perf_counter() - t0)
                self._dirty = False
            except (OSError, ValueError):
                self.broken = True
                logger.exception("WAL flush failed; log FROZEN")

    def flush_to_os(self) -> int | None:
        """Loop-side half of the threaded group commit: drain the
        TextIOWrapper buffer (must happen on the loop — concurrent
        write()/flush() on a text file corrupts it) and return the fd
        for the caller to fsync OFF the loop. None = nothing to sync."""
        if not self._dirty or self.broken:
            return None
        try:
            self._fh.flush()
            self._dirty = False
            return self._fh.fileno()
        except (OSError, ValueError):
            self.broken = True
            logger.exception("WAL flush failed; log FROZEN")
            return None

    # -- snapshot + compaction --------------------------------------------

    def snapshot(self) -> int:
        """Write a full-state snapshot at the current rv, rotate to a
        fresh WAL segment, and delete obsolete files. Returns the rv."""
        data, rv = self.begin_snapshot()
        self.write_snapshot(data, rv)
        return rv

    def begin_snapshot(self) -> tuple[str, int]:
        """Phase A, ATOMIC ON THE EVENT LOOP (no awaits): capture state
        and rotate the segment in one step, so no event can land in the
        old segment after the captured rv (an event there would be
        skipped by recovery once the new snapshot exists) and none can
        hit a closed file handle."""
        rv = self.store.resource_version
        data = self.store.dump()
        self.flush()
        self._fh.close()
        self._base_rv = rv
        self._fh = open(self._wal_path(rv), "a", encoding="utf-8")
        return data, rv

    def write_snapshot(self, data: str, rv: int) -> None:
        """Phase B, thread-safe (no store access): persist the captured
        state and only THEN compact older files — a crash in between
        leaves old snapshot + both segments, which recovery handles."""
        tmp = self._snap_path(rv) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path(rv))
        self._gc(keep_rv=rv)

    def _gc(self, keep_rv: int) -> None:
        for fn in os.listdir(self.dir):
            m = _SNAP_RE.match(fn) or _WAL_RE.match(fn)
            # A crash between the tmp write and os.replace leaves a
            # .tmp orphan; recovery never reads one (the name doesn't
            # match), so reclaim it with the other obsolete files.
            if fn.endswith(".tmp") or (m and int(m.group(1)) < keep_rv):
                try:
                    os.unlink(os.path.join(self.dir, fn))
                except OSError:
                    pass

    def close(self) -> None:
        self.store.remove_event_sink(self._on_event)
        self.flush()
        self._fh.close()


class DurabilityManager:
    """Owns the WAL + the periodic flush/snapshot loop for one store."""

    def __init__(self, store: MVCCStore, directory: str, *,
                 fsync: str | None = None, flush_interval_s: float = 0.05,
                 snapshot_interval_s: float = 30.0,
                 snapshot_every_events: int = 100_000,
                 metrics=None):
        self.store = store
        self.wal = WriteAheadLog(store, directory, fsync=fsync,
                                 metrics=metrics)
        self.flush_interval_s = flush_interval_s
        self.snapshot_interval_s = snapshot_interval_s
        self.snapshot_every_events = snapshot_every_events
        self._task: asyncio.Task | None = None
        #: in-flight background write_snapshot (an executor future).
        #: Cancelling _task mid-await does NOT stop the worker thread,
        #: so stop() awaits this before its own final snapshot — two
        #: writers interleaving segment rotation was the crash-corruption
        #: window tests/test_durability.py pins closed.
        self._snap_inflight = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        import time
        last_snap = time.monotonic()
        try:
            while True:
                await asyncio.sleep(self.flush_interval_s)
                # Buffer drain on the loop (text I/O is not thread-safe
                # against concurrent writes); only the fsync goes to a
                # worker thread. Durability window in "batch" mode is
                # one flush interval.
                fd = self.wal.flush_to_os()
                if fd is not None:
                    try:
                        t0 = time.perf_counter()
                        await asyncio.to_thread(os.fsync, fd)
                        self.wal.metrics.fsync_seconds.observe(
                            time.perf_counter() - t0)
                    except OSError:
                        # Genuine sync failure (nothing rotates this fd
                        # concurrently — snapshot rotation runs later in
                        # THIS task): records the store already
                        # acknowledged may not be on disk → freeze, same
                        # contract as an append failure.
                        self.wal.broken = True
                        logger.exception(
                            "WAL fsync failed; log FROZEN")
                now = time.monotonic()
                log_span = self.store.resource_version - self.wal._base_rv
                if log_span > 0 and (
                        now - last_snap >= self.snapshot_interval_s
                        or log_span >= self.snapshot_every_events):
                    # Capture + rotate atomically on the loop; the disk
                    # write runs in a worker thread. The executor future
                    # is kept (not to_thread) so stop() can await the
                    # thread even after cancelling this task. Idle
                    # clusters (log_span 0) skip re-snapshotting
                    # identical state.
                    data, rv = self.wal.begin_snapshot()
                    self._snap_inflight = \
                        asyncio.get_running_loop().run_in_executor(
                            None, self.wal.write_snapshot, data, rv)
                    # shield: cancelling THIS task must detach the
                    # awaiter, not cancel the future — a cancelled
                    # wrapper is unawaitable while its worker thread
                    # still writes, which is exactly what stop() needs
                    # to wait out.
                    await asyncio.shield(self._snap_inflight)
                    # Cleared only AFTER a normal completion: a
                    # cancellation mid-await leaves the reference for
                    # stop() to drain.
                    self._snap_inflight = None
                    last_snap = now
        except asyncio.CancelledError:
            return

    async def stop(self, *, final_snapshot: bool = False) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # Serialize against a background write_snapshot whose worker
        # thread survived the cancellation: letting the final snapshot
        # below run concurrently with it interleaves two segment
        # rotations + two _gc passes (the mid-snapshot corruption the
        # crash-atomicity satellite exists to rule out).
        inflight, self._snap_inflight = self._snap_inflight, None
        if inflight is not None:
            try:
                await inflight
            except Exception:
                logger.exception(
                    "background snapshot failed during stop")
        if final_snapshot:
            self.wal.snapshot()
        self.wal.close()


def _latest(directory: str, pattern: re.Pattern) -> list[tuple[int, str]]:
    out = []
    for fn in os.listdir(directory):
        m = pattern.match(fn)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, fn)))
    return sorted(out)


def _iter_wal(path: str) -> Iterable[
        tuple[int, str, str, dict, dict | None, dict | None]]:
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                rv, ev_type, resource, obj = rec[:4]
                prev_labels = rec[4] if len(rec) > 4 else None
                prev_fields = rec[5] if len(rec) > 5 else None
            except (json.JSONDecodeError, ValueError, IndexError):
                # Torn tail write from a crash: everything before it is
                # durable; the torn record never committed to callers
                # (fsync order) — stop replay here, like etcd.
                logger.warning("WAL %s: torn record, truncating replay",
                               path)
                return
            yield int(rv), ev_type, resource, obj, prev_labels, prev_fields


def recover_store(directory: str,
                  factory=None, *, rv_source=None,
                  metrics=None) -> MVCCStore:
    """Rebuild a store from the newest snapshot + its WAL segment tail.

    `factory` (optional) builds the empty store when there is no
    snapshot — pass `new_cluster_store` to get validation/subresources
    installed; recovery with a snapshot uses MVCCStore.load then the
    caller re-installs hooks (install_core_validation is idempotent).
    `rv_source` threads a shared RV counter into the rebuilt store (the
    multi-process shard restart path: recovery must never regress the
    live global counter). `metrics` (DurabilityMetrics) counts replayed
    events into wal_replay_entries_total.

    Replayed events re-enter the watch ring: a watcher resuming with an
    rv newer than the snapshot base sees exactly the missed events; an
    older rv raises Expired (410) → relist, the informer contract.
    """
    from kubernetes_tpu.store.mvcc import binding_subresource
    snaps = _latest(directory, _SNAP_RE)
    if snaps:
        snap_rv, snap_path = snaps[-1]
        with open(snap_path, encoding="utf-8") as f:
            store = MVCCStore.load(f.read(), rv_source=rv_source)
    else:
        snap_rv = 0
        if factory is not None:
            store = factory()
        else:
            store = MVCCStore(rv_source=rv_source)
    # Core subresources survive recovery (new_cluster_store parity).
    store.register_subresource("pods", "binding", binding_subresource)
    # Replay WAL segments based at or after the snapshot (older segments
    # were compacted; a crash between snapshot and _gc leaves both).
    for base_rv, path in _latest(directory, _WAL_RE):
        if base_rv < snap_rv:
            continue
        for rv, ev_type, resource, obj, prev_labels, prev_fields \
                in _iter_wal(path):
            if rv <= snap_rv:
                continue  # already inside the snapshot
            table = store._table(resource)
            key = store._key(obj)
            if ev_type == "DELETED":
                table.pop(key, None)
            else:
                table[key] = obj
            store._rv = max(store.resource_version, rv)
            if metrics is not None:
                metrics.replayed.inc()
            store._events.append(
                (resource, Event(ev_type, obj, rv, prev_labels,
                                 prev_fields)))
    # Watch-resume window: everything since the snapshot is replayable;
    # anything older is compacted (410 Expired → relist).
    store._first_retained_rv = snap_rv + 1
    return store
