"""Object validation — the admission-time subset that scheduling correctness
depends on.

Parity target: pkg/apis/core/validation/validation.go (`ValidatePod`,
`ValidatePodSpec`, `ValidateNode`) — trimmed to the invariants the rest of this
framework relies on (full field-by-field validation is cosmetic for a
scheduler-centric control plane; extend as controllers grow).
"""

from __future__ import annotations

import re

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.store.mvcc import Invalid

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _validate_meta(obj: dict, kind: str, namespaced: bool) -> None:
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    if not name or len(name) > 253 or not _DNS1123.match(name.replace(".", "-")):
        raise Invalid(f"{kind}: invalid metadata.name {name!r}")
    if namespaced and not meta.get("namespace"):
        raise Invalid(f"{kind}: metadata.namespace is required")


def validate_pod(pod: dict) -> None:
    _validate_meta(pod, "Pod", namespaced=True)
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or []
    if not containers:
        raise Invalid("Pod: spec.containers must be non-empty")
    names = set()
    for c in containers:
        cname = c.get("name", "")
        if not cname:
            raise Invalid("Pod: container name is required")
        if cname in names:
            raise Invalid(f"Pod: duplicate container name {cname!r}")
        names.add(cname)
        res = c.get("resources") or {}
        req = res.get("requests") or {}
        lim = res.get("limits") or {}
        for rl in (req, lim):
            for rname, v in rl.items():
                try:
                    q = parse_quantity(v)
                except ValueError as e:
                    raise Invalid(f"Pod: bad quantity for {rname}: {e}") from e
                if q < 0:
                    raise Invalid(f"Pod: negative quantity for {rname}")
        for rname, v in req.items():
            if rname in lim and parse_quantity(v) > parse_quantity(lim[rname]):
                raise Invalid(f"Pod: request for {rname} exceeds limit")
    for gate in spec.get("schedulingGates") or []:
        if not gate.get("name"):
            raise Invalid("Pod: schedulingGates[].name is required")
    prio = spec.get("priority")
    if prio is not None and not isinstance(prio, int):
        raise Invalid("Pod: spec.priority must be an integer")


def validate_node(node: dict) -> None:
    _validate_meta(node, "Node", namespaced=False)
    for taint in node.get("spec", {}).get("taints") or []:
        if not taint.get("key"):
            raise Invalid("Node: taint key is required")
        if taint.get("effect") not in ("NoSchedule", "PreferNoSchedule", "NoExecute"):
            raise Invalid(f"Node: invalid taint effect {taint.get('effect')!r}")
    for rname, v in node.get("status", {}).get("allocatable", {}).items():
        try:
            parse_quantity(v)
        except ValueError as e:
            raise Invalid(f"Node: bad allocatable {rname}: {e}") from e


def default_pod(pod: dict) -> None:
    """Defaulting (pkg/apis/core/v1/defaults.go subset): schedulerName,
    restartPolicy, phase, toleration defaults for not-ready/unreachable are
    added by admission in the reference (defaulttolerationseconds plugin)."""
    spec = pod.setdefault("spec", {})
    spec.setdefault("schedulerName", "default-scheduler")
    spec.setdefault("restartPolicy", "Always")
    pod.setdefault("status", {}).setdefault("phase", "Pending")
    tolerations = spec.setdefault("tolerations", [])
    have = {t.get("key") for t in tolerations}
    for key in ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable"):
        if key not in have:
            tolerations.append({
                "key": key, "operator": "Exists", "effect": "NoExecute",
                "tolerationSeconds": 300,
            })


DEFAULT_CLASS_ANN = "storageclass.kubernetes.io/is-default-class"


def validate_admission_policy(policy: dict) -> None:
    """ValidatingAdmissionPolicy: every expression must COMPILE inside
    the sandbox grammar at write time (the reference typechecks CEL at
    admission of the policy object, not at first use)."""
    _validate_meta(policy, "ValidatingAdmissionPolicy", namespaced=False)
    spec = policy.get("spec") or {}
    if spec.get("failurePolicy") not in (None, "Fail", "Ignore"):
        raise Invalid("ValidatingAdmissionPolicy: failurePolicy must be "
                      "Fail or Ignore")
    validations = spec.get("validations")
    if not validations:
        raise Invalid("ValidatingAdmissionPolicy: spec.validations must "
                      "be non-empty")
    from kubernetes_tpu.policy.expr import (
        ExpressionError,
        compile_expression,
    )

    def check(source: str, where: str) -> None:
        try:
            compile_expression(source)
        except ExpressionError as e:
            raise Invalid(
                f"ValidatingAdmissionPolicy: {where}: {e}") from e

    for i, v in enumerate(validations):
        check(v.get("expression", ""), f"spec.validations[{i}]")
        if v.get("messageExpression"):
            check(v["messageExpression"],
                  f"spec.validations[{i}].messageExpression")
    for i, c in enumerate(spec.get("matchConditions") or []):
        if not c.get("name"):
            raise Invalid(f"ValidatingAdmissionPolicy: "
                          f"spec.matchConditions[{i}].name is required")
        check(c.get("expression", ""), f"spec.matchConditions[{i}]")
    for i, var in enumerate(spec.get("variables") or []):
        if not var.get("name"):
            raise Invalid(f"ValidatingAdmissionPolicy: "
                          f"spec.variables[{i}].name is required")
        check(var.get("expression", ""), f"spec.variables[{i}]")
    for i, a in enumerate(spec.get("auditAnnotations") or []):
        if not a.get("key"):
            raise Invalid(f"ValidatingAdmissionPolicy: "
                          f"spec.auditAnnotations[{i}].key is required")
        check(a.get("valueExpression", ""),
              f"spec.auditAnnotations[{i}].valueExpression")


def validate_vap_binding(binding: dict) -> None:
    _validate_meta(binding, "ValidatingAdmissionPolicyBinding",
                   namespaced=False)
    if not (binding.get("spec") or {}).get("policyName"):
        raise Invalid("ValidatingAdmissionPolicyBinding: spec.policyName "
                      "is required")


def install_core_validation(store) -> None:
    store.register_mutator("pods", default_pod)
    store.register_validator("pods", validate_pod)
    store.register_validator("nodes", validate_node)
    store.register_validator("validatingadmissionpolicies",
                             validate_admission_policy)
    store.register_validator("validatingadmissionpolicybindings",
                             validate_vap_binding)

    def default_storage_class(pvc: dict) -> None:
        """DefaultStorageClass admission (plugin/pkg/admission/storage/
        storageclass/setdefault): PVCs with a nil class get the cluster's
        default StorageClass at create time. An explicit "" means "no
        class" and disables defaulting; ties between multiple defaults go
        to the newest by creationTimestamp."""
        spec = pvc.setdefault("spec", {})
        if spec.get("storageClassName") is not None:
            return
        defaults = [
            sc for sc in store._table("storageclasses").values()
            if (sc.get("metadata", {}).get("annotations") or {})
            .get(DEFAULT_CLASS_ANN) == "true"
        ]
        if not defaults:
            return
        # Newest creationTimestamp wins; ties break on smallest name
        # (the reference sorts newest-first, then Name ascending).
        latest = max(sc["metadata"].get("creationTimestamp") or ""
                     for sc in defaults)
        newest = min(
            (sc for sc in defaults
             if (sc["metadata"].get("creationTimestamp") or "") == latest),
            key=lambda sc: sc["metadata"]["name"])
        spec["storageClassName"] = newest["metadata"]["name"]

    store.register_mutator("persistentvolumeclaims", default_storage_class,
                           on=("create",))
