"""In-memory MVCC object store with etcd-compatible semantics.

Capability parity with the reference's storage stack
(staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go: `Create`, `Get`,
`GuaranteedUpdate` (CAS loop on ResourceVersion), `Delete`, `List`;
etcd3/watcher.go + storage/cacher/cacher.go: watch streams, bookmarks,
"410 Gone" on compacted revisions). etcd itself is out of scope — it is an
external dependency of the reference too; what every component actually
depends on is *these semantics*:

- A single monotonically-increasing **ResourceVersion** across the whole store.
- Every write bumps it; objects carry the RV of their last write.
- LIST returns a consistent snapshot + the store RV to resume watching from.
- WATCH(rv) replays every event after rv in order, then streams live events,
  with periodic **bookmark** events carrying the current RV.
- WATCH from an RV older than the retained window ⇒ **Expired** (410 Gone),
  client must relist (client-go Reflector handles this).
- **GuaranteedUpdate** = optimistic-concurrency read-modify-write retried on
  conflict — the primitive Binding, status updates, and controllers build on.

Concurrency model: single asyncio loop owns all state (the TPU-build analog of
the reference's "one mutex around cacheImpl" discipline, see SURVEY §5.2); the
public API is async and must be called from that loop. A thread-safe facade for
the scheduler's compiled hot path lives in kubernetes_tpu/client.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator, Awaitable, Callable, Mapping

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.utils import flags
from kubernetes_tpu.metrics.registry import WatchMetrics
from kubernetes_tpu.api.meta import (
    deep_copy,
    name_of,
    namespace_of,
    new_uid,
    set_creation_timestamp,
)

logger = logging.getLogger(__name__)


class StoreError(Exception):
    status = 500


class NotFound(StoreError):
    status = 404


class AlreadyExists(StoreError):
    status = 409


class Conflict(StoreError):
    """ResourceVersion precondition failed (optimistic concurrency)."""
    status = 409


class Expired(StoreError):
    """Requested RV has been compacted out of the event window (410 Gone)."""
    status = 410


class Invalid(StoreError):
    status = 422


@dataclass
class Event:
    """watch.Event (apimachinery pkg/watch): ADDED/MODIFIED/DELETED/BOOKMARK.

    `prev_labels` carries the pre-update labels (not on the wire) so selector
    watchers can be told when an object transitions *out* of their selector
    set — the reference cacher synthesizes a DELETED event in that case
    (cacher.go updateResourceVersion/dispatchEvent prevObject handling).
    """
    type: str
    object: dict
    rv: int
    prev_labels: dict | None = None
    #: pre-update values of registered field-selector fields (e.g. pods
    #: spec.nodeName) so field watchers see enter/leave transitions the
    #: same way label watchers do.
    prev_fields: dict | None = None

    def to_wire(self) -> dict:
        return {"type": self.type, "object": self.object}


def _synth(ev: Event, ev_type: str) -> Event:
    """Synthesized enter/leave twin of `ev` (same object, same rv, new
    type). `_wire_src` links it back so the wire encoders reuse the one
    per-codec encoding of the shared object (encode-once fan-out): a
    MODIFIED event synthesized into ADDED for a whole selector group
    costs zero extra serializations."""
    twin = Event(ev_type, ev.object, ev.rv, ev.prev_labels, ev.prev_fields)
    twin._wire_src = ev
    return twin


@dataclass
class _WatchChannel:
    queue: asyncio.Queue
    resource: str
    namespace: str | None
    selector: Selector | None
    fields: Mapping[str, str] | None = None
    closed: bool = False
    #: index slot this channel registered under (see _ResourceWatchers):
    #: ("plain",) | ("field", f, v) | ("sel", sig) | ("residue",)
    slot: tuple | None = None


def _selector_sig(sel: Selector) -> tuple:
    """Intern key for a selector: order-insensitive requirement tuple, so
    N informers sharing one selector (however constructed) land in one
    dispatch group — the `_term_sig` interning idiom from ops/affinity."""
    return tuple(sorted(
        (r.key, r.op, tuple(r.values)) for r in sel.requirements))


class _ResourceWatchers:
    """Interned watcher index for ONE resource — the watch cache's
    per-selector indexed-trigger analog (cacher.go triggerFunc +
    watchCache indexed watchers, SURVEY §3.3). Dispatch cost is
    O(matching watchers + distinct selector signatures), not O(watchers):

    - `plain`: no selector, no fields — every event matches (modulo
      namespace); no predicate evaluation at all.
    - `fields`: tracked-field exact-value reverse map {field → {value →
      [channels]}} — a bind event routes to exactly the one agent bucket
      its spec.nodeName names (plus the pre-value bucket on MODIFIED so
      enter/leave transitions reach the side the object left).
    - `groups`: label-selector interning by signature — N watchers
      sharing a selector pay ONE predicate evaluation per event and
      share ONE synthesized enter/leave Event (and its wire encoding).
    - `residue`: watchers on untracked fields — the full joint predicate
      per event, exactly the pre-index behavior.
    """

    __slots__ = ("plain", "fields", "groups", "residue")

    def __init__(self):
        self.plain: list[_WatchChannel] = []
        self.fields: dict[str, dict[str, list[_WatchChannel]]] = {}
        self.groups: dict[tuple, tuple[Selector, list[_WatchChannel]]] = {}
        self.residue: list[_WatchChannel] = []

    def empty(self) -> bool:
        return not (self.plain or self.fields or self.groups
                    or self.residue)


def _field_value(obj: Mapping, dotted: str):
    """Walk `spec.nodeName`-style paths; missing → '' (the apiserver
    treats absent fields as empty strings in field selectors)."""
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, Mapping):
            return ""
        cur = cur.get(part)
        if cur is None:
            return ""
    return cur if isinstance(cur, str) else str(cur)


def _fields_match(fields: Mapping[str, str], obj: Mapping) -> bool:
    return all(_field_value(obj, f) == v for f, v in fields.items())


@dataclass
class ListResult:
    items: list[dict]
    resource_version: int
    #: snapshot-pinned continue token (`"<rv>:<last-key>"`) when a
    #: limited page came off the watch-cache tier — every later page of
    #: the same LIST is served at this page's snapshot RV, on any wire.
    cont: str | None = None


# Retain this many events for watch replay before declaring RVs expired.
# (etcd compaction analog; sized so a relisting client never loses events
# under scheduler_perf churn.)
DEFAULT_EVENT_WINDOW = 200_000
BOOKMARK_INTERVAL_S = 5.0

# Debug guard (KTPU_DEBUG_FREEZE=1, enabled in tests): stored objects — which
# watch events share — are recursively frozen, so a handler that mutates a
# delivered object fails loudly instead of silently corrupting the source of
# truth with no RV bump. deep_copy() rebuilds plain dicts/lists, so copies
# handed to callers stay mutable.
_DEBUG_FREEZE = flags.get("KTPU_DEBUG_FREEZE")


def _frozen(*_a, **_k):
    raise TypeError(
        "attempt to mutate a stored/watch-delivered object; informer handlers "
        "must treat delivered objects as immutable (copy before modifying)")


class FrozenDict(dict):
    __setitem__ = __delitem__ = __ior__ = _frozen
    setdefault = update = pop = popitem = clear = _frozen


class FrozenList(list):
    __setitem__ = __delitem__ = __iadd__ = __imul__ = _frozen
    append = extend = insert = pop = remove = clear = sort = reverse = _frozen


def deep_freeze(obj):
    if isinstance(obj, dict):
        return FrozenDict((k, deep_freeze(v)) for k, v in obj.items())
    if isinstance(obj, list):
        return FrozenList(deep_freeze(v) for v in obj)
    return obj


def _maybe_freeze(obj: dict) -> dict:
    return deep_freeze(obj) if _DEBUG_FREEZE else obj


class RVCounter:
    """Mutable ResourceVersion source. One per store by default; the
    sharded control plane (store/sharded.py) hands ONE counter to all of
    its per-shard stores, so RVs stay globally monotonic across shards —
    a merged LIST's RV is resumable on every shard's watch, and pinned
    continue tokens address one global snapshot whichever shard serves
    the page (the etcd-revision-per-cluster contract, kept under
    partitioning)."""

    __slots__ = ("value",)

    def __init__(self, value: int = 0):
        self.value = value

    def next(self) -> int:
        self.value += 1
        return self.value


class MVCCStore:
    """The store. One instance per "cluster"; resources are table names
    ("pods", "nodes", "events", ...) — the GVR analog."""

    def __init__(self, event_window: int = DEFAULT_EVENT_WINDOW,
                 rv_source: RVCounter | None = None):
        # resource -> key -> object (key = "ns/name" or "name")
        self._tables: dict[str, dict[str, dict]] = {}
        self._rv_counter = rv_source or RVCounter()
        # Ring of (resource, Event) for watch replay.
        self._events: list[tuple[str, Event]] = []
        self._event_window = event_window
        self._first_retained_rv = 1
        self._watchers: list[_WatchChannel] = []
        #: resource -> interned watcher index; `_watchers` stays the flat
        #: registry (bookmarks, stop); the index is the dispatch path.
        self._index: dict[str, _ResourceWatchers] = {}
        #: dispatch efficiency counters (metrics/registry.py); the bench
        #: harness reports the deltas per measured phase.
        self.watch_metrics = WatchMetrics()
        self._bookmark_task: asyncio.Task | None = None
        # Subresource hooks, e.g. ("pods", "binding") -> handler.
        self._subresources: dict[tuple[str, str], Callable[..., Awaitable[dict]]] = {}
        # Admission/validation hooks per resource, run before create/update.
        self._validators: dict[str, list[Callable[[dict], None]]] = {}
        self._mutators: dict[
            str, list[tuple[Callable[[dict], None], frozenset[str]]]] = {}
        # CRD-registered kinds are store-local, not process globals: two
        # stores in one process must not share custom kind mappings, and a
        # deleted CRD must drop its entries (install_crd_support).
        self.custom_kinds: dict[str, str] = {}
        self.custom_cluster_scoped: set[str] = set()
        #: durability sinks (add_event_sink) — called per committed event.
        self._event_sinks: list = []
        #: resource -> fields whose PRE-update values ride each MODIFIED
        #: event so field watchers get enter/leave transitions. pods
        #: spec.nodeName is the registered default — the kubelet's watch
        #: shape (the reference apiserver indexes exactly this field).
        self._tracked_fields: dict[str, tuple[str, ...]] = {
            "pods": ("spec.nodeName", "status.phase")}
        #: direct (uncached) LIST scans per resource — the smoke guard's
        #: witness that a relist storm rides the cacher, not the table.
        self.list_direct_total: dict[str, int] = {}
        #: the watch-cache serving tier (store/cacher.py): RV-snapshotted
        #: LISTs, ring-served watch backfill, pinned continue tokens.
        #: Active by default; KTPU_WATCH_CACHE=0 is the kill switch that
        #: degrades every read to the direct-mvcc path below.
        self.cacher = None
        if flags.get("KTPU_WATCH_CACHE"):
            from kubernetes_tpu.store.cacher import Cacher
            self.cacher = Cacher(self)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _key(obj: Mapping) -> str:
        ns = namespace_of(obj)
        return f"{ns}/{name_of(obj)}" if ns else name_of(obj)

    def _table(self, resource: str) -> dict[str, dict]:
        return self._tables.setdefault(resource, {})

    def _next_rv(self) -> int:
        return self._rv_counter.next()

    @property
    def _rv(self) -> int:
        return self._rv_counter.value

    @_rv.setter
    def _rv(self, value: int) -> None:
        self._rv_counter.value = value

    @property
    def resource_version(self) -> int:
        return self._rv_counter.value

    def _record(self, resource: str, ev: Event) -> None:
        self._events.append((resource, ev))
        if len(self._events) > self._event_window:
            drop = len(self._events) - self._event_window
            self._first_retained_rv = self._events[drop - 1][1].rv + 1
            del self._events[:drop]
        # Durability sinks (store/durable.py WAL) observe every committed
        # event BEFORE watch dispatch — the etcd raft-log position. A sink
        # failure must not fail the (already committed) write nor starve
        # live watchers of the event: the sink owns its own degradation
        # (the WAL marks itself broken and stops appending).
        for sink in self._event_sinks:
            try:
                sink(resource, ev)
            except Exception:
                logger.exception("event sink failed; write stays committed")
        # Single fan-in for the serving tier (SURVEY §L0: the cacher's
        # one store watch): the snapshot/ring absorb the event BEFORE
        # watch dispatch, so a handler that reads during dispatch sees a
        # cache consistent with the event it was handed.
        if self.cacher is not None:
            self.cacher.ingest(resource, ev)
        self._dispatch(resource, ev)

    def add_event_sink(self, sink) -> None:
        """Register a synchronous (resource, Event) observer for every
        committed write (SURVEY §5.4 WAL attachment point)."""
        self._event_sinks.append(sink)

    def remove_event_sink(self, sink) -> None:
        try:
            self._event_sinks.remove(sink)
        except ValueError:
            pass

    @staticmethod
    def _select_for(ev: Event, chan: _WatchChannel) -> Event | None:
        """JOINT label+field selection with set-transition synthesis:
        matched-before but not-after ⇒ DELETED; not-before but after ⇒
        ADDED (cacher.go dispatchEvent prevObject semantics; the field
        half is how `spec.nodeName=` watches serve kubelets — a bind looks
        like ADDED to the node's agent).

        prev/cur are each the CONJUNCTION of label-match and field-match
        BEFORE the event type is synthesized, like the reference cacher's
        joint predicate. Chaining one selector's synthesis into the other
        mis-delivers opposite-direction transitions (labels enter while
        spec.nodeName leaves in one update: joint prev and cur are both
        non-matching, yet the chain synthesized a DELETED for an object
        the watcher never saw)."""
        sel = chan.selector
        has_sel = sel is not None and sel.requirements
        fields = chan.fields
        if not has_sel and not fields:
            return ev
        cur_l = (not has_sel) or sel.matches(
            ev.object.get("metadata", {}).get("labels"))
        cur_f = (not fields) or _fields_match(fields, ev.object)
        cur = cur_l and cur_f
        if ev.type == "ADDED":
            prev = False
        else:
            prev_l = cur_l if not has_sel or ev.prev_labels is None \
                else sel.matches(ev.prev_labels)
            prev_f = cur_f if not fields or ev.prev_fields is None \
                else all(
                    ev.prev_fields.get(f, _field_value(ev.object, f)) == v
                    for f, v in fields.items())
            prev = prev_l and prev_f
        if ev.type == "DELETED":
            return ev if (cur or prev) else None
        if cur and not prev:
            return _synth(ev, "ADDED")
        if prev and not cur:
            return _synth(ev, "DELETED")
        return ev if cur else None

    @staticmethod
    def _select_labels(ev: Event, sel: Selector, labels) -> Event | None:
        """Label-only selection for an interned selector group (channels
        with no field predicate): evaluated ONCE per (event, signature);
        the result — including a synthesized enter/leave twin — is shared
        by every channel in the group."""
        cur = sel.matches(labels)
        if ev.type == "ADDED":
            prev = False
        else:
            prev = cur if ev.prev_labels is None \
                else sel.matches(ev.prev_labels)
        if ev.type == "DELETED":
            return ev if (cur or prev) else None
        if cur and not prev:
            return _synth(ev, "ADDED")
        if prev and not cur:
            return _synth(ev, "DELETED")
        return ev if cur else None

    # -- watcher registry / interned dispatch index ------------------------

    def _register_watcher(self, chan: _WatchChannel) -> None:
        """Classify a channel into its dispatch slot. Channels carrying a
        TRACKED field predicate index by that field's exact value (the
        kubelet's spec.nodeName watch shape); selector-only channels
        intern by selector signature; untracked-field channels fall back
        to the linear residue."""
        self._watchers.append(chan)
        idx = self._index.setdefault(chan.resource, _ResourceWatchers())
        has_sel = chan.selector is not None and chan.selector.requirements
        if chan.fields:
            tracked = self._tracked_fields.get(chan.resource, ())
            f = next((f for f in chan.fields if f in tracked), None)
            if f is not None:
                v = chan.fields[f]
                idx.fields.setdefault(f, {}).setdefault(v, []).append(chan)
                chan.slot = ("field", f, v)
            else:
                idx.residue.append(chan)
                chan.slot = ("residue",)
        elif has_sel:
            sig = _selector_sig(chan.selector)
            grp = idx.groups.get(sig)
            if grp is None:
                grp = idx.groups[sig] = (chan.selector, [])
            grp[1].append(chan)
            chan.slot = ("sel", sig)
        else:
            idx.plain.append(chan)
            chan.slot = ("plain",)

    def _unregister_watcher(self, chan: _WatchChannel) -> None:
        try:
            self._watchers.remove(chan)
        except ValueError:
            pass
        idx = self._index.get(chan.resource)
        if idx is None or chan.slot is None:
            return
        kind = chan.slot[0]
        try:
            if kind == "field":
                _, f, v = chan.slot
                bucket = idx.fields[f][v]
                bucket.remove(chan)
                if not bucket:
                    del idx.fields[f][v]
                    if not idx.fields[f]:
                        del idx.fields[f]
            elif kind == "sel":
                sig = chan.slot[1]
                chans = idx.groups[sig][1]
                chans.remove(chan)
                if not chans:
                    del idx.groups[sig]
            elif kind == "plain":
                idx.plain.remove(chan)
            else:
                idx.residue.remove(chan)
        except (KeyError, ValueError):
            pass
        chan.slot = None
        if idx.empty():
            self._index.pop(chan.resource, None)

    def _dispatch(self, resource: str, ev: Event) -> None:
        idx = self._index.get(resource)
        if idx is None:
            return
        m = self.watch_metrics
        ev_ns = namespace_of(ev.object)
        delivered = 0
        checks = 0
        # Plain watchers (informers): no predicate at all.
        for w in idx.plain:
            if w.closed or (w.namespace and ev_ns != w.namespace):
                continue
            w.queue.put_nowait(ev)
            delivered += 1
        # Tracked-field exact-value routing: the post-value bucket plus,
        # on MODIFIED with a changed value, the pre-value bucket — so
        # both sides of an enter/leave transition see it. Candidates run
        # the full joint predicate (they may carry extra fields or a
        # selector); candidate count is O(matching watchers).
        for f, buckets in idx.fields.items():
            cur_v = _field_value(ev.object, f)
            cand = (buckets.get(cur_v),)
            if ev.type == "MODIFIED" and ev.prev_fields is not None:
                prev_v = ev.prev_fields.get(f, cur_v)
                if prev_v != cur_v:
                    cand = (cand[0], buckets.get(prev_v))
            hit = False
            for bucket in cand:
                if not bucket:
                    continue
                hit = True
                for w in bucket:
                    if w.closed or (w.namespace and ev_ns != w.namespace):
                        continue
                    checks += 1
                    selected = self._select_for(ev, w)
                    if selected is not None:
                        w.queue.put_nowait(selected)
                        delivered += 1
            if hit:
                m.index_hits.inc()
        # Interned selector groups: one predicate evaluation (and one
        # synthesized twin, shared wire bytes) per signature.
        if idx.groups:
            labels = ev.object.get("metadata", {}).get("labels")
            for sel, chans in idx.groups.values():
                checks += 1
                selected = self._select_labels(ev, sel, labels)
                if selected is None:
                    continue
                for w in chans:
                    if w.closed or (w.namespace and ev_ns != w.namespace):
                        continue
                    w.queue.put_nowait(selected)
                    delivered += 1
        # Untracked-field watchers: the pre-index linear path.
        for w in idx.residue:
            if w.closed or (w.namespace and ev_ns != w.namespace):
                continue
            checks += 1
            selected = self._select_for(ev, w)
            if selected is not None:
                w.queue.put_nowait(selected)
                delivered += 1
        if delivered:
            m.events_dispatched.inc(delivered)
        if checks:
            m.predicate_checks.inc(checks)

    def register_subresource(
        self, resource: str, sub: str, handler: Callable[..., Awaitable[dict]]
    ) -> None:
        self._subresources[(resource, sub)] = handler

    def register_validator(self, resource: str, fn: Callable[[dict], None]) -> None:
        self._validators.setdefault(resource, []).append(fn)

    def register_mutator(self, resource: str, fn: Callable[[dict], None], *,
                         on: tuple[str, ...] = ("create", "update")) -> None:
        """`on` restricts which operations run the mutator — admission
        plugins like DefaultStorageClass apply at create only."""
        self._mutators.setdefault(resource, []).append((fn, frozenset(on)))

    def _admit(self, resource: str, obj: dict, op: str = "create") -> None:
        for fn, ops in self._mutators.get(resource, []):
            if op in ops:
                fn(obj)
        if op != "delete":  # schema validation guards writes, not removal
            for fn in self._validators.get(resource, []):
                fn(obj)

    # -- kind/scope lookup (built-ins + this store's CRDs) ------------------

    def resource_for_kind(self, kind: str) -> str | None:
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        return self.custom_kinds.get(kind) or KIND_TO_RESOURCE.get(kind)

    def is_cluster_scoped(self, resource: str) -> bool:
        from kubernetes_tpu.api.meta import CLUSTER_SCOPED_RESOURCES
        return (resource in CLUSTER_SCOPED_RESOURCES
                or resource in self.custom_cluster_scoped)

    def kind_map(self) -> dict[str, str]:
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        merged = dict(KIND_TO_RESOURCE)
        merged.update(self.custom_kinds)
        return merged

    # -- CRUD --------------------------------------------------------------

    async def create(self, resource: str, obj: Mapping, *,
                     _owned: bool = False, return_copy: bool = True) -> dict | None:
        """etcd3 Create: txn If(ModRevision==0).Then(Put).

        `_owned=True` hands ownership of `obj` to the store (no entering
        copy — the caller must not touch it afterwards); `return_copy=False`
        skips the exit copy and returns None. Both are hot-path options
        (event recording, binding): deep-copying every wire object 4× per
        write is the store's top CPU cost at scheduler_perf scale.
        """
        obj = dict(obj) if _owned else deep_copy(dict(obj))
        key = self._key(obj)
        if not name_of(obj):
            raise Invalid(f"{resource}: metadata.name is required")
        table = self._table(resource)
        if key in table:
            raise AlreadyExists(f"{resource} {key!r} already exists")
        self._admit(resource, obj)
        set_creation_timestamp(obj)
        # The apiserver, not the client, owns uid assignment (registry
        # store PrepareForCreate). Constructor-made objects already carry
        # one; raw dicts (custom resources, YAML applies) get theirs here
        # so ownerReferences/GC work uniformly.
        obj["metadata"].setdefault("uid", new_uid())
        rv = self._next_rv()
        obj["metadata"]["resourceVersion"] = str(rv)
        obj = _maybe_freeze(obj)
        table[key] = obj
        # The watch event SHARES the stored object: watch consumers must
        # never mutate delivered objects — the convention client-go's shared
        # informer imposes (handlers all receive the one cached object).
        # Updates never mutate stored objects in place (they replace
        # table[key]), so shared references stay frozen at their RV. The
        # *returned* object stays a private copy: read-modify-write on it is
        # idiomatic for callers. KTPU_DEBUG_FREEZE=1 enforces the convention.
        self._record(resource, Event("ADDED", obj, rv))
        return deep_copy(obj) if return_copy else None

    async def get(self, resource: str, key: str) -> dict:
        table = self._table(resource)
        if key not in table:
            raise NotFound(f"{resource} {key!r} not found")
        return deep_copy(table[key])

    async def update(self, resource: str, obj: Mapping, *,
                     _owned: bool = False, return_copy: bool = True) -> dict | None:
        """Full replace with RV precondition when the object carries one.

        `_owned`/`return_copy`: see create().
        """
        obj = dict(obj) if _owned else deep_copy(dict(obj))
        key = self._key(obj)
        table = self._table(resource)
        if key not in table:
            raise NotFound(f"{resource} {key!r} not found")
        current = table[key]
        want_rv = obj.get("metadata", {}).get("resourceVersion")
        if want_rv and want_rv != current["metadata"]["resourceVersion"]:
            raise Conflict(
                f"{resource} {key!r}: resourceVersion mismatch "
                f"(have {current['metadata']['resourceVersion']}, got {want_rv})"
            )
        self._admit(resource, obj, "update")
        # Immutable metadata carries over (uid, creationTimestamp).
        obj["metadata"]["uid"] = current["metadata"].get("uid", obj["metadata"].get("uid"))
        obj["metadata"].setdefault(
            "creationTimestamp", current["metadata"].get("creationTimestamp")
        )
        rv = self._next_rv()
        obj["metadata"]["resourceVersion"] = str(rv)
        prev_labels = dict(current.get("metadata", {}).get("labels") or {})
        tracked = self._tracked_fields.get(resource)
        prev_fields = {f: _field_value(current, f)
                       for f in tracked} if tracked else None
        obj = _maybe_freeze(obj)
        table[key] = obj
        # Shared-object discipline: see create().
        self._record(resource,
                     Event("MODIFIED", obj, rv, prev_labels, prev_fields))
        return deep_copy(obj) if return_copy else None

    async def guaranteed_update(
        self, resource: str, key: str, mutate: Callable[[dict], dict | None],
        max_retries: int = 16, return_copy: bool = True,
    ) -> dict | None:
        """storage.GuaranteedUpdate: read → mutate → CAS-write, retry on
        Conflict. `mutate` gets a private copy; returning None aborts
        (an unchanged copy of the current object is returned).
        `return_copy=False` skips the result copy and returns None."""
        for _ in range(max_retries):
            current = await self.get(resource, key)  # already a private copy
            want_rv = current["metadata"]["resourceVersion"]
            updated = mutate(current)
            if updated is None:
                if not return_copy:
                    return None
                # mutate may have scribbled on `current` before aborting;
                # honor the "unchanged" contract with a fresh read. If the
                # object was deleted in between, fall back to the pre-read
                # copy (it WAS current at read time) rather than surfacing
                # a NotFound the caller never had to handle before.
                try:
                    return await self.get(resource, key)
                except NotFound:
                    return current
            updated["metadata"]["resourceVersion"] = want_rv
            try:
                return await self.update(resource, updated, _owned=True,
                                         return_copy=return_copy)
            except Conflict:
                continue
        raise Conflict(f"{resource} {key!r}: too many conflicts in guaranteed_update")

    async def delete(self, resource: str, key: str, *, uid: str | None = None) -> dict:
        table = self._table(resource)
        if key not in table:
            raise NotFound(f"{resource} {key!r} not found")
        current = table[key]
        if uid and current["metadata"].get("uid") != uid:
            raise Conflict(f"{resource} {key!r}: uid precondition failed")
        self._admit(resource, current, "delete")
        del table[key]
        rv = self._next_rv()
        tomb = deep_copy(current)
        tomb["metadata"]["resourceVersion"] = str(rv)
        # deep_freeze builds a fresh container tree, so the returned tomb
        # stays a private mutable copy either way.
        self._record(resource, Event("DELETED", _maybe_freeze(tomb), rv))
        return tomb

    async def list(
        self,
        resource: str,
        namespace: str | None = None,
        selector: Selector | None = None,
        limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
        *,
        resource_version: int | None = None,
        resource_version_match: str | None = None,
        copy: bool = True,
    ) -> ListResult:
        """Consistent LIST, served from the watch-cache tier when active
        (store/cacher.py documents the RV-semantics contract; `exact`
        RVs and snapshot-pinned continue tokens ride the cacher's ring).
        With the tier disabled, exact RVs other than the current one
        raise Expired — the clean degradation the kill switch promises.
        `copy=False` skips per-item deep copies for encode-only callers
        (only honored on the cacher path; the direct path always copies).
        """
        from kubernetes_tpu.store.cacher import parse_continue
        pinned_rv, cont = parse_continue(continue_key)
        rv = pinned_rv if pinned_rv is not None else resource_version
        exact = pinned_rv is not None or resource_version_match == "Exact"
        if self.cacher is not None:
            return await self.cacher.list(
                resource, namespace, selector, limit, cont, fields,
                resource_version=rv, exact=exact, copy=copy)
        if rv and exact and rv != self._rv:
            raise Expired(
                f"resourceVersion {rv} is not servable (watch cache "
                f"disabled; only the current RV {self._rv} is)")
        return await self.list_direct(
            resource, namespace, selector, limit, cont, fields)

    async def list_direct(
        self,
        resource: str,
        namespace: str | None = None,
        selector: Selector | None = None,
        limit: int = 0,
        continue_key: str | None = None,
        fields: Mapping[str, str] | None = None,
    ) -> ListResult:
        """The uncached mvcc scan: sorted table keys, filter, deep-copy.
        The cacher's differential suite pins `list()` bit-equal to this
        at matching RVs; `list_direct_total` counts these scans so the
        relist-storm smoke can prove agents never land here."""
        self.list_direct_total[resource] = \
            self.list_direct_total.get(resource, 0) + 1
        table = self._table(resource)
        keys = sorted(table.keys())
        if continue_key:
            keys = [k for k in keys if k > continue_key]
        items: list[dict] = []
        for k in keys:
            obj = table[k]
            if namespace and namespace_of(obj) != namespace:
                continue
            if selector is not None and not selector.matches(
                obj.get("metadata", {}).get("labels")
            ):
                continue
            if fields and not _fields_match(fields, obj):
                continue
            items.append(deep_copy(obj))
            if limit and len(items) >= limit:
                break
        return ListResult(items=items, resource_version=self._rv)

    # -- WATCH -------------------------------------------------------------

    async def watch(
        self,
        resource: str,
        resource_version: int = 0,
        namespace: str | None = None,
        selector: Selector | None = None,
        *,
        fields: Mapping[str, str] | None = None,
        bookmarks: bool = True,
    ) -> AsyncIterator[Event]:
        """Stream events after `resource_version`.

        rv=0 means "from now" (reference semantics for unset RV on the cacher
        path: start at current state — callers pair it with a LIST).
        Raises Expired if rv predates the retained window. With the
        watch-cache tier active, backfill is served from the per-resource
        ring (store/cacher.py); the direct path scans global history.
        """
        if self.cacher is not None:
            return await self.cacher.watch(
                resource, resource_version, namespace, selector,
                fields=fields, bookmarks=bookmarks)
        return await self.watch_direct(
            resource, resource_version, namespace, selector,
            fields=fields, bookmarks=bookmarks)

    async def watch_direct(
        self,
        resource: str,
        resource_version: int = 0,
        namespace: str | None = None,
        selector: Selector | None = None,
        *,
        fields: Mapping[str, str] | None = None,
        bookmarks: bool = True,
    ) -> AsyncIterator[Event]:
        """The uncached watch path: global-history backfill scan. Owns
        the 410 window contract; the cacher falls back here for RVs its
        ring no longer holds, so expiry behavior is identical on both.
        RVs ahead of the store (a client that outlived an RV-resetting
        restart) are Expired too — resuming there would silently drop
        every event until the counter caught up; a relist recovers."""
        if resource_version and resource_version > self._rv:
            raise Expired(
                f"resourceVersion {resource_version} is ahead of the "
                f"store (current: {self._rv}); relist")
        if resource_version and resource_version + 1 < self._first_retained_rv:
            raise Expired(
                f"resourceVersion {resource_version} is too old "
                f"(oldest retained: {self._first_retained_rv})"
            )
        replay = [
            ev for res, ev in self._events
            if res == resource and ev.rv > resource_version
        ] if resource_version else []
        return self._open_watch(
            resource, resource_version, namespace, selector,
            fields=fields, bookmarks=bookmarks, replay=replay)

    def _open_watch(
        self,
        resource: str,
        resource_version: int,
        namespace: str | None,
        selector: Selector | None,
        *,
        fields: Mapping[str, str] | None,
        bookmarks: bool,
        replay: list[Event],
    ) -> AsyncIterator[Event]:
        """Register a channel and stream `replay` then live events —
        shared by the ring-backfilled (cacher) and scan-backfilled
        (direct) establishment paths. Registration and the caller's
        replay computation happen in one loop tick, so no event is lost
        between replay and live."""
        chan = _WatchChannel(
            queue=asyncio.Queue(), resource=resource,
            namespace=namespace, selector=selector, fields=fields or None,
        )
        self._register_watcher(chan)
        self._ensure_bookmarks()

        async def gen() -> AsyncIterator[Event]:
            try:
                for ev in replay:
                    if chan.namespace and namespace_of(ev.object) != chan.namespace:
                        continue
                    selected = self._select_for(ev, chan)
                    if selected is None:
                        continue
                    yield selected
                # Live events queued during replay are already in chan.queue —
                # but replayed ones may also be queued (we registered early).
                # Skip duplicates by rv.
                last_rv = replay[-1].rv if replay else resource_version
                while not chan.closed:
                    ev = await chan.queue.get()
                    if ev.type != "BOOKMARK" and ev.rv <= last_rv:
                        continue
                    if not bookmarks and ev.type == "BOOKMARK":
                        continue
                    yield ev
            finally:
                chan.closed = True
                self._unregister_watcher(chan)

        return gen()

    def _ensure_bookmarks(self) -> None:
        if self._bookmark_task is None or self._bookmark_task.done():
            self._bookmark_task = asyncio.ensure_future(self._bookmark_loop())

    async def _bookmark_loop(self) -> None:
        """Periodic bookmark events so idle watchers learn the current RV
        (cacher.go dispatches bookmarks ~1/min; we use 5s for test speed)."""
        while self._watchers:
            await asyncio.sleep(BOOKMARK_INTERVAL_S)
            bk = Event("BOOKMARK", {"metadata": {"resourceVersion": str(self._rv)}}, self._rv)
            for w in list(self._watchers):
                if not w.closed:
                    w.queue.put_nowait(bk)

    def stop(self) -> None:
        for w in self._watchers:
            w.closed = True
            w.queue.put_nowait(Event("BOOKMARK", {"metadata": {}}, self._rv))
        self._watchers.clear()
        self._index.clear()
        if self._bookmark_task:
            self._bookmark_task.cancel()
            self._bookmark_task = None

    async def apply(self, resource: str, obj: Mapping, *,
                    field_manager: str, force: bool = False) -> dict:
        """Server-side apply (store/apply.py): declarative field
        ownership with managedFields + conflict detection."""
        from kubernetes_tpu.store.apply import server_side_apply
        return await server_side_apply(
            self, resource, obj, field_manager=field_manager, force=force)

    # -- subresources ------------------------------------------------------

    async def subresource(self, resource: str, key: str, sub: str, body: Mapping) -> dict:
        handler = self._subresources.get((resource, sub))
        if handler is None:
            raise NotFound(f"subresource {resource}/{sub} not registered")
        return await handler(self, key, body)

    # -- persistence (WAL-lite) -------------------------------------------

    def dump(self) -> str:
        """Serialize full state (snapshot checkpoint; SURVEY §5.4: the store IS
        the checkpoint)."""
        return json.dumps({"rv": self._rv, "tables": self._tables})

    @classmethod
    def load(cls, data: str,
             rv_source: RVCounter | None = None) -> "MVCCStore":
        """Rebuild from dump(). `rv_source` threads a shared counter
        through recovery (the multi-process control plane's coordinated
        RV scheme): a recovering shard adopts the LIVE global counter,
        and the snapshot's rv only ever advances it — the counter's
        monotonic setter means a restart can never hand out an rv the
        cluster already moved past."""
        raw = json.loads(data)
        store = cls(rv_source=rv_source)
        store._rv = raw["rv"]
        store._tables = raw["tables"]
        store._first_retained_rv = raw["rv"] + 1
        return store


# ---------------------------------------------------------------------------
# Binding subresource (pkg/registry/core/pod/storage/storage.go BindingREST)
# ---------------------------------------------------------------------------

async def binding_subresource(store: MVCCStore, key: str, binding: Mapping) -> dict:
    """POST pods/<key>/binding: set spec.nodeName via guaranteed update.

    Fails with Conflict if the pod is already bound to a different node
    (BindingREST.setPodHostAndAnnotations: "pod X is already assigned to node").
    """
    target = (binding.get("target") or {}).get("name")
    if not target:
        raise Invalid("binding.target.name is required")
    want_uid = binding.get("metadata", {}).get("uid")

    # Selective-copy read-modify-write instead of guaranteed_update: the
    # bind only touches spec.nodeName + the PodScheduled condition, so
    # copying just those containers (sharing the untouched sub-objects
    # with the frozen stored object — the watch-event discipline) saves a
    # full pod deep-copy on the perf path's hottest write. Atomicity: no
    # await between the table read and store.update on one loop; update's
    # RV precondition would catch an interleave anyway.
    table = store._table("pods")
    cur_obj = table.get(key)
    if cur_obj is None:
        raise NotFound(f"pods {key!r} not found")
    if want_uid and cur_obj["metadata"].get("uid") != want_uid:
        raise Conflict(f"binding {key!r}: uid mismatch")
    cur = (cur_obj.get("spec") or {}).get("nodeName")
    if cur and cur != target:
        raise Conflict(
            f"binding {key!r}: pod is already assigned to node {cur!r}")
    conds = [dict(c) for c in
             (cur_obj.get("status") or {}).get("conditions") or []]
    for c in conds:
        if c.get("type") == "PodScheduled":
            c["status"] = "True"
            break
    else:
        conds.append({"type": "PodScheduled", "status": "True"})
    new_obj = {**cur_obj,
               "metadata": dict(cur_obj["metadata"]),
               "spec": {**(cur_obj.get("spec") or {}), "nodeName": target},
               "status": {**(cur_obj.get("status") or {}),
                          "conditions": conds}}
    # BindingREST.Create returns metav1.Status, not the pod — which also
    # saves the exit deep-copy.
    await store.update("pods", new_obj, _owned=True, return_copy=False)
    return {"kind": "Status", "apiVersion": "v1", "status": "Success"}


def new_cluster_store(shards: int | None = None):
    """Store with the core subresources registered. `shards > 1` builds
    the partitioned control plane (store/sharded.py ShardedNodeStore:
    node-keyed resources hash-partition across per-shard mvcc stores
    under one global RV counter); None resolves the KTPU_SHARDS
    override, default 1 — the classic single store."""
    if shards is None:
        shards = flags.get("KTPU_SHARDS") or 1
    if shards > 1:
        from kubernetes_tpu.store.sharded import ShardedNodeStore
        store = ShardedNodeStore(shards)
    else:
        store = MVCCStore()
    store.register_subresource("pods", "binding", binding_subresource)
    return store
