"""Server-side apply: field ownership, conflicts, three-way merge.

Parity target: structured-merge-diff + the apply handler
(`pkg/endpoints/handlers/fieldmanager`, SURVEY §2.7 kubectl
`apply --server-side`):

- every applied field is OWNED by the applying fieldManager, recorded in
  metadata.managedFields as {manager, operation: "Apply", fieldsV1};
- applying a field another manager owns WITH A DIFFERENT VALUE is a
  CONFLICT (409 listing the owners) unless force=true, which transfers
  ownership (the reference's conflict/force semantics); equal values
  co-own;
- fields a manager applied before but omits now are REMOVED from the
  object unless another manager also owns them (apply is declarative:
  the config IS the manager's full intent).

Simplification vs the reference, by design: lists are ATOMIC leaves
(no listType=map granular merge) — the whole list is one owned field.
That is exactly how the reference treats `x-kubernetes-list-type:
atomic` lists; granular keyed-list merging is not modeled.
"""

from __future__ import annotations

import copy
from typing import Iterable, Mapping

from kubernetes_tpu.store.mvcc import Conflict, NotFound

#: metadata fields the SERVER owns; appliers never take these over.
_SERVER_META = {"name", "namespace", "uid", "resourceVersion",
                "creationTimestamp", "managedFields", "generation"}


class ApplyConflict(Conflict):
    """409 with the owning managers listed (reference conflict error)."""

    def __init__(self, conflicts: list[tuple[tuple, str]]):
        self.conflicts = conflicts
        lines = ", ".join(
            f"{'.'.join(path)} (owned by {mgr!r})"
            for path, mgr in conflicts)
        super().__init__(f"Apply failed with conflicting fields: {lines}")


def field_paths(obj: Mapping, prefix: tuple = ()) -> set[tuple]:
    """Leaf paths of the applied configuration. Lists are atomic leaves;
    metadata server-owned keys are excluded at the top level."""
    out: set[tuple] = set()
    for k, v in obj.items():
        if prefix == () and k in ("apiVersion", "kind"):
            continue
        if prefix == ("metadata",) and k in _SERVER_META:
            continue
        path = prefix + (k,)
        if isinstance(v, Mapping) and v:
            out |= field_paths(v, path)
        else:
            out.add(path)
    return out


def fields_v1(paths: Iterable[tuple]) -> dict:
    """Upstream's fieldsV1 wire shape: nested {"f:<key>": {...}}."""
    root: dict = {}
    for path in sorted(paths):
        node = root
        for part in path:
            node = node.setdefault(f"f:{part}", {})
    return root


def paths_from_fields_v1(doc: Mapping, prefix: tuple = ()) -> set[tuple]:
    out: set[tuple] = set()
    for k, v in doc.items():
        if not k.startswith("f:"):
            continue
        path = prefix + (k[2:],)
        if v:
            out |= paths_from_fields_v1(v, path)
        else:
            out.add(path)
    return out


def _related(p: tuple, q: tuple) -> bool:
    """True when one path is a (non-strict) prefix of the other."""
    n = len(p) if len(p) < len(q) else len(q)
    return p[:n] == q[:n]


def get_path(obj: Mapping, path: tuple):
    cur = obj
    for part in path:
        if not isinstance(cur, Mapping) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _set_path(obj: dict, path: tuple, value) -> None:
    cur = obj
    for part in path[:-1]:
        nxt = cur.get(part)
        if not isinstance(nxt, dict):
            nxt = cur[part] = {}
        cur = nxt
    cur[path[-1]] = value


def _del_path(obj: dict, path: tuple) -> None:
    cur = obj
    parents = []
    for part in path[:-1]:
        nxt = cur.get(part)
        if not isinstance(nxt, dict):
            return
        parents.append((cur, part))
        cur = nxt
    cur.pop(path[-1], None)
    # prune now-empty parents
    for parent, part in reversed(parents):
        if parent[part] == {}:
            parent.pop(part, None)
        else:
            break


def _owners(current: Mapping) -> dict[str, set[tuple]]:
    out: dict[str, set[tuple]] = {}
    for entry in (current.get("metadata") or {}) \
            .get("managedFields") or []:
        mgr = entry.get("manager", "")
        out[mgr] = paths_from_fields_v1(entry.get("fieldsV1") or {})
    return out


async def server_side_apply(store, resource: str, obj: Mapping, *,
                            field_manager: str, force: bool = False,
                            max_retries: int = 16) -> dict:
    """Apply `obj` as `field_manager`'s full declarative intent.

    Explicit CAS loop (not guaranteed_update): conflicts must be
    computed against the SAME object version the write lands on — a
    stale-read check would let a concurrent owner's write be silently
    overwritten — and ApplyConflict must escape, not be retried as an
    optimistic-concurrency conflict (it subclasses Conflict so the HTTP
    layer maps it to 409)."""
    from kubernetes_tpu.api.meta import namespaced_name
    from kubernetes_tpu.store.mvcc import AlreadyExists
    applied = dict(obj)
    key = namespaced_name(applied)
    applied_paths = field_paths(applied)
    for _ in range(max_retries):
        try:
            current = await store.get(resource, key)
        except NotFound:
            # Deep copy HERE (create path only): managedFields is injected
            # into metadata and must not mutate the caller's input; the
            # update path never writes into `applied`.
            fresh = copy.deepcopy(applied)
            meta = fresh.setdefault("metadata", {})
            meta["managedFields"] = [{
                "manager": field_manager, "operation": "Apply",
                "fieldsV1": fields_v1(applied_paths)}]
            try:
                return await store.create(resource, fresh)
            except AlreadyExists:
                continue  # create race: re-apply against the winner

        want_rv = current["metadata"]["resourceVersion"]
        owners = _owners(current)
        # An applied path collides with an owned path when one is a
        # prefix of the other, not only on exact match: applying a
        # scalar where another manager owns deeper leaves (or a subtree
        # under another manager's leaf) is a structural overwrite that
        # structured-merge-diff flags (advisor r4). Value-equal exact
        # overlaps co-own, as before.
        conflicts: list[tuple[tuple, str]] = []
        force_strip: dict[str, set[tuple]] = {}
        for path in applied_paths:
            new_val = get_path(applied, path)
            if get_path(current, path) == new_val:
                continue  # no change at this leaf → no conflict
            for mgr, owned in owners.items():
                if mgr == field_manager:
                    continue
                overlap = {q for q in owned if _related(path, q)}
                if overlap:
                    conflicts.append((path, mgr))
                    force_strip.setdefault(mgr, set()).update(overlap)
        if conflicts and not force:
            raise ApplyConflict(sorted(set(conflicts)))

        prev_own = owners.get(field_manager, set())
        removed = {
            p for p in prev_own - applied_paths
            if not any(p in owned for mgr, owned in owners.items()
                       if mgr != field_manager)}

        merged = current
        for path in sorted(applied_paths):
            _set_path(merged, path, get_path(applied, path))
        for path in sorted(removed):
            _del_path(merged, path)
        # Ownership bookkeeping: this manager owns exactly its applied
        # set; forced conflicts strip the field from the losers.
        new_owners: dict[str, set[tuple]] = {}
        for mgr, owned in owners.items():
            if mgr == field_manager:
                continue
            keep = set(owned)
            if force:
                keep -= force_strip.get(mgr, set())
            keep -= removed
            if keep:
                new_owners[mgr] = keep
        new_owners[field_manager] = set(applied_paths)
        merged["metadata"]["managedFields"] = [
            {"manager": mgr, "operation": "Apply",
             "fieldsV1": fields_v1(paths)}
            for mgr, paths in sorted(new_owners.items())]
        merged["metadata"]["resourceVersion"] = want_rv
        try:
            return await store.update(resource, merged)
        except ApplyConflict:
            raise
        except Conflict:
            continue  # CAS retry against the newer version
    raise Conflict(f"{resource} {key!r}: too many apply retries")


# ---------------------------------------------------------------------------
# kubectl patch: strategic-merge / merge patch
# ---------------------------------------------------------------------------

#: patchMergeKey per list field (apimachinery strategic-merge tags): lists
#: of objects under these keys merge entry-by-entry on the key instead of
#: replacing wholesale.
_MERGE_KEYS = {"containers": "name", "initContainers": "name",
               "tolerations": "key", "env": "name", "ports": "containerPort",
               "volumes": "name", "volumeMounts": "mountPath"}


def strategic_merge_patch(current: Mapping, patch: Mapping, *,
                          strategic: bool = True) -> dict:
    """RFC-7386 merge patch, plus the strategic keyed-list merge subset
    (`kubectl patch` default): dicts merge recursively, explicit null
    deletes, lists replace — except, when `strategic`, lists of objects
    under a known patchMergeKey field merge per entry on that key."""

    def merge(cur, pat, field=""):
        if isinstance(cur, Mapping) and isinstance(pat, Mapping):
            out = dict(cur)
            for k, v in pat.items():
                if v is None:
                    out.pop(k, None)
                elif k in out:
                    out[k] = merge(out[k], v, k)
                else:
                    out[k] = copy.deepcopy(v)
            return out
        if strategic and isinstance(cur, list) and isinstance(pat, list):
            mk = _MERGE_KEYS.get(field)
            if mk and all(isinstance(e, Mapping) and mk in e
                          for e in [*cur, *pat]):
                out = [copy.deepcopy(e) for e in cur]
                index = {e[mk]: i for i, e in enumerate(out)}
                for e in pat:
                    i = index.get(e[mk])
                    if i is None:
                        index[e[mk]] = len(out)
                        out.append(copy.deepcopy(e))
                    else:
                        out[i] = merge(out[i], e)
                return out
        return copy.deepcopy(pat)

    return merge(dict(current), patch)
