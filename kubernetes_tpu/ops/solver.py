"""On-device batched pod→node assignment.

This replaces the reference's one-pod-at-a-time `schedulePod` →
`findNodesThatFitPod` → `prioritizeNodes` → `selectHost` chain
(pkg/scheduler/schedule_one.go) with a single XLA program over the whole
pending batch. Intra-batch resource contention — the correctness hazard
SURVEY §3.1 flags for batched popping — is resolved *inside* the kernel:
the scan thread capacity through pod steps, so a batch's assignments are
exactly what P sequential host cycles would produce (same priority order,
same capacity accounting), minus the per-cycle Python/framework overhead.

Two solvers:

- `greedy_assign` — lax.scan over pods in queue (priority) order. Each step
  masks by remaining capacity, picks argmax(score), debits the chosen node.
  Deterministic (ties → lowest node index; the host path's seeded reservoir
  tiebreak is equivalent up to tie choice). This is the oracle-equivalent
  default.
- `multistart_greedy_assign` — the contention solver: the SAME scan under
  K pod orders in parallel (vmap over permutations), gang all-or-nothing
  masking, keep the order that places the most pods; identity order wins
  ties so uncontended batches equal the oracle bit-for-bit.

Both are shape-static, jit-compiled once per (P, N, R) signature, and emit
`(P,) int32` node indices with -1 = unschedulable-this-cycle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -jnp.inf


@jax.jit
def greedy_assign(req_q, free_q, free_pods, mask, scores):
    """Sequential-equivalent batched greedy.

    req_q: (P,R) int32 quantized requests (row order = scheduling order)
    free_q: (N,R) int32 remaining capacity (alloc_q - used_q)
    free_pods: (N,) int32 remaining pod slots
    mask: (P,N) bool non-capacity feasibility (plugins other than resources)
    scores: (P,N) float32 combined weighted scores
    → (P,) int32 node index or -1
    """
    n = free_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)

    def step(carry, inp):
        free_q, free_pods = carry
        req, m, sc = inp
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        any_fit = jnp.any(fits)
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        return (free_q, free_pods), idx

    (_, _), assign = lax.scan(step, (free_q, free_pods), (req_q, mask, scores))
    return assign


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring(req_q, req_nz_q, free_q, free_pods, used_nz_q,
                            alloc_q, mask, static_scores, fit_col_w,
                            bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                            strategy: str):
    """Sequential-equivalent greedy with **live re-scoring**.

    The capacity-dependent score plugins (NodeResourcesFit strategies,
    BalancedAllocation) are recomputed inside each scan step from the
    *current* used-resources state — exactly what P sequential host cycles
    see (each cycle re-snapshots after the previous assume). Without this,
    a batch of identical pods all score the batch-start state and pile onto
    one node, wrecking the balance/fragmentation the scorers exist for.

    Capacity-independent score components (taints, host rows, weights
    already applied) arrive pre-summed in `static_scores` (P,N).
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)

    def step(carry, inp):
        free_q, free_pods, used_nz = carry
        req, req_nz, m, sc_static = inp
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        any_fit = jnp.any(fits)
        sc = sc_static
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        used_nz = used_nz + jnp.where(hit[:, None], req_nz[None, :], 0)
        return (free_q, free_pods, used_nz), idx

    (_, _, _), assign = lax.scan(
        step, (free_q, free_pods, used_nz_q),
        (req_q, req_nz_q, mask, static_scores))
    return assign


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring_spread(req_q, req_nz_q, free_q, free_pods,
                                   used_nz_q, alloc_q, mask, static_scores,
                                   fit_col_w, bal_col_mask, shape_u, shape_s,
                                   w_fit, w_bal, strategy: str,
                                   dom_onehot, cid_onehot, dom_counts,
                                   max_skew, min_ok, has_key_nc,
                                   applies, contributes):
    """greedy_assign_rescoring + PodTopologySpread hard constraints INSIDE
    the scan (sequential-equivalent, like capacity).

    The batch-then-verify split is pathological for tight `maxSkew`: the
    solver's batch-start masks let every pod into one domain, the host
    verify rejects all but ~(domains × maxSkew) per batch, and throughput
    collapses to a requeue loop. The domain counts ride the scan carry
    instead — and the constraint set is the UNION across every spread
    template in the batch, so heterogeneous batches (several templates,
    minDomains/namespaceSelector constraints, restricted node
    eligibility, non-self-matching selectors, plus non-spread pods
    matching some template's selector) ALL stay on device:

    dom_onehot: (N, D) float32 — node → domain one-hot over the union of
        ALL constraints' eligible domains (the template's node-eligibility
        mask is folded in per constraint column: ineligible nodes belong
        to no domain and neither count nor gate).
    cid_onehot: (D, C) float32 — domain → owning constraint.
    dom_counts: (D,) float32 — batch-start matching-pod count per domain
        (eligible nodes only, the owning constraint's namespace set).
    max_skew:   (C,) float32 per constraint.
    min_ok:     (C,) float32 — 0.0 when the constraint has fewer eligible
        domains than its minDomains (global minimum is then treated as 0,
        the k8s MinDomainsInPodTopologySpread rule), else 1.0.
    has_key_nc: (N, C) float32 — node HAS the constraint's topology key
        (regardless of eligibility). Keyless nodes reject
        (DoNotSchedule); keyed nodes outside every eligible domain pass
        as "fresh" (the host plugin's count-is-None continue). A keyed-
        but-INELIGIBLE node whose domain value does exist eligible
        elsewhere also fresh-passes here — sound because eligibility is
        the pod's own nodeSelector/affinity/tolerations, so the static
        and taint masks already reject that node for this pod.
    applies:     (P, C) float32 — constraint c GATES pod p's placement
        (p carries it in its own template).
    contributes: (P, C) float32 — pod p COUNTS toward constraint c when
        placed (namespace + selector match) — computed for every pod in
        the chunk, spread-constrained or not. Doubles as the per-pod
        selfMatch term of the skew check (filtering.go selfMatchNum).

    Returns (assign, dom_counts') so the caller can chain counts across
    chunks on device, exactly like the packed used-state.
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    big = jnp.float32(1e30)
    # Static per-constraint node→eligible-domain membership: nodes outside
    # it (but keyed) take the fresh-domain pass.
    in_dom_nc = (dom_onehot @ cid_onehot) > 0                          # (N,C)
    gate_nc = has_key_nc > 0

    def step(carry, inp):
        free_q, free_pods, used_nz, dcounts = carry
        req, req_nz, m, sc_static, app, contrib = inp
        # min count over each constraint's domains (empty domains included),
        # floored to 0 under a minDomains deficit.
        min_c = jnp.min(
            jnp.where(cid_onehot > 0, dcounts[:, None], big), axis=0)  # (C,)
        min_c = min_c * min_ok
        self_d = cid_onehot @ contrib                                  # (D,)
        allowed_d = (dcounts + self_d - cid_onehot @ min_c) \
            <= (cid_onehot @ max_skew)                                 # (D,)
        in_allowed = (dom_onehot @ (allowed_d[:, None] * cid_onehot)) > 0
        # Every constraint THE POD CARRIES: the node must have the
        # topology key (DoNotSchedule rejects keyless nodes), and if it
        # belongs to one of the constraint's eligible domains, that
        # domain's skew must allow this pod's selfMatch increment; keyed
        # nodes outside every eligible domain are fresh and pass.
        node_c_ok = gate_nc & (in_allowed | jnp.logical_not(in_dom_nc))
        spread_ok = jnp.all(node_c_ok | (app[None, :] == 0), axis=1)
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        fits = fits & spread_ok
        any_fit = jnp.any(fits)
        sc = sc_static
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        used_nz = used_nz + jnp.where(hit[:, None], req_nz[None, :], 0)
        # The placed pod counts in the domains of constraints it MATCHES
        # (cid @ contrib masks the chosen node's domain one-hot per
        # constraint ownership).
        dcounts = dcounts + jnp.where(
            any_fit,
            (hit.astype(jnp.float32) @ dom_onehot) * (cid_onehot @ contrib),
            0.0)
        return (free_q, free_pods, used_nz, dcounts), idx

    (_, _, _, dom_counts2), assign = lax.scan(
        step, (free_q, free_pods, used_nz_q, dom_counts),
        (req_q, req_nz_q, mask, static_scores, applies, contributes))
    return assign, dom_counts2


@partial(jax.jit, static_argnames=("strategy",))
def multistart_greedy_assign(req_q, req_nz_q, free_q, free_pods, used_nz_q,
                             alloc_q, mask, static_scores, fit_col_w,
                             bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                             strategy: str, perms, gang_onehot,
                             gang_required):
    """K permuted greedy scans in parallel + gang all-or-nothing.

    Sequential greedy in queue order is the oracle, but it strands capacity
    under contention (e.g. nodes of 4 CPU with queue [3,3,2,2,2]: the two
    3s split the nodes and every 2 is stranded; order [2,2,2,...] places
    three pods). The whole batch is known up front, so run the SAME
    sequential-equivalent scan under K pod orders at once — vmap over
    permutations, each scan threading its own capacity — and keep the
    order that places the most pods. perms[0] must be the identity and
    wins ties, so uncontended batches stay bit-identical to the oracle.

    Gangs (Coscheduling all-or-nothing, SURVEY §2.8's EP-analog row):
    gang_onehot (P, G) marks members, gang_required (G,) the minMember
    floor; a scan's partial gang placements are dropped before counting,
    making under-quota gangs atomic failures inside the solver rather
    than Permit-barrier churn.

    perms: (K, P) int32 permutations of the pod axis.
    Returns (P,) int32 chosen assignment (-1 = unassigned).
    """
    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)

    def one(perm):
        a = greedy_assign_rescoring(
            req_q[perm], req_nz_q[perm], free_q, free_pods, used_nz_q,
            alloc_q, mask[perm], static_scores[perm], fit_col_w,
            bal_col_mask, shape_u, shape_s, w_fit, w_bal, strategy)
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv]

    assigns = jax.vmap(one)(perms)                         # (K, P)
    eff = jax.vmap(
        lambda a: gang_filter(a, gang_onehot, gang_required))(assigns)
    placed = eff >= 0
    n_placed = jnp.sum(placed, axis=1).astype(jnp.float32)
    # Tie-break on total placed request volume: at equal pod count the
    # order that consumes MORE capacity strands less (strictly better
    # fragmentation). Full ties → lowest k (identity = oracle).
    sizes = jnp.sum(req_q, axis=1).astype(jnp.float32)     # (P,)
    vol = jnp.sum(jnp.where(placed, sizes[None, :], 0.0), axis=1)
    vol_norm = vol / jnp.maximum(jnp.max(vol), 1.0)
    best = jnp.argmax(n_placed + 0.5 * vol_norm)
    return eff[best]


def gang_filter(assign, gang_onehot, gang_required):
    """Drop placements of gangs below their required member count."""
    placed = (assign >= 0).astype(jnp.float32)
    counts = placed @ gang_onehot                          # (G,)
    gang_ok = (counts >= gang_required).astype(jnp.float32)
    pod_in_gang = jnp.sum(gang_onehot, axis=1) > 0
    pod_ok = (gang_onehot @ gang_ok) > 0
    keep = (assign >= 0) & (pod_ok | ~pod_in_gang)
    return jnp.where(keep, assign, -1)


#: int32 "no victim" priority padding — mirrors _WaveState.INF (int64 there;
#: the device scan runs int32, and k8s priorities are int32 by API).
PRIO_INF = jnp.int32(2**31 - 1)


@jax.jit
def propose_victims(req_q, prio, banned, used, alloc, pods_used, pods_alloc,
                    vreq, vprio, offsets):
    """Batched preemption victim proposal (SURVEY §7 phase 6,
    "solve-with-victim-relaxation"): ONE device program proposes, for every
    failed preemptor in a wave, the reference-cost-minimal (node, victim
    count) — replacing the per-preemptor host candidate search.

    Per node, victims are the priority-ASCENDING resident prefix (the same
    ordering `DefaultPreemption._WaveState` builds), so "evict the first k"
    is always the cheapest k-victim set and prefix feasibility is a
    relaxed-capacity check. The scan threads claims through per-node state
    exactly like the capacity carry in `greedy_assign`: a chosen node's
    victim prefix is consumed (shifted out) and the preemptor's load is
    charged, so concurrent preemptors spread instead of stacking — the
    in-wave accounting `_WaveState.claim` does, but without P host
    round-trips.

    req_q:    (P, R) int32 preemptor requests, wave order (priority desc)
    prio:     (P,)   int32 preemptor priorities
    banned:   (P, N) bool  — UnschedulableAndUnresolvable nodes per preemptor
    used/alloc:        (N, R) int32 node requested/allocatable
    pods_used/alloc:   (N,)   int32
    vreq:     (N, K, R) int32 per-victim requests (ascending priority; 0 pad)
    vprio:    (N, K)    int32 per-victim priorities (PRIO_INF pad)
    offsets:  (P,) int32 per-preemptor rotation for the equal-cost tiebreak
        (the host path's seeded tie shuffle, made deterministic: ties pick
        the node minimizing (index - offset) mod N, so a wave's preemptors
        spread across an equal-cost set instead of all hitting node 0)

    Returns (node (P,) int32 [-1 = no candidate], count (P,) int32,
    used', pods_used', vreq', vprio') — the post-claim carry, so a caller
    chunking a wave wider than one P bucket threads state across calls
    without re-uploading (same pattern as the packed used-state chain).

    Cost ordering per the reference's pickOneNodeForPreemption subset the
    host path implements: lowest max victim priority → smallest priority
    sum → fewest victims (PDB tier absent there too). Proposals are
    host-verified against the live snapshot (full Filter chain) before any
    eviction — this program only replaces the SEARCH.
    """
    N, K, R = vreq.shape
    iota_n = jnp.arange(N, dtype=jnp.int32)
    karange = jnp.arange(K, dtype=jnp.int32)
    BIG = jnp.int32(2**31 - 1)

    def step(carry, inp):
        used, pods_used, vreq, vprio = carry
        q, p, ban, off = inp
        valid = vprio < PRIO_INF                            # (N, K)
        rel = jnp.cumsum(vreq, axis=1)                      # (N, K, R)
        prio_m = jnp.where(valid, vprio, 0)
        # Priority SUM rides float32: an int32 cumsum of near-INT32_MAX
        # priorities over a deep prefix overflows. Exact below 2^24;
        # above, the sum key coarsens ties only — candidates are
        # host-verified before any eviction either way.
        vsum = jnp.cumsum(prio_m.astype(jnp.float32), axis=1)
        vmax = lax.cummax(prio_m, axis=1)                   # (N, K)
        # Ascending sort ⇒ vprio[k] < p implies the whole prefix is
        # below the preemptor (same invariant the host candidates() uses).
        eligible = vprio < p
        fits = jnp.all(used[:, None, :] - rel + q[None, None, :]
                       <= alloc[:, None, :], axis=-1)
        fits = fits & (pods_used[:, None] - karange[None, :]
                       <= pods_alloc[:, None])
        ok = eligible & fits                                # (N, K)
        any_ok = jnp.any(ok, axis=1) & jnp.logical_not(ban)
        kmin = jnp.argmax(ok, axis=1).astype(jnp.int32)     # first fit
        cmax = jnp.take_along_axis(vmax, kmin[:, None], 1)[:, 0]
        csum = jnp.take_along_axis(vsum, kmin[:, None], 1)[:, 0]
        # Staged lexicographic argmin (vmax, vsum, count), rotation tiebreak.
        k1 = jnp.where(any_ok, cmax, BIG)
        c1 = any_ok & (cmax == jnp.min(k1))
        k2 = jnp.where(c1, csum, jnp.float32(jnp.inf))
        c2 = c1 & (csum == jnp.min(k2))
        k3 = jnp.where(c2, kmin, BIG)
        c3 = c2 & (kmin == jnp.min(k3))
        rot = (iota_n - off) % N
        n_star = jnp.argmin(jnp.where(c3, rot, BIG)).astype(jnp.int32)
        found = jnp.any(any_ok)
        count = kmin[n_star] + 1
        # Claim: drop the chosen prefix, charge the preemptor, shift the
        # node's victim arrays so later wave members see the truth.
        hit = (iota_n == n_star) & found
        freed = rel[n_star, count - 1]                      # (R,)
        used = used + jnp.where(hit[:, None], q[None, :] - freed[None, :], 0)
        pods_used = pods_used + jnp.where(hit, 1 - count, 0)
        src = jnp.clip(karange + count, 0, K - 1)
        keep = (karange + count) < K
        row_vreq = jnp.where(keep[:, None], vreq[n_star][src], 0)
        row_vprio = jnp.where(keep, vprio[n_star][src], PRIO_INF)
        vreq = jnp.where(hit[:, None, None], row_vreq[None, :, :], vreq)
        vprio = jnp.where(hit[:, None], row_vprio[None, :], vprio)
        out = (jnp.where(found, n_star, jnp.int32(-1)),
               jnp.where(found, count, jnp.int32(0)))
        return (used, pods_used, vreq, vprio), out

    carry, (node, count) = lax.scan(
        step, (used, pods_used, vreq, vprio),
        (req_q, prio, banned, offsets))
    return (node, count) + carry


@jax.jit
def fragmentation(free_q, alloc_q, valid):
    """Node fragmentation %: mean over non-empty resource columns of the
    free/allocatable fraction on nodes that host at least one pod would
    over-estimate; the metric BASELINE tracks is simpler — mean remaining
    capacity fraction across valid nodes (lower = tighter packing)."""
    alloc = alloc_q.astype(jnp.float32)
    frac = jnp.where(alloc > 0, free_q.astype(jnp.float32) / alloc, 0.0)
    per_node = jnp.sum(frac, axis=1) / jnp.maximum(
        jnp.sum(alloc > 0, axis=1), 1)
    return 100.0 * jnp.sum(jnp.where(valid, per_node, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)
