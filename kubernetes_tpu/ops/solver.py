"""On-device batched pod→node assignment.

This replaces the reference's one-pod-at-a-time `schedulePod` →
`findNodesThatFitPod` → `prioritizeNodes` → `selectHost` chain
(pkg/scheduler/schedule_one.go) with a single XLA program over the whole
pending batch. Intra-batch resource contention — the correctness hazard
SURVEY §3.1 flags for batched popping — is resolved *inside* the kernel:
the scan thread capacity through pod steps, so a batch's assignments are
exactly what P sequential host cycles would produce (same priority order,
same capacity accounting), minus the per-cycle Python/framework overhead.

Two solvers:

- `greedy_assign` — lax.scan in queue (priority) order. Each step masks
  by remaining capacity, picks argmax(score), debits the chosen node.
  Deterministic (ties → lowest node index; the host path's seeded reservoir
  tiebreak is equivalent up to tie choice). This is the oracle-equivalent
  default. The serial scan handles one pod per step; the `_wave` variants
  below handle W pods per step with the same assignments bit-for-bit.
- `multistart_greedy_assign` — the contention solver: the SAME scan under
  K pod orders in parallel (vmap over permutations), gang all-or-nothing
  masking, keep the order that places the most pods; identity order wins
  ties so uncontended batches equal the oracle bit-for-bit.

Speculative wavefront scans (`*_wave`): the serial scan's length P is the
wall at scale — every step is a chain of tiny ops dispatched in sequence.
The wavefront form evaluates W pods per scan step against the SAME carry
state, commits the wave's prefix-distinct argmax choices speculatively,
and falls back to an in-step serial replay (`lax.fori_loop` over the
wave) exactly when a pairwise conflict check cannot prove the speculation
serial-equivalent — so assignments are **bit-identical at every W** (the
same contract the shortlist and class-plane scans hold) while the scan
length drops P → P/W in the low-conflict regime. See
`greedy_assign_rescoring_wave` for the speculation/replay contract.

Both are shape-static, jit-compiled once per (P, N, R) signature, and emit
`(P,) int32` node indices with -1 = unschedulable-this-cycle.

Class-dictionary planes: every scan reads `mask`/`static_scores` as
CLOSED-OVER planes addressed per step through `rows` — a (P,) row index
mapping each pod to its plane row. The backend ships (C, N) planes over
pod EQUIVALENCE CLASSES (pods sharing request/toleration/host-row/score
signatures — template batches have a handful) with `rows = class index
per pod`, so no (P, N) plane exists on host or device; the legacy
per-pod form is the degenerate `rows = arange(P)` (C == P), which is
also the KTPU_CLASS_PLANES=0 kill-switch shape. Per-pod residuals that
would otherwise split a class — single-allowed-column host rows
(NodeName, DRA allocated-claim pinning) — ride the sparse exception
vector `exc`: (P,) int32, -1 = none, else the ONE global column the pod
is additionally restricted to (intersected with its class row).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -jnp.inf


@jax.jit
def greedy_assign(req_q, free_q, free_pods, mask, scores):
    """Sequential-equivalent batched greedy.

    req_q: (P,R) int32 quantized requests (row order = scheduling order)
    free_q: (N,R) int32 remaining capacity (alloc_q - used_q)
    free_pods: (N,) int32 remaining pod slots
    mask: (P,N) bool non-capacity feasibility (plugins other than resources)
    scores: (P,N) float32 combined weighted scores
    → (P,) int32 node index or -1
    """
    n = free_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)

    def step(carry, inp):
        free_q, free_pods = carry
        req, m, sc = inp
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        any_fit = jnp.any(fits)
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        return (free_q, free_pods), idx

    (_, _), assign = lax.scan(step, (free_q, free_pods), (req_q, mask, scores))
    return assign


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring(req_q, req_nz_q, free_q, free_pods, used_nz_q,
                            alloc_q, mask, static_scores, fit_col_w,
                            bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                            strategy: str, rows=None, exc=None):
    """Sequential-equivalent greedy with **live re-scoring**.

    The capacity-dependent score plugins (NodeResourcesFit strategies,
    BalancedAllocation) are recomputed inside each scan step from the
    *current* used-resources state — exactly what P sequential host cycles
    see (each cycle re-snapshots after the previous assume). Without this,
    a batch of identical pods all score the batch-start state and pile onto
    one node, wrecking the balance/fragmentation the scorers exist for.

    Capacity-independent score components (taints, host rows, weights
    already applied) arrive pre-summed in `static_scores` — (C, N) class
    planes addressed through `rows` (see module docstring); with
    rows=None the planes are per-pod (C == P, row = pod). `exc` is the
    optional (P,) single-allowed-column restriction (-1 = none).
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    if rows is None:
        rows = jnp.arange(p, dtype=jnp.int32)

    def step(carry, inp):
        free_q, free_pods, used_nz = carry
        if exc is None:
            req, req_nz, row = inp
        else:
            req, req_nz, row, e = inp
        m = mask[row]
        if exc is not None:
            m = m & ((e < 0) | (iota == e))
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        any_fit = jnp.any(fits)
        sc = static_scores[row]
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        used_nz = used_nz + jnp.where(hit[:, None], req_nz[None, :], 0)
        return (free_q, free_pods, used_nz), idx

    xs = (req_q, req_nz_q, rows) if exc is None \
        else (req_q, req_nz_q, rows, exc)
    (_, _, _), assign = lax.scan(
        step, (free_q, free_pods, used_nz_q), xs)
    return assign


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring_spread(req_q, req_nz_q, free_q, free_pods,
                                   used_nz_q, alloc_q, mask, static_scores,
                                   fit_col_w, bal_col_mask, shape_u, shape_s,
                                   w_fit, w_bal, strategy: str,
                                   dom_onehot, cid_onehot, dom_counts,
                                   max_skew, min_ok, has_key_nc,
                                   applies, contributes, rows=None,
                                   exc=None):
    """greedy_assign_rescoring + PodTopologySpread hard constraints INSIDE
    the scan (sequential-equivalent, like capacity).

    The batch-then-verify split is pathological for tight `maxSkew`: the
    solver's batch-start masks let every pod into one domain, the host
    verify rejects all but ~(domains × maxSkew) per batch, and throughput
    collapses to a requeue loop. The domain counts ride the scan carry
    instead — and the constraint set is the UNION across every spread
    template in the batch, so heterogeneous batches (several templates,
    minDomains/namespaceSelector constraints, restricted node
    eligibility, non-self-matching selectors, plus non-spread pods
    matching some template's selector) ALL stay on device:

    dom_onehot: (N, D) float32 — node → domain one-hot over the union of
        ALL constraints' eligible domains (the template's node-eligibility
        mask is folded in per constraint column: ineligible nodes belong
        to no domain and neither count nor gate).
    cid_onehot: (D, C) float32 — domain → owning constraint.
    dom_counts: (D,) float32 — batch-start matching-pod count per domain
        (eligible nodes only, the owning constraint's namespace set).
    max_skew:   (C,) float32 per constraint.
    min_ok:     (C,) float32 — 0.0 when the constraint has fewer eligible
        domains than its minDomains (global minimum is then treated as 0,
        the k8s MinDomainsInPodTopologySpread rule), else 1.0.
    has_key_nc: (N, C) float32 — node HAS the constraint's topology key
        (regardless of eligibility). Keyless nodes reject
        (DoNotSchedule); keyed nodes outside every eligible domain pass
        as "fresh" (the host plugin's count-is-None continue). A keyed-
        but-INELIGIBLE node whose domain value does exist eligible
        elsewhere also fresh-passes here — sound because eligibility is
        the pod's own nodeSelector/affinity/tolerations, so the static
        and taint masks already reject that node for this pod.
    applies:     (P, C) float32 — constraint c GATES pod p's placement
        (p carries it in its own template).
    contributes: (P, C) float32 — pod p COUNTS toward constraint c when
        placed (namespace + selector match) — computed for every pod in
        the chunk, spread-constrained or not. Doubles as the per-pod
        selfMatch term of the skew check (filtering.go selfMatchNum).

    `rows`/`exc` are the class-plane indirection of the module docstring
    (rows=None ⇒ per-pod planes). applies/contributes stay per-pod.

    Returns (assign, dom_counts') so the caller can chain counts across
    chunks on device, exactly like the packed used-state.
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    big = jnp.float32(1e30)
    if rows is None:
        rows = jnp.arange(p, dtype=jnp.int32)
    # Static per-constraint node→eligible-domain membership: nodes outside
    # it (but keyed) take the fresh-domain pass.
    in_dom_nc = (dom_onehot @ cid_onehot) > 0                          # (N,C)
    gate_nc = has_key_nc > 0

    def step(carry, inp):
        free_q, free_pods, used_nz, dcounts = carry
        if exc is None:
            req, req_nz, row, app, contrib = inp
        else:
            req, req_nz, row, app, contrib, e = inp
        m = mask[row]
        if exc is not None:
            m = m & ((e < 0) | (iota == e))
        sc_static = static_scores[row]
        # min count over each constraint's domains (empty domains included),
        # floored to 0 under a minDomains deficit.
        min_c = jnp.min(
            jnp.where(cid_onehot > 0, dcounts[:, None], big), axis=0)  # (C,)
        min_c = min_c * min_ok
        self_d = cid_onehot @ contrib                                  # (D,)
        allowed_d = (dcounts + self_d - cid_onehot @ min_c) \
            <= (cid_onehot @ max_skew)                                 # (D,)
        in_allowed = (dom_onehot @ (allowed_d[:, None] * cid_onehot)) > 0
        # Every constraint THE POD CARRIES: the node must have the
        # topology key (DoNotSchedule rejects keyless nodes), and if it
        # belongs to one of the constraint's eligible domains, that
        # domain's skew must allow this pod's selfMatch increment; keyed
        # nodes outside every eligible domain are fresh and pass.
        node_c_ok = gate_nc & (in_allowed | jnp.logical_not(in_dom_nc))
        spread_ok = jnp.all(node_c_ok | (app[None, :] == 0), axis=1)
        fits = m & jnp.all(req[None, :] <= free_q, axis=1) & (free_pods >= 1)
        fits = fits & spread_ok
        any_fit = jnp.any(fits)
        sc = sc_static
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
        masked = jnp.where(fits, sc, NEG_INF)
        idx = jnp.argmax(masked).astype(jnp.int32)
        idx = jnp.where(any_fit, idx, jnp.int32(-1))
        hit = iota == idx
        free_q = free_q - jnp.where(hit[:, None], req[None, :], 0)
        free_pods = free_pods - hit.astype(jnp.int32)
        used_nz = used_nz + jnp.where(hit[:, None], req_nz[None, :], 0)
        # The placed pod counts in the domains of constraints it MATCHES
        # (cid @ contrib masks the chosen node's domain one-hot per
        # constraint ownership).
        dcounts = dcounts + jnp.where(
            any_fit,
            (hit.astype(jnp.float32) @ dom_onehot) * (cid_onehot @ contrib),
            0.0)
        return (free_q, free_pods, used_nz, dcounts), idx

    xs = (req_q, req_nz_q, rows, applies, contributes) if exc is None \
        else (req_q, req_nz_q, rows, applies, contributes, exc)
    (_, _, _, dom_counts2), assign = lax.scan(
        step, (free_q, free_pods, used_nz_q, dom_counts), xs)
    return assign, dom_counts2


@partial(jax.jit, static_argnames=("strategy",))
def multistart_greedy_assign(req_q, req_nz_q, free_q, free_pods, used_nz_q,
                             alloc_q, mask, static_scores, fit_col_w,
                             bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                             strategy: str, perms, gang_onehot,
                             gang_required, rows=None, exc=None):
    """K permuted greedy scans in parallel + gang all-or-nothing.

    Sequential greedy in queue order is the oracle, but it strands capacity
    under contention (e.g. nodes of 4 CPU with queue [3,3,2,2,2]: the two
    3s split the nodes and every 2 is stranded; order [2,2,2,...] places
    three pods). The whole batch is known up front, so run the SAME
    sequential-equivalent scan under K pod orders at once — vmap over
    permutations, each scan threading its own capacity — and keep the
    order that places the most pods. perms[0] must be the identity and
    wins ties, so uncontended batches stay bit-identical to the oracle.

    Gangs (Coscheduling all-or-nothing, SURVEY §2.8's EP-analog row):
    gang_onehot (P, G) marks members, gang_required (G,) the minMember
    floor; a scan's partial gang placements are dropped before counting,
    making under-quota gangs atomic failures inside the solver rather
    than Permit-barrier churn.

    perms: (K, P) int32 permutations of the pod axis.
    Returns (P,) int32 chosen assignment (-1 = unassigned).
    """
    return _multistart_body(
        req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s, w_fit,
        w_bal, strategy, perms, gang_onehot, gang_required, rows, exc)


def _multistart_body(req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
                     mask, static_scores, fit_col_w, bal_col_mask, shape_u,
                     shape_s, w_fit, w_bal, strategy, perms, gang_onehot,
                     gang_required, rows=None, exc=None):
    """Traceable multistart core — also the shortlist path's whole-chunk
    fallback branch (see multistart_greedy_assign_shortlist).

    Only the small per-pod vectors permute; the (C, N) planes stay
    closed-over and each order addresses them through `rows[perm]` —
    permuting the planes themselves would materialize one (P, N) copy
    per order, exactly what the class-dictionary format removes."""
    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)
    if rows is None:
        rows = arange_p

    def one(perm):
        a = greedy_assign_rescoring(
            req_q[perm], req_nz_q[perm], free_q, free_pods, used_nz_q,
            alloc_q, mask, static_scores, fit_col_w,
            bal_col_mask, shape_u, shape_s, w_fit, w_bal, strategy,
            rows=rows[perm], exc=None if exc is None else exc[perm])
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv]

    assigns = jax.vmap(one)(perms)                         # (K, P)
    return _select_best(assigns, req_q, gang_onehot, gang_required)


def _select_best(assigns, req_q, gang_onehot, gang_required):
    """Gang-filter K candidate assignments and keep the best order."""
    eff = jax.vmap(
        lambda a: gang_filter(a, gang_onehot, gang_required))(assigns)
    placed = eff >= 0
    n_placed = jnp.sum(placed, axis=1).astype(jnp.float32)
    # Tie-break on total placed request volume: at equal pod count the
    # order that consumes MORE capacity strands less (strictly better
    # fragmentation). Full ties → lowest k (identity = oracle).
    sizes = jnp.sum(req_q, axis=1).astype(jnp.float32)     # (P,)
    vol = jnp.sum(jnp.where(placed, sizes[None, :], 0.0), axis=1)
    vol_norm = vol / jnp.maximum(jnp.max(vol), 1.0)
    best = jnp.argmax(n_placed + 0.5 * vol_norm)
    return eff[best]


def gang_filter(assign, gang_onehot, gang_required):
    """Drop placements of gangs below their required member count."""
    placed = (assign >= 0).astype(jnp.float32)
    counts = placed @ gang_onehot                          # (G,)
    gang_ok = (counts >= gang_required).astype(jnp.float32)
    pod_in_gang = jnp.sum(gang_onehot, axis=1) > 0
    pod_ok = (gang_onehot @ gang_ok) > 0
    keep = (assign >= 0) & (pod_ok | ~pod_in_gang)
    return jnp.where(keep, assign, -1)


# ---------------------------------------------------------------------------
# Shortlist-pruned solve: per-pod top-K candidate columns with an exactness
# fallback — the O(P·K + fallbacks·N) form of the sequential-equivalent scan
# for large N (the 50k-node preset is bound by the N-wide inner reduce).
# ---------------------------------------------------------------------------

def shortlist_prefilter(feas0, sc0, k: int):
    """Per-row top-K candidate columns + the exactness threshold.

    feas0: (S,N) bool chunk-start feasibility (static mask ∧ capacity fit;
        within a chunk capacity only DECREASES, so a chunk-start-infeasible
        node can never become the winner — spread gating is deliberately
        NOT folded in, it is non-monotone and re-checked in-scan).
    sc0:   (S,N) float32 chunk-start live scores (kernels.chunk_start_scores).

    Returns (cand (S,K) int32, thresh (S,)): the K best columns per row and
    the (K+1)-th value — the max score any node OUTSIDE the shortlist can
    ever reach during the chunk's scan, because a node's live score moves
    only when the node is debited, debits are tracked (touched nodes join
    the scan's candidate set), and untouched nodes keep sc0 exactly.
    -inf threshold ⇔ the shortlist already holds every feasible node.

    lax.top_k breaks ties toward the LOWER index — load-bearing for the
    scans' tie rule: every node outside the shortlist whose sc0 equals the
    threshold has a HIGHER index than every in-list node at that value, so
    an untouched in-list winner at exactly the threshold still wins the
    full scan's lowest-index tie-break.
    """
    vals, cand = lax.top_k(jnp.where(feas0, sc0, NEG_INF), k + 1)
    return cand[:, :k].astype(jnp.int32), vals[:, k]


def block_bound_prefilter(alloc_q, used_nz_q, req_nz_q, static_scores,
                          feasible, fit_col_w, bal_col_mask, shape_u,
                          shape_s, w_fit, w_bal, strategy: str, n_real,
                          k: int, block_w: int):
    """Two-pass block-sparse form of the shortlist prefilter — the
    sublinear replacement for the full (C,N) `chunk_start_scores` +
    `shortlist_prefilter` pass at large N.

    Pass 1 (O(C·B)): fold the N node columns into B = ceil(N/block_w)
    fixed blocks, derive per-block aggregate planes IN-PROGRAM from the
    live capacity planes (never from maintained state — a mid-batch
    verify-reject fold-back decreases `used`, which would turn any
    chained max/min stale in the unsafe direction), and compute a per-
    (class, block) score upper bound (kernels.block_score_upper_bound).
    Select the M = 2·ceil((K+1)/block_w) highest-bound blocks per class.

    Pass 2 (O(C·M·block_w)): gather just the selected blocks' columns,
    score them with `kernels.gathered_start_scores` (bit-identical
    element arithmetic to the full pass — every op is element-wise over
    columns with reductions only over R), and take the per-class top-K
    + threshold exactly as `shortlist_prefilter` would.

    Exactness gate — the result is used ONLY when, for every class c
    and every non-selected block b, one arm holds:

    - strict:  ub[c,b] < t̂[c] — the bound (which over-approximates by
      construction, plus BLOCK_UB_EPS of float slack) already loses to
      the gathered (K+1)-th value, so no column of b can enter the
      top-K or move the threshold.
    - empty:   feas_cnt[c,b] == 0 — no feasible column at all.
    - uniform: block b lies strictly AFTER the last selected block,
      block b and that reference block are capacity-uniform and share
      one static score (exact tuple equality of (stat_max, stat_min,
      amin, amax, umin, umax) — no epsilon: identical inputs ⇒
      identical f32 outputs), and the reference block's best gathered
      value v_ref ≤ t̂. Then every feasible column of b scores exactly
      v_ref, and its position after the whole selection puts it at a
      higher global index than every gathered column, so at v_ref == t̂
      the full-width top_k's lower-index tie rule (see
      shortlist_prefilter) would still pick the gathered columns —
      threshold and candidates are bit-identical. This arm is what
      keeps uniform fleets (every node identical, every bound tied)
      prunable — the strict arm alone can never separate identical
      blocks — and because it keys on the last selected block rather
      than a fixed 0..M-1 prefix, it keeps firing as a drain's usage
      frontier advances and selection shifts to later blocks (the
      already-filled blocks behind the frontier prune via strict: their
      debited scores sit below the fresh-node threshold by more than
      BLOCK_UB_EPS for any non-trivial request). A uniform block before
      or between selected blocks cannot use this arm (its columns would
      WIN the ties) and routes to the fallback.

    When any block fails all arms, the whole chunk falls back to the
    full-width pass via lax.cond — exact by construction, and the
    fallback branch traces the r18/r21 call graph verbatim.

    Candidate caveat shared with the full prefilter: when a class has
    fewer than K feasible columns, the -inf candidate slots may name
    different (infeasible) columns than the full pass would — inert for
    the scans, which re-mask candidates against live feasibility.

    n_real: traced int32 — real (unpadded) node count; padding columns
    are excluded from every aggregate. k/block_w: static.

    Returns (sc0 (C,N) — gathered columns hold their exact chunk-start
    value, non-gathered columns are 0.0 and only ever read through the
    candidate set, cand (C,K), thresh (C,), blocks_scanned int32,
    blocks_pruned int32).
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle
    n = alloc_q.shape[0]
    c = static_scores.shape[0]
    bw = block_w
    b = -(-n // bw)
    m = 2 * (-(-(k + 1) // bw))
    if m + 1 > b:
        raise ValueError(
            f"block prefilter needs M+1={m + 1} <= B={b}; route "
            "block_w=0 for this shape (see AdaptiveTuner.block_width)")

    col_real = jnp.arange(n, dtype=jnp.int32) < n_real
    amin_pos, amin, amax, umin, umax = kernels.block_capacity_aggregates(
        alloc_q, used_nz_q, col_real, bw)
    stat_max, stat_min, feas_cnt = kernels.block_feasible_stat(
        feasible, static_scores, bw)
    ub = kernels.block_score_upper_bound(
        stat_max, feas_cnt, amin_pos, amax, umin, umax, req_nz_q,
        fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
        strategy)                                                # (C,B)

    _, sel = lax.top_k(ub, m + 1)
    sel_ids = jnp.sort(sel[:, :m].astype(jnp.int32), axis=1)     # (C,M) asc
    rowi = jnp.arange(c, dtype=jnp.int32)[:, None]

    # Gather the selected blocks' columns. Ascending sel_ids keep the
    # gathered order a subsequence of global column order, so top_k's
    # lower-index tie rule below means the same thing it means full-width.
    cols = (sel_ids[:, :, None] * bw
            + jnp.arange(bw, dtype=jnp.int32)[None, None, :]).reshape(c, -1)
    valid = cols < n                    # tail fold-pad beyond the planes
    safe_cols = jnp.minimum(cols, n - 1)
    feas_g = jnp.take_along_axis(feasible, safe_cols, axis=1) & valid
    stat_g = jnp.take_along_axis(static_scores, safe_cols, axis=1)
    sc0_g = kernels.gathered_start_scores(
        alloc_q[safe_cols], used_nz_q[safe_cols], req_nz_q, stat_g,
        fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
        strategy)                                                # (C,G)
    masked_g = jnp.where(feas_g, sc0_g, NEG_INF)
    vals, loc = lax.top_k(masked_g, k + 1)
    cand = jnp.take_along_axis(
        safe_cols, loc[:, :k], axis=1).astype(jnp.int32)
    thresh = vals[:, k]

    # Scatter gathered scores into a full-width sc0 row set the scans
    # can element-gather from. Invalid (fold-pad) lanes write to the
    # throwaway column N of an (N+1)-wide buffer — a clamp-to-N-1 write
    # would clobber the real last column.
    tgt = jnp.where(valid, cols, n)
    sc0_full = jnp.zeros((c, n + 1), jnp.float32).at[
        rowi, tgt].set(sc0_g)[:, :n]

    # --- exactness predicate over non-selected blocks ---
    is_sel = jnp.zeros((c, b), jnp.bool_).at[rowi, sel_ids].set(True)
    strict = ub < thresh[:, None]
    empty = feas_cnt == 0

    ref = sel_ids[:, m - 1:m]                                    # (C,1)
    unif_cap = (jnp.all(amin == amax, axis=1)
                & jnp.all(umin == umax, axis=1))[None, :]        # (1,B)
    stat_unif = stat_max == stat_min                             # (C,B)
    eq_cap = (jnp.all(amax[None, :, :] == amax[ref], axis=-1)
              & jnp.all(amin[None, :, :] == amin[ref], axis=-1)
              & jnp.all(umax[None, :, :] == umax[ref], axis=-1)
              & jnp.all(umin[None, :, :] == umin[ref], axis=-1))
    eq_stat = ((stat_max == jnp.take_along_axis(stat_max, ref, axis=1))
               & (stat_min == jnp.take_along_axis(stat_min, ref, axis=1)))
    # Only blocks strictly AFTER the last selected block qualify: their
    # columns all sit at higher global indices than every gathered
    # column, so ties at t̂ lose top_k's lower-index rule. A uniform
    # block BEFORE or BETWEEN selected blocks would win those ties —
    # it must prune via strict/empty or force the fallback.
    after_ref = jnp.arange(b, dtype=jnp.int32)[None, :] > ref
    v_ref = jnp.max(masked_g.reshape(c, m, bw)[:, m - 1, :], axis=-1)
    uniform = (after_ref & unif_cap & stat_unif & eq_cap & eq_stat
               & (v_ref <= thresh)[:, None])

    ok_all = jnp.all(is_sel | strict | empty | uniform)

    def _block_exact(_):
        return sc0_full, cand, thresh

    def _block_fallback_full(_):
        sc0 = kernels.chunk_start_scores(
            alloc_q, used_nz_q, req_nz_q, static_scores, fit_col_w,
            bal_col_mask, shape_u, shape_s, w_fit, w_bal, strategy)
        cand_f, thresh_f = shortlist_prefilter(feasible, sc0, k)
        return sc0, cand_f, thresh_f

    sc0_out, cand_out, thresh_out = lax.cond(
        ok_all, _block_exact, _block_fallback_full, jnp.int32(0))
    blocks_scanned = jnp.int32(c * b)
    blocks_pruned = jnp.where(ok_all, jnp.int32(c * (b - m)), jnp.int32(0))
    return sc0_out, cand_out, thresh_out, blocks_scanned, blocks_pruned


def _shortlist_scan(req_q, req_nz_q, rows, free_q, free_pods, used_nz_q,
                    alloc_q, mask, static_scores, fit_col_w, bal_col_mask,
                    shape_u, shape_s, w_fit, w_bal, strategy: str,
                    sc0, sl_class, sl_cand, sl_thresh, has_node,
                    inline_fallback: bool, exc=None):
    """The narrow sequential-equivalent scan: per pod, re-score only the
    pod's K shortlist columns plus every node already debited this chunk,
    and prove the winner exact against the prefilter threshold.

    Exactness argument, per step: nodes fall in three classes —
    (a) shortlist candidates and (b) nodes touched (debited) earlier in
    this chunk are both IN the candidate set and re-scored live; (c) an
    untouched node outside the shortlist still scores exactly its sc0
    ≤ thresh. So when the candidate-set winner's score beats `thresh`
    strictly — or ties it while itself untouched (see shortlist_prefilter
    on why the index tie-break then also goes the winner's way) — it is
    the full N-wide argmax. Otherwise the step falls back to the full row:
    inline via lax.cond when `inline_fallback` (single-order scans — the
    cond executes one branch), or by poisoning the whole scan when the
    caller runs under vmap (lax.cond would become a both-branches select
    there and the pruning would buy nothing).

    Untouched candidates gather their score from sc0 rather than
    recomputing it, so the `== thresh` comparison never straddles two
    float evaluations of the same quantity.

    sc0: (S,N) class-level chunk-start scores; sl_class: (P,) row index
    per pod (pods of one template share a class — and a shortlist);
    sl_cand: (P,K); sl_thresh: (P,); has_node: (P,) bool — pods whose
    static mask is empty (padding, unknown resources) trivially resolve
    to -1 with no fallback.

    `rows` (P,) maps each step to its pod's row in the UNPERMUTED
    mask/static_scores planes (class planes — (C, N); C == P in the
    per-pod degenerate form), which stay closed-over: the trusted path
    reads them through (row, ci) element gathers, never a row slice
    — an (N,)-wide xs row per step would put O(N) memory traffic back
    into the scan (and a permuted multistart copy would materialize the
    planes once per order). Only the fallback branch slices a full row,
    and only when taken. `exc` (optional (P,)) is the per-pod
    single-allowed-column exception: candidates outside it are
    infeasible for the pod, so a pinned pod whose column misses the
    class shortlist resolves through the bound-check fallback (all its
    candidates mask out → not trusted unless the shortlist already held
    every feasible class column).

    Returns (assign (P,), fallbacks int32, poisoned bool). With
    inline_fallback the assignment is exact and poisoned is always False;
    without it the assignment is only valid when poisoned is False.
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    iota_n = jnp.arange(n, dtype=jnp.int32)

    def step(carry, inp):
        free_q, free_pods, used_nz, touched, tidx, kstep, nfall, pois = carry
        if exc is None:
            req, req_nz, row, cand, t, cls, hn = inp
        else:
            req, req_nz, row, cand, t, cls, hn, e = inp
        cset = jnp.concatenate([cand, tidx])               # (K+P,)
        valid = cset < n
        ci = jnp.where(valid, cset, 0)
        live = static_scores[row, ci]
        live = live + w_fit * kernels.fit_score(
            alloc_q[ci], used_nz[ci], req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        live = live + w_bal * kernels.balanced_allocation_score(
            alloc_q[ci], used_nz[ci], req_nz[None, :], bal_col_mask)[0]
        live = jnp.where(touched[ci], live, sc0[cls, ci])
        fits = mask[row, ci] & valid \
            & jnp.all(req[None, :] <= free_q[ci], axis=1) \
            & (free_pods[ci] >= 1)
        if exc is not None:
            fits = fits & ((e < 0) | (ci == e))
        masked = jnp.where(fits, live, NEG_INF)
        best = jnp.max(masked)
        any_fit = best > NEG_INF
        widx = jnp.min(jnp.where(masked == best, ci, n)).astype(jnp.int32)
        w_touched = touched[jnp.minimum(widx, n - 1)]
        trusted = jnp.where(
            any_fit,
            (best > t) | ((best == t) & jnp.logical_not(w_touched)),
            t == NEG_INF) | jnp.logical_not(hn)
        sl_idx = jnp.where(any_fit, widx, jnp.int32(-1))
        if inline_fallback:
            def full_row(_):
                fits_n = mask[row] & jnp.all(req[None, :] <= free_q, axis=1) \
                    & (free_pods >= 1)
                if exc is not None:
                    fits_n = fits_n & ((e < 0) | (iota_n == e))
                sc = static_scores[row]
                sc = sc + w_fit * kernels.fit_score(
                    alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
                    shape_u, shape_s)[0]
                sc = sc + w_bal * kernels.balanced_allocation_score(
                    alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
                mk = jnp.where(fits_n, sc, NEG_INF)
                i2 = jnp.argmax(mk).astype(jnp.int32)
                return jnp.where(jnp.any(fits_n), i2, jnp.int32(-1))

            idx = lax.cond(trusted, lambda _: sl_idx, full_row, None)
        else:
            idx = sl_idx
            pois = pois | jnp.logical_not(trusted)
        nfall = nfall + jnp.logical_not(trusted).astype(jnp.int32)
        # Scatter updates (O(R), not O(N·R) — the whole point is that no
        # per-step work scales with N on the trusted path).
        hit = idx >= 0
        safe = jnp.clip(idx, 0, n - 1)
        free_q = free_q.at[safe].add(
            jnp.where(hit, -req, 0).astype(free_q.dtype))
        free_pods = free_pods.at[safe].add(
            jnp.where(hit, -1, 0).astype(free_pods.dtype))
        used_nz = used_nz.at[safe].add(
            jnp.where(hit, req_nz, 0).astype(used_nz.dtype))
        touched = touched.at[safe].set(touched[safe] | hit)
        tidx = tidx.at[kstep].set(jnp.where(hit, idx, n))
        return (free_q, free_pods, used_nz, touched, tidx, kstep + 1,
                nfall, pois), idx

    carry0 = (free_q, free_pods, used_nz_q,
              jnp.zeros((n,), jnp.bool_),
              jnp.full((p,), n, jnp.int32),
              jnp.int32(0), jnp.int32(0), jnp.bool_(False))
    xs = (req_q, req_nz_q, rows, sl_cand, sl_thresh, sl_class, has_node)
    if exc is not None:
        xs = xs + (exc,)
    (_, _, _, _, _, _, nfall, pois), assign = lax.scan(step, carry0, xs)
    return assign, nfall, pois


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring_shortlist(req_q, req_nz_q, free_q, free_pods,
                                      used_nz_q, alloc_q, mask,
                                      static_scores, fit_col_w, bal_col_mask,
                                      shape_u, shape_s, w_fit, w_bal,
                                      strategy: str,
                                      sc0, sl_class, sl_cand, sl_thresh,
                                      has_node, rows=None, exc=None):
    """greedy_assign_rescoring, shortlist-pruned: bit-identical assignments
    at O(P·(K+P)) with per-step inline fallback to the full N-wide row
    (the lax.cond executes one branch — fallbacks cost O(N) only when
    taken). Returns (assign (P,), fallbacks int32)."""
    if rows is None:
        rows = jnp.arange(req_q.shape[0], dtype=jnp.int32)
    assign, nfall, _ = _shortlist_scan(
        req_q, req_nz_q, rows, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy, sc0, sl_class, sl_cand, sl_thresh,
        has_node, inline_fallback=True, exc=exc)
    return assign, nfall


@partial(jax.jit, static_argnames=("strategy",))
def multistart_greedy_assign_shortlist(req_q, req_nz_q, free_q, free_pods,
                                       used_nz_q, alloc_q, mask,
                                       static_scores, fit_col_w,
                                       bal_col_mask, shape_u, shape_s,
                                       w_fit, w_bal, strategy: str, perms,
                                       gang_onehot, gang_required,
                                       sc0, sl_class, sl_cand, sl_thresh,
                                       has_node, rows=None, exc=None):
    """multistart_greedy_assign, shortlist-pruned.

    The K permuted scans run vmapped, so a per-step lax.cond would lower
    to a both-branches select and re-pay the N-wide row every step — the
    narrow scans instead mark any step whose bound check fails as
    POISONED, and one outer lax.cond (not vmapped — a real branch) reruns
    the whole chunk through the full multistart when any order was
    poisoned. Shortlist/threshold are chunk-start state, so they are
    permutation-independent; only per-pod rows reorder.

    Returns (assign (P,), fallback_pods int32) — fallback accounting is
    whole-chunk here (P on a poisoned chunk, 0 otherwise)."""
    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)
    if rows is None:
        rows = arange_p

    def one(perm):
        # Only the small per-pod vectors permute; the class planes stay
        # unpermuted and the scan addresses them through `rows[perm]` —
        # permuting them here would materialize one copy per order.
        a, _, pois = _shortlist_scan(
            req_q[perm], req_nz_q[perm], rows[perm], free_q, free_pods,
            used_nz_q, alloc_q, mask, static_scores, fit_col_w,
            bal_col_mask, shape_u, shape_s, w_fit, w_bal, strategy,
            sc0, sl_class[perm], sl_cand[perm], sl_thresh[perm],
            has_node[perm], inline_fallback=False,
            exc=None if exc is None else exc[perm])
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv], pois

    assigns, pois = jax.vmap(one)(perms)
    any_pois = jnp.any(pois)

    def full(_):
        return _multistart_body(
            req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
            static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal, strategy, perms, gang_onehot, gang_required,
            rows, exc)

    def take(_):
        return _select_best(assigns, req_q, gang_onehot, gang_required)

    assign = lax.cond(any_pois, full, take, None)
    return assign, jnp.where(any_pois, jnp.int32(P), jnp.int32(0))


@partial(jax.jit, static_argnames=("strategy",))
def greedy_assign_rescoring_spread_shortlist(
        req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy: str,
        dom_onehot, cid_onehot, dom_counts, max_skew, min_ok, has_key_nc,
        applies, contributes,
        sc0, sl_class, sl_cand, sl_thresh, has_node, rows=None, exc=None):
    """greedy_assign_rescoring_spread, shortlist-pruned (identity order,
    inline per-step fallback like the non-spread scan).

    Spread gating is non-monotone (a domain can open as the global min
    rises), so it deliberately plays no part in the prefilter: shortlist
    and threshold are capacity/mask/score-only — an outside node's SCORE
    is still bounded by the threshold whatever its gating does, and the
    in-scan candidate set applies the exact per-step gate. Conservative
    only: a pod whose allowed domains all sit outside its score head
    falls back to the full row.

    Returns (assign (P,), dom_counts', fallbacks int32)."""
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    big = jnp.float32(1e30)
    iota_n = jnp.arange(n, dtype=jnp.int32)
    in_dom_nc = (dom_onehot @ cid_onehot) > 0                          # (N,C)
    gate_nc = has_key_nc > 0

    rows_p = jnp.arange(p, dtype=jnp.int32) if rows is None else rows

    def step(carry, inp):
        (free_q, free_pods, used_nz, dcounts, touched, tidx, kstep,
         nfall) = carry
        if exc is None:
            req, req_nz, row, app, contrib, cand, t, cls, hn = inp
        else:
            req, req_nz, row, app, contrib, cand, t, cls, hn, e = inp
        min_c = jnp.min(
            jnp.where(cid_onehot > 0, dcounts[:, None], big), axis=0)  # (C,)
        min_c = min_c * min_ok
        self_d = cid_onehot @ contrib                                  # (D,)
        allowed_d = (dcounts + self_d - cid_onehot @ min_c) \
            <= (cid_onehot @ max_skew)                                 # (D,)
        allowed_dc = allowed_d[:, None] * cid_onehot                   # (D,C)

        cset = jnp.concatenate([cand, tidx])
        valid = cset < n
        ci = jnp.where(valid, cset, 0)
        in_allowed_c = (dom_onehot[ci] @ allowed_dc) > 0               # (C',C)
        node_ok_c = gate_nc[ci] & (
            in_allowed_c | jnp.logical_not(in_dom_nc[ci]))
        spread_ok_c = jnp.all(node_ok_c | (app[None, :] == 0), axis=1)
        live = static_scores[row, ci]
        live = live + w_fit * kernels.fit_score(
            alloc_q[ci], used_nz[ci], req_nz[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        live = live + w_bal * kernels.balanced_allocation_score(
            alloc_q[ci], used_nz[ci], req_nz[None, :], bal_col_mask)[0]
        live = jnp.where(touched[ci], live, sc0[cls, ci])
        fits = mask[row, ci] & valid & spread_ok_c \
            & jnp.all(req[None, :] <= free_q[ci], axis=1) \
            & (free_pods[ci] >= 1)
        if exc is not None:
            fits = fits & ((e < 0) | (ci == e))
        masked = jnp.where(fits, live, NEG_INF)
        best = jnp.max(masked)
        any_fit = best > NEG_INF
        widx = jnp.min(jnp.where(masked == best, ci, n)).astype(jnp.int32)
        w_touched = touched[jnp.minimum(widx, n - 1)]
        trusted = jnp.where(
            any_fit,
            (best > t) | ((best == t) & jnp.logical_not(w_touched)),
            t == NEG_INF) | jnp.logical_not(hn)
        sl_idx = jnp.where(any_fit, widx, jnp.int32(-1))

        def full_row(_):
            in_allowed = (dom_onehot @ allowed_dc) > 0
            node_c_ok = gate_nc & (in_allowed | jnp.logical_not(in_dom_nc))
            spread_ok = jnp.all(node_c_ok | (app[None, :] == 0), axis=1)
            fits_n = mask[row] & jnp.all(req[None, :] <= free_q, axis=1) \
                & (free_pods >= 1) & spread_ok
            if exc is not None:
                fits_n = fits_n & ((e < 0) | (iota_n == e))
            sc = static_scores[row]
            sc = sc + w_fit * kernels.fit_score(
                alloc_q, used_nz, req_nz[None, :], fit_col_w, strategy,
                shape_u, shape_s)[0]
            sc = sc + w_bal * kernels.balanced_allocation_score(
                alloc_q, used_nz, req_nz[None, :], bal_col_mask)[0]
            mk = jnp.where(fits_n, sc, NEG_INF)
            i2 = jnp.argmax(mk).astype(jnp.int32)
            return jnp.where(jnp.any(fits_n), i2, jnp.int32(-1))

        idx = lax.cond(trusted, lambda _: sl_idx, full_row, None)
        nfall = nfall + jnp.logical_not(trusted).astype(jnp.int32)
        hit = idx >= 0
        safe = jnp.clip(idx, 0, n - 1)
        free_q = free_q.at[safe].add(
            jnp.where(hit, -req, 0).astype(free_q.dtype))
        free_pods = free_pods.at[safe].add(
            jnp.where(hit, -1, 0).astype(free_pods.dtype))
        used_nz = used_nz.at[safe].add(
            jnp.where(hit, req_nz, 0).astype(used_nz.dtype))
        # Same accounting as the full spread scan's `hit @ dom_onehot`,
        # via one row gather instead of an O(N·D) reduce.
        dcounts = dcounts + jnp.where(
            hit, dom_onehot[safe] * (cid_onehot @ contrib), 0.0)
        touched = touched.at[safe].set(touched[safe] | hit)
        tidx = tidx.at[kstep].set(jnp.where(hit, idx, n))
        return (free_q, free_pods, used_nz, dcounts, touched, tidx,
                kstep + 1, nfall), idx

    carry0 = (free_q, free_pods, used_nz_q, dom_counts,
              jnp.zeros((n,), jnp.bool_),
              jnp.full((p,), n, jnp.int32),
              jnp.int32(0), jnp.int32(0))
    xs = (req_q, req_nz_q, rows_p, applies, contributes,
          sl_cand, sl_thresh, sl_class, has_node)
    if exc is not None:
        xs = xs + (exc,)
    (_, _, _, dom_counts2, _, _, _, nfall), assign = lax.scan(
        step, carry0, xs)
    return assign, dom_counts2, nfall


# ---------------------------------------------------------------------------
# Speculative wavefront scans: W pods per scan step with exact conflict
# replay. The serial scans above are bound by their LENGTH — P sequential
# steps, each a chain of small ops — while the r14 class planes make a
# W-wide evaluation of the same step nearly as cheap as a 1-wide one.
#
# Per wave step:
#   1. evaluate all W pods against the same carry state (one (W,·) pass
#      over the closed-over class planes);
#   2. pick PREFIX-DISTINCT speculative choices: member w takes the best
#      node not picked by members 0..w-1 (max score, lowest node index
#      among ties — the serial argmax rule over the not-yet-debited set);
#   3. prove each speculation serial-equivalent with a pairwise conflict
#      check: member w's pick stands iff no earlier member's committed
#      node, RE-SCORED after its own debit, would beat member w's pick
#      under the serial (score, lowest-index) order. Debits usually only
#      lower a node's score (LeastAllocated), but not always (a debit can
#      RAISE MostAllocated/BalancedAllocation scores and serial greedy
#      then re-picks the same node) — the check re-scores instead of
#      assuming monotonicity, so speculation is exact by proof, not hope;
#   4. commit the whole wave's debits in one scatter when no member
#      conflicts; otherwise REPLAY the wave serially (lax.fori_loop of
#      the one-pod step body) — reproducing the serial order exactly.
#
# Untouched nodes keep bitwise-identical scores across a wave (the score
# kernels are elementwise per node), so the only nodes whose serial value
# can differ from the wave evaluation are the ≤W wave commits — exactly
# the set the pairwise check re-scores. Assignments are therefore
# bit-identical to the W=1 scans at every wave width; only the replay
# fraction (observability, tuner feedback) is workload-dependent.
# ---------------------------------------------------------------------------


def _wave_split(wave_w: int, arrays):
    """Pad the pod axis to a multiple of wave_w and reshape each array to
    (n_waves, wave_w, ...) for wave-by-wave scanning. Returns the reshaped
    arrays plus the matching real-pod mask (padding members never fit,
    never commit, never conflict) and the padded length."""
    p = arrays[0].shape[0]
    pad = (-p) % wave_w
    out = []
    for a in arrays:
        if pad:
            a = jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)
        out.append(a.reshape((-1, wave_w) + a.shape[1:]))
    real = (jnp.arange(p + pad, dtype=jnp.int32) < p).reshape(-1, wave_w)
    return out, real, p + pad


def _wave_spec_picks(masked, node_of, nbig, wave_w: int):
    """Prefix-distinct speculative picks for one wave.

    masked: (W, M) candidate scores with NEG_INF = infeasible; node_of:
    (W, M) int32 global node id per slot (slots may repeat a node — the
    shortlist candidate set does); nbig: "no pick" sentinel greater than
    every node id. Member w's pick is the max value over slots whose node
    no earlier member picked, resolved to the LOWEST node id among ties —
    the serial argmax rule over the not-yet-debited nodes. The loop is
    unrolled (W is static) over tiny fused compares; no top-k is involved,
    so tie resolution is exact even when many slots share the max value.

    Returns (b (W,) f32 scores, y (W,) int32 node ids, nbig = no pick).
    """
    bs, ys = [], []
    for w in range(wave_w):
        row = masked[w]
        for yp in ys:
            row = jnp.where(node_of[w] == yp, NEG_INF, row)
        b = jnp.max(row)
        y = jnp.min(jnp.where(row == b, node_of[w], nbig))
        ys.append(jnp.where(b > NEG_INF, y, nbig).astype(jnp.int32))
        bs.append(b)
    return jnp.stack(bs), jnp.stack(ys)


def _wave_conflicts(b, y, nbig, req, req_nz, free_q, free_pods, used_nz,
                    alloc_q, m_pair, stat_pair, fit_col_w, bal_col_mask,
                    shape_u, shape_s, w_fit, w_bal, strategy,
                    extra_ok=None):
    """(W,) conflict bits: member w's speculative pick is invalidated by
    an earlier member's commit in the same wave.

    For each committed node y_j (j < w), re-score it FOR POD w after pod
    j's debit (used_nz[y_j] + req_nz_j, free[y_j] - req_j) with the same
    elementwise kernels the serial step uses; member w conflicts iff some
    y_j stays feasible for it and beats its pick under the serial order —
    strictly higher score, or equal score at a lower node index. A member
    with no pick (b = -inf) conflicts whenever any earlier commit is
    still feasible for it (serial might place it there). `m_pair`
    (W, W): pod w's static mask at node y_j; `stat_pair` (W, W): pod w's
    capacity-independent score at y_j; `extra_ok` optionally folds a
    variant-specific gate (spread) into feasibility. Prefix-distinct
    picks never collide, so node identity conflicts cannot occur — only
    score movement on debited nodes can, and that is exactly what is
    re-checked.
    """
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    W = y.shape[0]
    n = free_q.shape[0]
    hit = y < nbig
    safe = jnp.minimum(y, n - 1)
    fr_j = free_q[safe] - req                                  # (W,R)
    fp_j = free_pods[safe] - 1                                 # (W,)
    unz_j = used_nz[safe] + req_nz                             # (W,R)
    al_j = alloc_q[safe]
    upd = stat_pair + w_fit * kernels.fit_score(
        al_j, unz_j, req_nz, fit_col_w, strategy, shape_u, shape_s)
    upd = upd + w_bal * kernels.balanced_allocation_score(
        al_j, unz_j, req_nz, bal_col_mask)                     # (W,W)
    cap = jnp.all(req[:, None, :] <= fr_j[None, :, :], axis=-1)
    feas = m_pair & cap & (fp_j >= 1)[None, :] & hit[None, :]
    if extra_ok is not None:
        feas = feas & extra_ok
    beats = feas & ((upd > b[:, None])
                    | ((upd == b[:, None]) & (y[None, :] < y[:, None])))
    w_iota = jnp.arange(W, dtype=jnp.int32)
    tri = w_iota[None, :] < w_iota[:, None]                    # j < w
    return jnp.any(beats & tri, axis=1)


def _rescoring_wave_scan(req_q, req_nz_q, free_q, free_pods, used_nz_q,
                         alloc_q, mask, static_scores, fit_col_w,
                         bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                         strategy: str, wave_w: int, rows, exc,
                         poison: bool):
    """Traceable wavefront core of greedy_assign_rescoring.

    poison=False: conflicted waves take the in-step serial replay branch
    (a real lax.cond — only taken waves pay it), so the result is exact.
    poison=True is the vmapped-multistart shape (a cond under vmap lowers
    to a both-branches select, re-paying the serial wave every step):
    speculation always commits, the first conflict POISONS the scan, and
    the caller discards poisoned results — same contract as the shortlist
    multistart. Returns (assign, commits, replays, poisoned)."""
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    W = max(1, min(wave_w, p))
    iota_n = jnp.arange(n, dtype=jnp.int32)
    ex = jnp.full((p,), -1, jnp.int32) if exc is None else exc
    (req_w, req_nz_w, rows_w, ex_w), real_w, _ = _wave_split(
        W, (req_q, req_nz_q, rows, ex))

    def wave_step(carry, inp):
        free_q, free_pods, used_nz, ncom, nrep, pois = carry
        req, req_nz, row, e, real = inp
        m = mask[row]                                          # (W,N)
        m = m & ((e < 0)[:, None] | (iota_n[None, :] == e[:, None]))
        m = m & real[:, None]
        fits = m & jnp.all(req[:, None, :] <= free_q[None, :, :], axis=-1) \
            & (free_pods >= 1)[None, :]
        sc = static_scores[row]
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz, fit_col_w, strategy, shape_u, shape_s)
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz, bal_col_mask)
        masked = jnp.where(fits, sc, NEG_INF)
        node_of = jnp.broadcast_to(iota_n[None, :], masked.shape)
        b, y = _wave_spec_picks(masked, node_of, n, W)
        safe = jnp.minimum(y, n - 1)
        conflict = _wave_conflicts(
            b, y, n, req, req_nz, free_q, free_pods, used_nz, alloc_q,
            m[:, safe], static_scores[row[:, None], safe[None, :]],
            fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
            strategy)
        nreal = jnp.sum(real.astype(jnp.int32))

        def fast(st):
            fq, fp, unz, nc, nr, po = st
            hit = y < n
            fq = fq.at[safe].add(
                jnp.where(hit[:, None], -req, 0).astype(fq.dtype))
            fp = fp.at[safe].add(jnp.where(hit, -1, 0).astype(fp.dtype))
            unz = unz.at[safe].add(
                jnp.where(hit[:, None], req_nz, 0).astype(unz.dtype))
            return (fq, fp, unz, nc + nreal, nr, po), \
                jnp.where(hit, y, jnp.int32(-1))

        if poison:
            carry2, out = fast((free_q, free_pods, used_nz, ncom, nrep,
                                pois | jnp.any(conflict)))
            return carry2, out

        def slow(st):
            fq, fp, unz, nc, nr, po = st

            def body(w, s):
                fq, fp, unz, out = s
                rq, rnz = req[w], req_nz[w]
                fits_w = m[w] & jnp.all(rq[None, :] <= fq, axis=1) \
                    & (fp >= 1)
                scw = static_scores[row[w]]
                scw = scw + w_fit * kernels.fit_score(
                    alloc_q, unz, rnz[None, :], fit_col_w, strategy,
                    shape_u, shape_s)[0]
                scw = scw + w_bal * kernels.balanced_allocation_score(
                    alloc_q, unz, rnz[None, :], bal_col_mask)[0]
                mk = jnp.where(fits_w, scw, NEG_INF)
                idx = jnp.argmax(mk).astype(jnp.int32)
                idx = jnp.where(jnp.any(fits_w), idx, jnp.int32(-1))
                hitw = idx >= 0
                sf = jnp.clip(idx, 0, n - 1)
                fq = fq.at[sf].add(jnp.where(hitw, -rq, 0).astype(fq.dtype))
                fp = fp.at[sf].add(jnp.where(hitw, -1, 0).astype(fp.dtype))
                unz = unz.at[sf].add(
                    jnp.where(hitw, rnz, 0).astype(unz.dtype))
                return (fq, fp, unz, out.at[w].set(idx))

            fq, fp, unz, out = lax.fori_loop(
                0, W, body, (fq, fp, unz, jnp.full((W,), -1, jnp.int32)))
            return (fq, fp, unz, nc, nr + nreal, po), out

        return lax.cond(jnp.any(conflict), slow, fast,
                        (free_q, free_pods, used_nz, ncom, nrep, pois))

    carry0 = (free_q, free_pods, used_nz_q, jnp.int32(0), jnp.int32(0),
              jnp.bool_(False))
    (_, _, _, ncom, nrep, pois), out = lax.scan(
        wave_step, carry0, (req_w, req_nz_w, rows_w, ex_w, real_w))
    return out.reshape(-1)[:p], ncom, nrep, pois


@partial(jax.jit, static_argnames=("strategy", "wave_w"))
def greedy_assign_rescoring_wave(req_q, req_nz_q, free_q, free_pods,
                                 used_nz_q, alloc_q, mask, static_scores,
                                 fit_col_w, bal_col_mask, shape_u, shape_s,
                                 w_fit, w_bal, strategy: str, wave_w: int,
                                 rows=None, exc=None):
    """greedy_assign_rescoring, W pods per scan step (see the wavefront
    section comment for the speculation/replay contract). Assignments are
    bit-identical to the W=1 scan at every wave_w; wave_w=1 runs the
    degenerate one-member wave. Returns (assign (P,), commits int32,
    replays int32) — the commit/replay split is the tuner's feedback
    signal (replays are exact but serial)."""
    if rows is None:
        rows = jnp.arange(req_q.shape[0], dtype=jnp.int32)
    assign, ncom, nrep, _ = _rescoring_wave_scan(
        req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy, wave_w, rows, exc, poison=False)
    return assign, ncom, nrep


@partial(jax.jit, static_argnames=("strategy", "wave_w"))
def multistart_greedy_assign_wave(req_q, req_nz_q, free_q, free_pods,
                                  used_nz_q, alloc_q, mask, static_scores,
                                  fit_col_w, bal_col_mask, shape_u, shape_s,
                                  w_fit, w_bal, strategy: str, wave_w: int,
                                  perms, gang_onehot, gang_required,
                                  rows=None, exc=None):
    """multistart_greedy_assign with wavefront scans under the vmap.

    The K permuted scans run vmapped, so the per-wave replay cond would
    lower to a both-branches select — instead every order runs
    speculation-only and POISONS on its first conflict, and one outer
    lax.cond (a real branch) reruns the whole chunk through the W=1
    multistart when any order was poisoned (the shortlist-multistart
    contract). Returns (assign (P,), commits int32, replays int32);
    counters are whole-chunk on the poisoned path (P replays)."""
    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)
    if rows is None:
        rows = arange_p

    def one(perm):
        a, _, _, pois = _rescoring_wave_scan(
            req_q[perm], req_nz_q[perm], free_q, free_pods, used_nz_q,
            alloc_q, mask, static_scores, fit_col_w, bal_col_mask,
            shape_u, shape_s, w_fit, w_bal, strategy, wave_w, rows[perm],
            None if exc is None else exc[perm], poison=True)
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv], pois

    assigns, pois = jax.vmap(one)(perms)
    any_pois = jnp.any(pois)

    def full(_):
        return _multistart_body(
            req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
            static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal, strategy, perms, gang_onehot, gang_required,
            rows, exc)

    def take(_):
        return _select_best(assigns, req_q, gang_onehot, gang_required)

    assign = lax.cond(any_pois, full, take, None)
    ncom = jnp.where(any_pois, jnp.int32(0), jnp.int32(P))
    nrep = jnp.where(any_pois, jnp.int32(P), jnp.int32(0))
    return assign, ncom, nrep


@partial(jax.jit, static_argnames=("strategy", "wave_w", "interpret"))
def greedy_assign_rescoring_wave_pallas(req_q, req_nz_q, free_q,
                                        free_pods, used_nz_q, alloc_q,
                                        mask, static_scores, fit_col_w,
                                        bal_col_mask, shape_u, shape_s,
                                        w_fit, w_bal, strategy: str,
                                        wave_w: int, rows=None, exc=None,
                                        interpret: bool = True):
    """greedy_assign_rescoring_wave with the wave scan replaced by the
    fused Pallas kernel (ops/pallas_kernel.py) — one grid step per wave,
    carry resident, in-step serial replay of conflicted waves inside the
    kernel. Same signature, same returns, assignments bit-identical to
    the scan at every wave_w (the kernel body runs the identical op
    sequence); the scan stays the semantic reference and the router's
    fallback target. interpret=True validates on CPU; False compiles
    (accelerator backends only)."""
    from kubernetes_tpu.ops import pallas_kernel  # local: import cycle

    if rows is None:
        rows = jnp.arange(req_q.shape[0], dtype=jnp.int32)
    assign, ncom, nrep, _ = pallas_kernel.wave_solve(
        req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy, wave_w, rows, exc, poison=False,
        perms=None, interpret=interpret)
    return assign[0], ncom[0], nrep[0]


@partial(jax.jit, static_argnames=("strategy", "wave_w", "interpret"))
def multistart_greedy_assign_wave_pallas(req_q, req_nz_q, free_q,
                                         free_pods, used_nz_q, alloc_q,
                                         mask, static_scores, fit_col_w,
                                         bal_col_mask, shape_u, shape_s,
                                         w_fit, w_bal, strategy: str,
                                         wave_w: int, perms, gang_onehot,
                                         gang_required, rows=None,
                                         exc=None,
                                         interpret: bool = True):
    """multistart_greedy_assign_wave with the K vmapped wave scans
    replaced by ONE fused pallas_call whose grid major axis is the order
    index k (each order owns its carry block). The poison contract, the
    outer replay cond, and `_select_best` are byte-for-byte the scan
    wrapper's — only the per-order speculation is fused — so the result
    is bit-identical whenever the per-order speculative assigns are,
    which the differential suite checks at every W."""
    from kubernetes_tpu.ops import pallas_kernel  # local: import cycle

    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)
    if rows is None:
        rows = arange_p
    assigns_p, _, _, pois = pallas_kernel.wave_solve(
        req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
        static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy, wave_w, rows, exc, poison=True,
        perms=perms, interpret=interpret)

    def unperm(a, perm):
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv]

    assigns = jax.vmap(unperm)(assigns_p, perms)
    any_pois = jnp.any(pois)

    def full(_):
        return _multistart_body(
            req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
            static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal, strategy, perms, gang_onehot, gang_required,
            rows, exc)

    def take(_):
        return _select_best(assigns, req_q, gang_onehot, gang_required)

    assign = lax.cond(any_pois, full, take, None)
    ncom = jnp.where(any_pois, jnp.int32(0), jnp.int32(P))
    nrep = jnp.where(any_pois, jnp.int32(P), jnp.int32(0))
    return assign, ncom, nrep


@partial(jax.jit, static_argnames=("strategy", "wave_w"))
def greedy_assign_rescoring_spread_wave(req_q, req_nz_q, free_q, free_pods,
                                        used_nz_q, alloc_q, mask,
                                        static_scores, fit_col_w,
                                        bal_col_mask, shape_u, shape_s,
                                        w_fit, w_bal, strategy: str,
                                        wave_w: int,
                                        dom_onehot, cid_onehot, dom_counts,
                                        max_skew, min_ok, has_key_nc,
                                        applies, contributes, rows=None,
                                        exc=None):
    """greedy_assign_rescoring_spread, W pods per scan step with per-wave
    domain-count updates.

    Spread gating is NON-monotone in the carry — a commit that moves a
    domain count can OPEN another domain for later pods (the global-min
    rise), so an earlier commit can change a later member's feasible SET
    upward, which the capacity/score conflict check cannot see. The
    conflict predicate therefore adds the exact structural rule: member w
    replays whenever any earlier member committed a placement that moves
    any domain count (contributes to any constraint) AND member w carries
    a gating constraint itself; gate-free members (applies all-zero) ride
    the capacity/score rule alone, with the wave-start spread gate folded
    into the pairwise feasibility. Domain counts commit per wave (exact:
    counts are small integers in f32, addition order immaterial).
    Returns (assign (P,), dom_counts', commits, replays)."""
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    W = max(1, min(wave_w, p))
    big = jnp.float32(1e30)
    iota_n = jnp.arange(n, dtype=jnp.int32)
    in_dom_nc = (dom_onehot @ cid_onehot) > 0                          # (N,C)
    gate_nc = has_key_nc > 0
    if rows is None:
        rows = jnp.arange(p, dtype=jnp.int32)
    ex = jnp.full((p,), -1, jnp.int32) if exc is None else exc
    (req_w, req_nz_w, rows_w, app_w, con_w, ex_w), real_w, _ = _wave_split(
        W, (req_q, req_nz_q, rows, applies, contributes, ex))

    def spread_gate(dcounts, contrib, app):
        """(W,N) DoNotSchedule gate at the given counts — the serial
        step's gate, batched over the wave (each member folds its own
        selfMatch term)."""
        min_c = jnp.min(
            jnp.where(cid_onehot > 0, dcounts[:, None], big), axis=0)
        min_c = min_c * min_ok                                         # (C,)
        self_d = contrib @ cid_onehot.T                                # (W,D)
        allowed_d = (dcounts[None, :] + self_d
                     - (cid_onehot @ min_c)[None, :]) \
            <= (cid_onehot @ max_skew)[None, :]                        # (W,D)
        in_allowed = jnp.einsum(
            "nd,wdc->wnc", dom_onehot,
            allowed_d[:, :, None] * cid_onehot[None, :, :]) > 0        # (W,N,C)
        node_c_ok = gate_nc[None, :, :] \
            & (in_allowed | jnp.logical_not(in_dom_nc)[None, :, :])
        return jnp.all(node_c_ok | (app[:, None, :] == 0), axis=2)     # (W,N)

    def wave_step(carry, inp):
        free_q, free_pods, used_nz, dcounts, ncom, nrep = carry
        req, req_nz, row, app, contrib, e, real = inp
        m = mask[row]
        m = m & ((e < 0)[:, None] | (iota_n[None, :] == e[:, None]))
        m = m & real[:, None]
        sp_ok = spread_gate(dcounts, contrib, app)                     # (W,N)
        fits = m & sp_ok \
            & jnp.all(req[:, None, :] <= free_q[None, :, :], axis=-1) \
            & (free_pods >= 1)[None, :]
        sc = static_scores[row]
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz, fit_col_w, strategy, shape_u, shape_s)
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz, bal_col_mask)
        masked = jnp.where(fits, sc, NEG_INF)
        node_of = jnp.broadcast_to(iota_n[None, :], masked.shape)
        b, y = _wave_spec_picks(masked, node_of, n, W)
        safe = jnp.minimum(y, n - 1)
        hit = y < n
        conflict = _wave_conflicts(
            b, y, n, req, req_nz, free_q, free_pods, used_nz, alloc_q,
            m[:, safe], static_scores[row[:, None], safe[None, :]],
            fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
            strategy, extra_ok=sp_ok[:, safe])
        # The structural non-monotonicity rule: any earlier count-moving
        # commit forces gated members into the serial replay.
        movers = hit & jnp.any(contrib > 0, axis=1)                    # (W,)
        earlier_moved = jnp.cumsum(movers.astype(jnp.int32)) \
            - movers.astype(jnp.int32) > 0
        conflict = conflict | (earlier_moved & jnp.any(app > 0, axis=1))
        nreal = jnp.sum(real.astype(jnp.int32))

        def fast(st):
            fq, fp, unz, dc, nc, nr = st
            fq = fq.at[safe].add(
                jnp.where(hit[:, None], -req, 0).astype(fq.dtype))
            fp = fp.at[safe].add(jnp.where(hit, -1, 0).astype(fp.dtype))
            unz = unz.at[safe].add(
                jnp.where(hit[:, None], req_nz, 0).astype(unz.dtype))
            add = jnp.where(hit[:, None],
                            dom_onehot[safe] * (contrib @ cid_onehot.T),
                            0.0)                                       # (W,D)
            dc = dc + jnp.sum(add, axis=0)
            return (fq, fp, unz, dc, nc + nreal, nr), \
                jnp.where(hit, y, jnp.int32(-1))

        def slow(st):
            fq, fp, unz, dc, nc, nr = st

            def body(w, s):
                fq, fp, unz, dc, out = s
                rq, rnz = req[w], req_nz[w]
                sp_w = spread_gate(dc, contrib[w][None, :],
                                   app[w][None, :])[0]
                fits_w = m[w] & sp_w \
                    & jnp.all(rq[None, :] <= fq, axis=1) & (fp >= 1)
                scw = static_scores[row[w]]
                scw = scw + w_fit * kernels.fit_score(
                    alloc_q, unz, rnz[None, :], fit_col_w, strategy,
                    shape_u, shape_s)[0]
                scw = scw + w_bal * kernels.balanced_allocation_score(
                    alloc_q, unz, rnz[None, :], bal_col_mask)[0]
                mk = jnp.where(fits_w, scw, NEG_INF)
                idx = jnp.argmax(mk).astype(jnp.int32)
                idx = jnp.where(jnp.any(fits_w), idx, jnp.int32(-1))
                hitw = idx >= 0
                sf = jnp.clip(idx, 0, n - 1)
                fq = fq.at[sf].add(jnp.where(hitw, -rq, 0).astype(fq.dtype))
                fp = fp.at[sf].add(jnp.where(hitw, -1, 0).astype(fp.dtype))
                unz = unz.at[sf].add(
                    jnp.where(hitw, rnz, 0).astype(unz.dtype))
                dc = dc + jnp.where(
                    hitw, dom_onehot[sf] * (cid_onehot @ contrib[w]), 0.0)
                return (fq, fp, unz, dc, out.at[w].set(idx))

            fq, fp, unz, dc, out = lax.fori_loop(
                0, W, body,
                (fq, fp, unz, dc, jnp.full((W,), -1, jnp.int32)))
            return (fq, fp, unz, dc, nc, nr + nreal), out

        return lax.cond(jnp.any(conflict), slow, fast,
                        (free_q, free_pods, used_nz, dcounts, ncom, nrep))

    carry0 = (free_q, free_pods, used_nz_q, dom_counts,
              jnp.int32(0), jnp.int32(0))
    (_, _, _, dom_counts2, ncom, nrep), out = lax.scan(
        wave_step, carry0,
        (req_w, req_nz_w, rows_w, app_w, con_w, ex_w, real_w))
    return out.reshape(-1)[:p], dom_counts2, ncom, nrep


def _shortlist_wave_scan(req_q, req_nz_q, rows, free_q, free_pods,
                         used_nz_q, alloc_q, mask, static_scores, fit_col_w,
                         bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                         strategy: str, wave_w: int,
                         sc0, sl_class, sl_cand, sl_thresh, has_node,
                         poison: bool, exc=None):
    """_shortlist_scan with W pods per wave step.

    The wave evaluates each member's candidate set (its top-K shortlist ∪
    every node debited this chunk) against the same carry, takes
    prefix-distinct picks, and speculation must clear BOTH proofs:

    - the shortlist bound check (the W=1 `trusted` rule verbatim): the
      pick beats the prefilter threshold, so no node OUTSIDE the
      candidate set can be the serial winner;
    - the pairwise wave check (_wave_conflicts): no same-wave earlier
      commit, re-scored after its debit, beats the pick — covering the
      nodes whose serial value moved since the wave evaluation.

    A member failing either falls into the serial replay, which runs the
    full N-wide row (exact regardless of why the bound failed); replays
    count into `fallbacks` — they pay the same O(N) a W=1 bound-check
    fallback pays. Chunk-touched candidates are already evaluated LIVE
    against the carry (the `touched` gather), so wave-start candidate
    values equal serial values everywhere except same-wave commits.
    poison semantics as _rescoring_wave_scan (the vmapped multistart
    shape). Returns (assign, fallbacks, commits, replays, poisoned)."""
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = free_q.shape[0]
    p = req_q.shape[0]
    W = max(1, min(wave_w, p))
    iota_n = jnp.arange(n, dtype=jnp.int32)
    ex = jnp.full((p,), -1, jnp.int32) if exc is None else exc
    (req_w, req_nz_w, rows_w, cand_w, t_w, cls_w, hn_w, ex_w), real_w, \
        p_pad = _wave_split(
            W, (req_q, req_nz_q, rows, sl_cand, sl_thresh, sl_class,
                has_node, ex))

    def live_scores(ci, row, cls, req_nz, used_nz, touched):
        """(W,M) candidate scores: live recompute for touched nodes,
        chunk-start sc0 gather for untouched (the W=1 float-consistency
        rule — the == threshold comparison never straddles two
        evaluations of the same quantity)."""
        live = static_scores[row[:, None], ci]
        live = live + w_fit * jax.vmap(
            lambda a, u, rn: kernels.fit_score(
                a, u, rn[None, :], fit_col_w, strategy, shape_u,
                shape_s)[0])(alloc_q[ci], used_nz[ci], req_nz)
        live = live + w_bal * jax.vmap(
            lambda a, u, rn: kernels.balanced_allocation_score(
                a, u, rn[None, :], bal_col_mask)[0])(
                    alloc_q[ci], used_nz[ci], req_nz)
        return jnp.where(touched[ci], live, sc0[cls[:, None], ci])

    def wave_step(carry, inp):
        (free_q, free_pods, used_nz, touched, tidx, kstep, nfall,
         ncom, nrep, pois) = carry
        req, req_nz, row, cand, t, cls, hn, e, real = inp
        cset = jnp.concatenate(
            [cand, jnp.broadcast_to(tidx[None, :], (W, p_pad))], axis=1)
        valid = cset < n
        ci = jnp.where(valid, cset, 0)                          # (W,M)
        live = live_scores(ci, row, cls, req_nz, used_nz, touched)
        fits = mask[row[:, None], ci] & valid \
            & jnp.all(req[:, None, :] <= free_q[ci], axis=-1) \
            & (free_pods[ci] >= 1) \
            & ((e < 0)[:, None] | (ci == e[:, None])) \
            & real[:, None]
        masked = jnp.where(fits, live, NEG_INF)
        b, y = _wave_spec_picks(masked, ci, n, W)
        safe = jnp.minimum(y, n - 1)
        hit = y < n
        # The W=1 trusted rule on each member's pick (chunk-touched
        # status at wave start; picks are never same-wave commits).
        trusted = jnp.where(
            hit,
            (b > t) | ((b == t) & jnp.logical_not(touched[safe])),
            t == NEG_INF) | jnp.logical_not(hn)
        conflict = jnp.logical_not(trusted) | _wave_conflicts(
            b, y, n, req, req_nz, free_q, free_pods, used_nz, alloc_q,
            mask[row[:, None], safe[None, :]]
            & ((e < 0)[:, None] | (safe[None, :] == e[:, None]))
            & real[:, None],
            static_scores[row[:, None], safe[None, :]],
            fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
            strategy)
        nreal = jnp.sum(real.astype(jnp.int32))

        def fast(st):
            (fq, fp, unz, tch, tix, ks, nf, nc, nr, po) = st
            fq = fq.at[safe].add(
                jnp.where(hit[:, None], -req, 0).astype(fq.dtype))
            fp = fp.at[safe].add(jnp.where(hit, -1, 0).astype(fp.dtype))
            unz = unz.at[safe].add(
                jnp.where(hit[:, None], req_nz, 0).astype(unz.dtype))
            # max-combine, NOT read-modify-write set: every no-pick
            # member aliases index n-1 through `safe`, and a duplicate-
            # index .set() scatter leaves which update wins unspecified
            # — a stale False could erase a same-wave commit's mark.
            tch = tch.at[safe].max(hit)
            tix = lax.dynamic_update_slice(
                tix, jnp.where(hit, y, n), (ks,))
            return (fq, fp, unz, tch, tix, ks + W, nf, nc + nreal, nr,
                    po), jnp.where(hit, y, jnp.int32(-1))

        if poison:
            carry2, out = fast(
                (free_q, free_pods, used_nz, touched, tidx, kstep, nfall,
                 ncom, nrep, pois | jnp.any(conflict)))
            return carry2, out

        def slow(st):
            (fq, fp, unz, tch, tix, ks, nf, nc, nr, po) = st

            def body(w, s):
                fq, fp, unz, tch, tix, out = s
                rq, rnz = req[w], req_nz[w]
                fits_n = mask[row[w]] & real[w] \
                    & jnp.all(rq[None, :] <= fq, axis=1) & (fp >= 1) \
                    & ((e[w] < 0) | (iota_n == e[w]))
                scw = static_scores[row[w]]
                scw = scw + w_fit * kernels.fit_score(
                    alloc_q, unz, rnz[None, :], fit_col_w, strategy,
                    shape_u, shape_s)[0]
                scw = scw + w_bal * kernels.balanced_allocation_score(
                    alloc_q, unz, rnz[None, :], bal_col_mask)[0]
                mk = jnp.where(fits_n, scw, NEG_INF)
                idx = jnp.argmax(mk).astype(jnp.int32)
                idx = jnp.where(jnp.any(fits_n), idx, jnp.int32(-1))
                hitw = idx >= 0
                sf = jnp.clip(idx, 0, n - 1)
                fq = fq.at[sf].add(jnp.where(hitw, -rq, 0).astype(fq.dtype))
                fp = fp.at[sf].add(jnp.where(hitw, -1, 0).astype(fp.dtype))
                unz = unz.at[sf].add(
                    jnp.where(hitw, rnz, 0).astype(unz.dtype))
                tch = tch.at[sf].set(tch[sf] | hitw)
                tix = tix.at[ks + w].set(jnp.where(hitw, idx, n))
                return (fq, fp, unz, tch, tix, out.at[w].set(idx))

            fq, fp, unz, tch, tix, out = lax.fori_loop(
                0, W, body,
                (fq, fp, unz, tch, tix, jnp.full((W,), -1, jnp.int32)))
            return (fq, fp, unz, tch, tix, ks + W, nf + nreal, nc,
                    nr + nreal, po), out

        return lax.cond(
            jnp.any(conflict), slow, fast,
            (free_q, free_pods, used_nz, touched, tidx, kstep, nfall,
             ncom, nrep, pois))

    carry0 = (free_q, free_pods, used_nz_q,
              jnp.zeros((n,), jnp.bool_),
              jnp.full((p_pad,), n, jnp.int32),
              jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
              jnp.bool_(False))
    (_, _, _, _, _, _, nfall, ncom, nrep, pois), out = lax.scan(
        wave_step, carry0,
        (req_w, req_nz_w, rows_w, cand_w, t_w, cls_w, hn_w, ex_w, real_w))
    return out.reshape(-1)[:p], nfall, ncom, nrep, pois


@partial(jax.jit, static_argnames=("strategy", "wave_w"))
def greedy_assign_rescoring_shortlist_wave(req_q, req_nz_q, free_q,
                                           free_pods, used_nz_q, alloc_q,
                                           mask, static_scores, fit_col_w,
                                           bal_col_mask, shape_u, shape_s,
                                           w_fit, w_bal, strategy: str,
                                           wave_w: int,
                                           sc0, sl_class, sl_cand,
                                           sl_thresh, has_node, rows=None,
                                           exc=None):
    """greedy_assign_rescoring_shortlist with wavefront waves: exact via
    the in-step serial replay (full N-wide rows, counted as fallbacks).
    Returns (assign (P,), fallbacks, commits, replays)."""
    if rows is None:
        rows = jnp.arange(req_q.shape[0], dtype=jnp.int32)
    assign, nfall, ncom, nrep, _ = _shortlist_wave_scan(
        req_q, req_nz_q, rows, free_q, free_pods, used_nz_q, alloc_q,
        mask, static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
        w_fit, w_bal, strategy, wave_w, sc0, sl_class, sl_cand, sl_thresh,
        has_node, poison=False, exc=exc)
    return assign, nfall, ncom, nrep


@partial(jax.jit, static_argnames=("strategy", "wave_w"))
def multistart_greedy_assign_shortlist_wave(req_q, req_nz_q, free_q,
                                            free_pods, used_nz_q, alloc_q,
                                            mask, static_scores, fit_col_w,
                                            bal_col_mask, shape_u, shape_s,
                                            w_fit, w_bal, strategy: str,
                                            wave_w: int, perms,
                                            gang_onehot, gang_required,
                                            sc0, sl_class, sl_cand,
                                            sl_thresh, has_node, rows=None,
                                            exc=None):
    """multistart_greedy_assign_shortlist with wavefront waves under the
    vmap: each order runs speculation-only and poisons on its first wave
    conflict OR failed bound check; one outer lax.cond reruns the whole
    chunk through the W=1 full multistart when any order was poisoned.
    Returns (assign (P,), fallback_pods, commits, replays) — fallback
    and replay accounting is whole-chunk here, like the W=1 variant."""
    P = req_q.shape[0]
    arange_p = jnp.arange(P, dtype=jnp.int32)
    if rows is None:
        rows = arange_p

    def one(perm):
        a, _, _, _, pois = _shortlist_wave_scan(
            req_q[perm], req_nz_q[perm], rows[perm], free_q, free_pods,
            used_nz_q, alloc_q, mask, static_scores, fit_col_w,
            bal_col_mask, shape_u, shape_s, w_fit, w_bal, strategy, wave_w,
            sc0, sl_class[perm], sl_cand[perm], sl_thresh[perm],
            has_node[perm], poison=True,
            exc=None if exc is None else exc[perm])
        inv = jnp.zeros_like(perm).at[perm].set(arange_p)
        return a[inv], pois

    assigns, pois = jax.vmap(one)(perms)
    any_pois = jnp.any(pois)

    def full(_):
        return _multistart_body(
            req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
            static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
            w_fit, w_bal, strategy, perms, gang_onehot, gang_required,
            rows, exc)

    def take(_):
        return _select_best(assigns, req_q, gang_onehot, gang_required)

    assign = lax.cond(any_pois, full, take, None)
    nfall = jnp.where(any_pois, jnp.int32(P), jnp.int32(0))
    ncom = jnp.where(any_pois, jnp.int32(0), jnp.int32(P))
    nrep = jnp.where(any_pois, jnp.int32(P), jnp.int32(0))
    return assign, nfall, ncom, nrep

def _solve_one_core(alloc_q, used_pack, alloc_pods, taint_f_mat,
                    taint_p_mat, mask_bits, host_scores, req_pack,
                    fit_col_w, bal_col_mask, shape_u, shape_s,
                    w_fit, w_bal, w_taint, taint_filter_on, strategy):
    """Traceable body shared by solve_one / solve_one_fresh."""
    from kubernetes_tpu.ops import kernels  # local to avoid import cycle

    n = alloc_q.shape[0]
    r = alloc_q.shape[1]
    tf = taint_f_mat.shape[1]
    # Wire decompression, identical to _mask_solve_update's unpack.
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    cmask = ((mask_bits[:, None] >> shifts) & 1).reshape(-1) \
        .astype(jnp.bool_)[:n]
    used_q = used_pack[:, :r]
    used_nz = used_pack[:, r:2 * r]
    used_pods = used_pack[:, 2 * r]
    req_q = req_pack[None, :r]
    req_nz = req_pack[None, r:2 * r]
    untol_f = req_pack[2 * r:2 * r + tf].astype(jnp.bool_)[None]
    untol_p = req_pack[2 * r + tf:].astype(jnp.bool_)[None]

    fit0 = kernels.fit_filter_mask(
        alloc_q, used_q, used_pods, alloc_pods, req_q)          # (1,N)
    taint_ok = kernels.taint_filter_mask(taint_f_mat, untol_f)
    taint_ok = taint_ok | jnp.logical_not(taint_filter_on)
    mask = cmask[None, :] & taint_ok
    feasible = mask & fit0
    static = host_scores[None, :].astype(jnp.float32) \
        + w_taint * kernels.taint_toleration_score(
            taint_p_mat, untol_p, feasible)

    # The scan step body for pod 0: chunk-start free state IS the
    # current state for a single-pod "chunk".
    free_q = alloc_q - used_q
    free_pods = alloc_pods - used_pods
    fits = mask[0] & jnp.all(req_q[0][None, :] <= free_q, axis=1) \
        & (free_pods >= 1)
    sc = static[0]
    sc = sc + w_fit * kernels.fit_score(
        alloc_q, used_nz, req_nz, fit_col_w, strategy, shape_u, shape_s)[0]
    sc = sc + w_bal * kernels.balanced_allocation_score(
        alloc_q, used_nz, req_nz, bal_col_mask)[0]
    masked = jnp.where(fits, sc, NEG_INF)
    idx = jnp.argmax(masked).astype(jnp.int32)
    return jnp.where(jnp.any(fits), idx, jnp.int32(-1))


@partial(jax.jit, static_argnames=("strategy",))
def solve_one(alloc_q, used_pack, alloc_pods, taint_f_mat, taint_p_mat,
              mask_bits, host_scores, req_pack,
              fit_col_w, bal_col_mask, shape_u, shape_s,
              w_fit, w_bal, w_taint, taint_filter_on, strategy: str):
    """One pod against the resident cluster planes, bit-identical to the
    batch path's first scan step.

    This is deliberately the EXACT composition `_mask_solve_update` +
    `greedy_assign_rescoring` compute for the first pod of a chunk — the
    same kernels in the same order on the same dtypes — so a lone pod
    routed here by the serving tier's admission window gets the
    assignment the batch path would have given it (the smoke suite's
    randomized differential pins it). What is REMOVED is everything a
    lone pod cannot use: the P-step scan, multistart permutation set,
    shortlist prefilter/top-k, gang masks, spread carry, per-chunk plane
    build. The program is fixed-shape per (N, R, T) cluster signature,
    so after the first compile a placement is one dispatch.

    mask_bits: (N/8,) uint8 bit-packed host filter row (the pod's AND-
        folded static rows; all-true for the common template pod).
    host_scores: (N,) f16/f32 host score row (zero for the common pod —
        cast to f32 on device exactly like the batch wire).
    req_pack: (2R+tf+tp,) int32 — req_q ‖ req_nz_q ‖ untol_f ‖ untol_p,
        the class_pack row of this pod's equivalence class.
    used_pack: (N, 2R+1) int32 resident used-state (used_q ‖ used_nz_q ‖
        used_pods) — the serving tier keeps it warm on device and
        refreshes O(changed) rows from the cache's dirty set.

    Returns the node index as an int32 scalar (-1 = no fit). There is
    deliberately NO debit output: the placement's assume re-enters
    through the cache's dirty set and the next refresh re-quantizes
    that one row — a debited pack here would be dead work per solve
    (and double-count against the refresh).
    """
    return _solve_one_core(
        alloc_q, used_pack, alloc_pods, taint_f_mat, taint_p_mat,
        mask_bits, host_scores, req_pack, fit_col_w, bal_col_mask,
        shape_u, shape_s, w_fit, w_bal, w_taint, taint_filter_on, strategy)


@partial(jax.jit, static_argnames=("strategy",))
def solve_one_fresh(alloc_q, used_pack, rows, vals, alloc_pods,
                    taint_f_mat, taint_p_mat, mask_bits, host_scores,
                    req_pack, fit_col_w, bal_col_mask, shape_u, shape_s,
                    w_fit, w_bal, w_taint, taint_filter_on, strategy: str):
    """solve_one with the resident-plane refresh FUSED in: scatter the
    dirty rows (`vals` re-quantized host-side, rows bucket-padded by
    repeating the first index — idempotent) into the resident pack,
    then solve against the refreshed state — ONE device dispatch where
    refresh-then-solve was two, which is most of the fast path's wall
    on a local device. Returns (idx, refreshed_pack): the caller keeps
    the refreshed (PRE-debit) pack as the new resident base — the
    solve's own assume re-enters through the cache's dirty set, so
    debiting here would double-count it on the next refresh."""
    pack = used_pack.at[rows].set(vals)
    idx = _solve_one_core(
        alloc_q, pack, alloc_pods, taint_f_mat, taint_p_mat,
        mask_bits, host_scores, req_pack, fit_col_w, bal_col_mask,
        shape_u, shape_s, w_fit, w_bal, w_taint, taint_filter_on, strategy)
    return idx, pack


#: int32 "no victim" priority padding — mirrors _WaveState.INF (int64 there;
#: the device scan runs int32, and k8s priorities are int32 by API).
PRIO_INF = jnp.int32(2**31 - 1)


@jax.jit
def propose_victims(req_q, prio, banned, used, alloc, pods_used, pods_alloc,
                    vreq, vprio, offsets):
    """Batched preemption victim proposal (SURVEY §7 phase 6,
    "solve-with-victim-relaxation"): ONE device program proposes, for every
    failed preemptor in a wave, the reference-cost-minimal (node, victim
    count) — replacing the per-preemptor host candidate search.

    Per node, victims are the priority-ASCENDING resident prefix (the same
    ordering `DefaultPreemption._WaveState` builds), so "evict the first k"
    is always the cheapest k-victim set and prefix feasibility is a
    relaxed-capacity check. The scan threads claims through per-node state
    exactly like the capacity carry in `greedy_assign`: a chosen node's
    victim prefix is consumed (shifted out) and the preemptor's load is
    charged, so concurrent preemptors spread instead of stacking — the
    in-wave accounting `_WaveState.claim` does, but without P host
    round-trips.

    req_q:    (P, R) int32 preemptor requests, wave order (priority desc)
    prio:     (P,)   int32 preemptor priorities
    banned:   (P, N) bool  — UnschedulableAndUnresolvable nodes per preemptor
    used/alloc:        (N, R) int32 node requested/allocatable
    pods_used/alloc:   (N,)   int32
    vreq:     (N, K, R) int32 per-victim requests (ascending priority; 0 pad)
    vprio:    (N, K)    int32 per-victim priorities (PRIO_INF pad)
    offsets:  (P,) int32 per-preemptor rotation for the equal-cost tiebreak
        (the host path's seeded tie shuffle, made deterministic: ties pick
        the node minimizing (index - offset) mod N, so a wave's preemptors
        spread across an equal-cost set instead of all hitting node 0)

    Returns (node (P,) int32 [-1 = no candidate], count (P,) int32,
    used', pods_used', vreq', vprio') — the post-claim carry, so a caller
    chunking a wave wider than one P bucket threads state across calls
    without re-uploading (same pattern as the packed used-state chain).

    Cost ordering per the reference's pickOneNodeForPreemption subset the
    host path implements: lowest max victim priority → smallest priority
    sum → fewest victims (PDB tier absent there too). Proposals are
    host-verified against the live snapshot (full Filter chain) before any
    eviction — this program only replaces the SEARCH.
    """
    N, K, R = vreq.shape
    iota_n = jnp.arange(N, dtype=jnp.int32)
    karange = jnp.arange(K, dtype=jnp.int32)
    BIG = jnp.int32(2**31 - 1)

    def step(carry, inp):
        used, pods_used, vreq, vprio = carry
        q, p, ban, off = inp
        valid = vprio < PRIO_INF                            # (N, K)
        rel = jnp.cumsum(vreq, axis=1)                      # (N, K, R)
        prio_m = jnp.where(valid, vprio, 0)
        # Priority SUM rides float32: an int32 cumsum of near-INT32_MAX
        # priorities over a deep prefix overflows. Exact below 2^24;
        # above, the sum key coarsens ties only — candidates are
        # host-verified before any eviction either way.
        vsum = jnp.cumsum(prio_m.astype(jnp.float32), axis=1)
        vmax = lax.cummax(prio_m, axis=1)                   # (N, K)
        # Ascending sort ⇒ vprio[k] < p implies the whole prefix is
        # below the preemptor (same invariant the host candidates() uses).
        eligible = vprio < p
        fits = jnp.all(used[:, None, :] - rel + q[None, None, :]
                       <= alloc[:, None, :], axis=-1)
        fits = fits & (pods_used[:, None] - karange[None, :]
                       <= pods_alloc[:, None])
        ok = eligible & fits                                # (N, K)
        any_ok = jnp.any(ok, axis=1) & jnp.logical_not(ban)
        kmin = jnp.argmax(ok, axis=1).astype(jnp.int32)     # first fit
        cmax = jnp.take_along_axis(vmax, kmin[:, None], 1)[:, 0]
        csum = jnp.take_along_axis(vsum, kmin[:, None], 1)[:, 0]
        # Staged lexicographic argmin (vmax, vsum, count), rotation tiebreak.
        k1 = jnp.where(any_ok, cmax, BIG)
        c1 = any_ok & (cmax == jnp.min(k1))
        k2 = jnp.where(c1, csum, jnp.float32(jnp.inf))
        c2 = c1 & (csum == jnp.min(k2))
        k3 = jnp.where(c2, kmin, BIG)
        c3 = c2 & (kmin == jnp.min(k3))
        rot = (iota_n - off) % N
        n_star = jnp.argmin(jnp.where(c3, rot, BIG)).astype(jnp.int32)
        found = jnp.any(any_ok)
        count = kmin[n_star] + 1
        # Claim: drop the chosen prefix, charge the preemptor, shift the
        # node's victim arrays so later wave members see the truth.
        hit = (iota_n == n_star) & found
        freed = rel[n_star, count - 1]                      # (R,)
        used = used + jnp.where(hit[:, None], q[None, :] - freed[None, :], 0)
        pods_used = pods_used + jnp.where(hit, 1 - count, 0)
        src = jnp.clip(karange + count, 0, K - 1)
        keep = (karange + count) < K
        row_vreq = jnp.where(keep[:, None], vreq[n_star][src], 0)
        row_vprio = jnp.where(keep, vprio[n_star][src], PRIO_INF)
        vreq = jnp.where(hit[:, None, None], row_vreq[None, :, :], vreq)
        vprio = jnp.where(hit[:, None], row_vprio[None, :], vprio)
        out = (jnp.where(found, n_star, jnp.int32(-1)),
               jnp.where(found, count, jnp.int32(0)))
        return (used, pods_used, vreq, vprio), out

    carry, (node, count) = lax.scan(
        step, (used, pods_used, vreq, vprio),
        (req_q, prio, banned, offsets))
    return (node, count) + carry


@jax.jit
def fragmentation(free_q, alloc_q, valid):
    """Node fragmentation %: mean over non-empty resource columns of the
    free/allocatable fraction on nodes that host at least one pod would
    over-estimate; the metric BASELINE tracks is simpler — mean remaining
    capacity fraction across valid nodes (lower = tighter packing)."""
    alloc = alloc_q.astype(jnp.float32)
    frac = jnp.where(alloc > 0, free_q.astype(jnp.float32) / alloc, 0.0)
    per_node = jnp.sum(frac, axis=1) / jnp.maximum(
        jnp.sum(alloc > 0, axis=1), 1)
    return 100.0 * jnp.sum(jnp.where(valid, per_node, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


@jax.jit
def fragmentation_occupied(free_q, alloc_q, used_pods, valid):
    """OCCUPIED-node fragmentation %: mean free-capacity fraction over
    nodes hosting at least one pod. This is the r20 optimizable metric —
    the all-nodes `fragmentation` above is placement-INVARIANT once every
    pod places (total free capacity is fixed by the workload), while this
    variant rewards concentrating load: packing the same pods onto fewer,
    fuller nodes lowers it, spreading them raises it. 0 occupied nodes →
    0.0 (an empty cluster is not fragmented)."""
    occ = valid & (used_pods > 0)
    alloc = alloc_q.astype(jnp.float32)
    frac = jnp.where(alloc > 0, free_q.astype(jnp.float32) / alloc, 0.0)
    per_node = jnp.sum(frac, axis=1) / jnp.maximum(
        jnp.sum(alloc > 0, axis=1), 1)
    return 100.0 * jnp.sum(jnp.where(occ, per_node, 0.0)) / jnp.maximum(
        jnp.sum(occ), 1)


#: annealing stages of the Sinkhorn temperature schedule (4T → 2T → T).
SINKHORN_STAGES = 3


@jax.jit
def sinkhorn_plan(feasible, cost, row_counts, col_cap, iters, temp):
    """Entropic-regularized transport plan over the (C, N) class planes
    (the r20 batch-optimal solve mode — SURVEY §5's Sinkhorn row).

    The class dictionary is what makes this affordable: the cost matrix
    is C×N (pod equivalence classes × nodes), never P×N, so the whole
    iteration runs on device planes that already exist. Marginals:

    - row_counts (C,) f32 — pods per class this chunk (the row mass each
      class must place; padding rides the reserved EMPTY class whose
      all-false feasible row zeros its kernel row).
    - col_cap (N,) — remaining pod slots per node, an INEQUALITY bound:
      the column step caps column mass at capacity (the partial-transport
      update v = min(1, b/col)) rather than forcing columns full, so
      under-capacity nodes simply receive less mass.

    Costs are the greedy scorer's own chunk-start scores (the warm
    start), shifted per row so one temperature means the same thing at
    any score scale. Temperature ANNEALS over SINKHORN_STAGES stages
    (4T → 2T → T): early high-temperature rounds spread mass and settle
    the capacity duals, late low-temperature rounds sharpen toward the
    assignment vertex. `iters`/`temp` are traced (live KTPU_SINKHORN_ITERS
    / KTPU_SINKHORN_TEMP knobs, no recompile); the loop lowers to a while.

    Returns (log_plan (C,N) f32, plan (C,N) f32). log_plan is sanitized
    (-1e30 on infeasible/non-finite entries) so it drops directly into
    the scans as `static_scores` for the feasibility-preserving rounding
    pass; monotone per row, so the rounding argmax ranks by plan mass.
    On uniform workloads the plan ties across equal columns and the
    rounding degenerates to first-fit — which is exactly the packing
    behavior the occupied-fragmentation metric rewards.
    """
    a = row_counts.astype(jnp.float32)
    b = jnp.maximum(col_cap.astype(jnp.float32), 0.0)
    eps = jnp.float32(1e-12)
    n_iters = jnp.maximum(iters, 1)
    stages = jnp.int32(SINKHORN_STAGES)
    kmask = feasible.astype(jnp.float32)
    # Row-relative costs: subtract each row's feasible max so exp() is
    # bounded in (0, 1] and `temp` is scale-free.
    rmax = jnp.max(jnp.where(feasible, cost.astype(jnp.float32), NEG_INF),
                   axis=1, keepdims=True)
    sc = jnp.where(feasible, cost.astype(jnp.float32) - rmax, 0.0)

    def kernel(stage):
        t = temp * jnp.exp2((stages - 1 - stage).astype(jnp.float32))
        return kmask * jnp.exp(sc / jnp.maximum(t, eps))

    def step(i, uv):
        u, v = uv
        k = kernel(jnp.minimum((stages * i) // n_iters, stages - 1))
        u = a / jnp.maximum(k @ v, eps)
        col = u @ k
        v = jnp.minimum(jnp.float32(1.0), b / jnp.maximum(col, eps))
        return (u, v)

    u, v = lax.fori_loop(
        0, n_iters, step,
        (jnp.ones(a.shape, jnp.float32), jnp.ones(b.shape, jnp.float32)))
    plan = u[:, None] * kernel(stages - 1) * v[None, :]
    log_plan = jnp.log(plan + jnp.float32(1e-30))
    log_plan = jnp.where(jnp.isfinite(log_plan) & feasible, log_plan,
                         jnp.float32(-1e30))
    return log_plan, plan


@jax.jit
def consolidation_scores(free_q, alloc_q, used_pods, valid, threshold):
    """Per-node consolidation priority for the descheduler, scored from
    the same resident device planes the solver consumes: occupied nodes
    whose mean free-capacity fraction is ≥ `threshold` are drain
    candidates, scored by emptiness (emptiest first — draining the node
    with the least to move frees a whole node soonest). Empty nodes,
    invalid rows, and well-packed nodes score NEG_INF (never drained)."""
    alloc = alloc_q.astype(jnp.float32)
    frac = jnp.where(alloc > 0, free_q.astype(jnp.float32) / alloc, 0.0)
    per_node = jnp.sum(frac, axis=1) / jnp.maximum(
        jnp.sum(alloc > 0, axis=1), 1)
    eligible = valid & (used_pods > 0) & (per_node >= threshold)
    return jnp.where(eligible, per_node, NEG_INF)
