"""Tensorized InterPodAffinity filter (BASELINE config #2 hot path).

Replaces the host plugin's O(pods × nodes × terms) Python walk
(pkg/scheduler/framework/plugins/interpodaffinity/filtering.go — "the
classic hot spot", SURVEY §2.3) with dense algebra over interned label
signatures (ops/labelsets.py):

    counts_t (N,)  = node_sig_count @ match_vec(term)        # matvec
    D_t (K,)       = segment_sum(counts_t · has_key, domains) # per-domain
    per_node (N,)  = D_t[domain_ids]                          # gather
    anti mask      = ¬has_key ∨ (per_node == 0)
    affinity mask  = has_key ∧ (per_node > 0)   [+ first-pod-in-group rule]
    symmetry mask  = ¬has_key ∨ (forbidden-domain count == 0), applied to
                     pods the resident term matches

Numpy, deliberately: U (label signatures) and T (unique terms) are tiny for
template-derived workloads, so per-term cost is a (N×U) matvec — far below
one device dispatch. The resulting (P,N) mask feeds the XLA solver; parity
with the host plugin is differential-tested (tests/test_affinity_tensor.py).

namespaceSelector terms COMPILE like everything else: the term's
effective namespace set resolves at table-build time
(interpodaffinity.resolve_term_namespaces) — through the plugin's
NamespaceResolver when one is wired (the reference's PreFilter namespace
merge), else statically ({} = ALL_NAMESPACES, non-empty selectors match
their explicit namespaces only, exactly what an informer-less resolver
resolves). Either way the result is just a (possibly wildcard) namespace
tuple in the interned-count keys, so no term shape routes a pod off the
tensor path; `supported()` is always True.
"""

from __future__ import annotations

import numpy as np

from kubernetes_tpu.api.labels import from_label_selector, ns_contains
from kubernetes_tpu.ops.labelsets import LabelSigTable, TopologyTable
from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
    resolve_term_namespaces as _term_ns,
)
from kubernetes_tpu.scheduler.types import PodInfo, Snapshot


def _seg_sum(values: np.ndarray, ids: np.ndarray, num: int) -> np.ndarray:
    out = np.zeros((num,), dtype=values.dtype)
    np.add.at(out, ids, values)
    return out


class AffinityCompiler:
    """Per-snapshot compiled state for batched affinity filtering.

    `ns_resolver` (plugins.interpodaffinity.NamespaceResolver) resolves
    namespaceSelector terms against live Namespace labels; without one
    the static resolution of resolve_term_namespaces applies ({} = every
    namespace, non-empty selectors = explicit namespaces only). Every
    term shape compiles — there is no host-fallback routing here."""

    def __init__(self, snapshot: Snapshot, n_pad: int, ns_resolver=None):
        self.ns_resolver = ns_resolver
        self.snapshot = snapshot
        self.n_pad = n_pad
        self.n_real = len(snapshot.nodes)
        self.sigs = LabelSigTable(snapshot, n_pad)
        self.topo = TopologyTable(snapshot.nodes, n_pad)
        # Resident pods' required anti-affinity terms (symmetry source):
        # term signature -> (carrier-count vector over nodes, term, owner_ns).
        self.resident_anti: dict[str, tuple[np.ndarray, dict, str]] = {}
        for n, ni in enumerate(snapshot.nodes):
            if not ni.pods_with_required_anti_affinity:
                continue
            for pi in ni.pods_with_required_anti_affinity:
                for term in pi.required_anti_affinity_terms:
                    key = repr((term, pi.namespace))
                    got = self.resident_anti.get(key)
                    if got is None:
                        vec = np.zeros((n_pad,), dtype=np.float32)
                        self.resident_anti[key] = (vec, term, pi.namespace)
                        got = self.resident_anti[key]
                    got[0][n] += 1.0
        # Resident pods' PREFERRED terms + required-affinity terms (score
        # symmetry sources — scoring.go's second loop): term signature →
        # (weight-summed carrier vector over nodes, term, owner_ns).
        # Preferred anti-affinity carriers get negative weights.
        self.resident_score: dict[
            str, tuple[np.ndarray, dict, str, bool]] = {}

        def _carrier(term: dict, ns: str, n: int, w: float,
                     is_hard: bool = False) -> None:
            key = repr((term, ns, is_hard))
            got = self.resident_score.get(key)
            if got is None:
                got = self.resident_score[key] = (
                    np.zeros((n_pad,), dtype=np.float32), term, ns, is_hard)
            got[0][n] += w

        for n, ni in enumerate(snapshot.nodes):
            for pi in ni.pods_with_affinity:
                for t in pi.preferred_affinity_terms:
                    _carrier(t.get("podAffinityTerm") or {}, pi.namespace,
                             n, float(t.get("weight", 1)))
                for t in pi.preferred_anti_affinity_terms:
                    _carrier(t.get("podAffinityTerm") or {}, pi.namespace,
                             n, -float(t.get("weight", 1)))
                for t in pi.required_affinity_terms:
                    # hardPodAffinityWeight multiplies at score_row time.
                    _carrier(t, pi.namespace, n, 1.0, is_hard=True)
        #: per-pending-pod-signature symmetry-match cache
        self._sym_match_cache: dict[tuple, bool] = {}
        #: per-(term,ns) per-node matching-count cache
        self._count_cache: dict[str, np.ndarray] = {}
        #: per-term-signature compiled masks
        self._mask_cache: dict[str, np.ndarray] = {}
        #: full-row caches keyed by pod CONTENT signature (namespace,
        #: labels, term list): template-stamped batches share one row —
        #: the per-pod O(N) row assembly was the 5k families' top host
        #: cost. Cached rows are shared and IDENTITY-STABLE per
        #: signature; callers must not mutate them. The backend's
        #: class-dictionary build leans on that stability: its row
        #: interning memoizes by object identity, so a template's
        #: thousand pods hash the row bytes once and land in one device
        #: plane class (ops/backend._prep_chunk).
        self._filter_row_cache: dict[tuple, np.ndarray] = {}
        self._score_row_cache: dict[tuple, np.ndarray] = {}

    # -- primitives --------------------------------------------------------

    def counts_for(self, selector: dict | None,
                   namespaces: tuple[str, ...]) -> np.ndarray:
        """(n_pad,) count of resident pods matching selector per node."""
        key = repr((selector, namespaces))
        c = self._count_cache.get(key)
        if c is None:
            c = self.sigs.node_sig_count @ self.sigs.match_vec(
                selector, namespaces)
            self._count_cache[key] = c
        return c

    def _domain_presence(self, counts: np.ndarray,
                         topology_key: str) -> tuple[np.ndarray, np.ndarray]:
        """(per_node_domain_count (n_pad,), has_key (n_pad,))."""
        dom_ids, num = self.topo.domains(topology_key)
        has_key = dom_ids > 0
        d = _seg_sum(np.where(has_key, counts, 0.0), dom_ids, num)
        d[0] = 0.0
        return d[dom_ids], has_key

    # -- per-term masks (cached by term signature) -------------------------

    def supported(self, pod: PodInfo) -> bool:
        """Every term shape compiles (namespaceSelector included) —
        retained as a seam for future exotic term shapes."""
        return True

    def anti_term_mask(self, term: dict, owner_ns: str) -> np.ndarray:
        key = "anti/" + repr((term, owner_ns))
        m = self._mask_cache.get(key)
        if m is None:
            counts = self.counts_for(term.get("labelSelector"),
                                     _term_ns(term, owner_ns, self.ns_resolver))
            per_node, has_key = self._domain_presence(
                counts, term.get("topologyKey", ""))
            m = ~has_key | (per_node == 0)
            self._mask_cache[key] = m
        return m

    def affinity_term_presence(self, term: dict,
                               owner_ns: str) -> tuple[np.ndarray, np.ndarray, float]:
        """(per_node matching count, has_key, total matches anywhere)."""
        key = "aff/" + repr((term, owner_ns))
        got = self._mask_cache.get(key)
        if got is None:
            counts = self.counts_for(term.get("labelSelector"),
                                     _term_ns(term, owner_ns, self.ns_resolver))
            tk = term.get("topologyKey", "")
            per_node, has_key = self._domain_presence(counts, tk)
            # `total` drives the first-pod-in-group escape: the host plugin
            # only counts matches on nodes that HAVE the topology key
            # (pre_filter skips tv-None nodes), so mask accordingly.
            total = float(np.sum(np.where(
                has_key[: self.n_real], counts[: self.n_real], 0.0)))
            got = (per_node, has_key, total)
            self._mask_cache[key] = got
        return got

    def symmetry_mask(self, pod: PodInfo) -> np.ndarray:
        """Nodes forbidden to `pod` by resident pods' required anti-affinity
        (the both-ways check in filtering.go)."""
        mask = np.ones((self.n_pad,), dtype=np.bool_)
        if not self.resident_anti:
            return mask
        from kubernetes_tpu.api.labels import from_label_selector
        pod_sig = (pod.namespace, tuple(sorted(pod.labels.items())))
        for key, (carriers, term, owner_ns) in self.resident_anti.items():
            mk = (key, pod_sig)
            hit = self._sym_match_cache.get(mk)
            if hit is None:
                nses = _term_ns(term, owner_ns, self.ns_resolver)
                hit = ns_contains(nses, pod.namespace) and \
                    from_label_selector(
                        term.get("labelSelector")).matches(pod.labels)
                self._sym_match_cache[mk] = hit
            if not hit:
                continue
            skey = "sym/" + key
            m = self._mask_cache.get(skey)
            if m is None:
                per_node, has_key = self._domain_presence(
                    carriers, term.get("topologyKey", ""))
                m = ~has_key | (per_node == 0)
                self._mask_cache[skey] = m
            mask &= m
        return mask

    # -- the batch entry ----------------------------------------------------

    def filter_row(self, pod: PodInfo) -> np.ndarray:
        """(n_pad,) bool feasibility row for one pending pod — exact
        InterPodAffinity.Filter semantics over the snapshot. Cached by
        pod CONTENT signature (template batches share one row); the
        returned array is shared — do not mutate."""
        ck = (pod.namespace, tuple(sorted(pod.labels.items())),
              repr(pod.required_affinity_terms),
              repr(pod.required_anti_affinity_terms))
        cached = self._filter_row_cache.get(ck)
        if cached is not None:
            return cached
        row = self.symmetry_mask(pod).copy()
        for term in pod.required_anti_affinity_terms:
            row &= self.anti_term_mask(term, pod.namespace)
        if pod.required_affinity_terms:
            # first-pod-in-group rule: if NO term matches anything anywhere
            # and the pod matches its own terms, terms don't reject (nodes
            # still need the topology keys).
            presences = [
                self.affinity_term_presence(t, pod.namespace)
                for t in pod.required_affinity_terms]
            total_any = sum(p[2] for p in presences)
            if total_any == 0 and self._self_matches(pod):
                for _, has_key, _ in presences:
                    row &= has_key
            else:
                for per_node, has_key, _ in presences:
                    row &= has_key & (per_node > 0)
        row[self.n_real:] = False
        self._filter_row_cache[ck] = row
        return row

    def score_supported(self, pod: PodInfo) -> bool:
        """Preferred terms compile like required ones (namespaceSelector
        included) — retained as a seam, always True."""
        return True

    def _masked_presence(self, counts: np.ndarray, topology_key: str,
                         feasible: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
        """_domain_presence restricted to feasible nodes — the host
        pre_score iterates only the FILTERED node list, so residents on
        infeasible nodes contribute nothing (per-pod, so uncached)."""
        dom_ids, num = self.topo.domains(topology_key)
        has_key = dom_ids > 0
        d = _seg_sum(np.where(has_key & feasible, counts, 0.0),
                     dom_ids, num)
        d[0] = 0.0
        return d[dom_ids], has_key

    def score_row(self, pod: PodInfo, hard_weight: float,
                  feasible: np.ndarray) -> np.ndarray:
        """(n_pad,) raw InterPodAffinity score — exactly pre_score's
        domain-weight accumulation (scoring.go) over the pod's FEASIBLE
        nodes, vectorized: the pod's preferred (anti-)terms weigh matching
        residents by domain; residents' preferred terms + required terms
        (× hardPodAffinityWeight) weigh back symmetrically. Cached by
        (pod content signature, feasible-mask bytes): template batches
        share one row per distinct feasibility class. Shared array — do
        not mutate."""
        ck = (pod.namespace, tuple(sorted(pod.labels.items())),
              repr(pod.preferred_affinity_terms),
              repr(pod.preferred_anti_affinity_terms),
              hard_weight, feasible.tobytes())
        cached = self._score_row_cache.get(ck)
        if cached is not None:
            return cached
        row = np.zeros((self.n_pad,), dtype=np.float32)
        for term in pod.preferred_affinity_terms:
            t = term.get("podAffinityTerm") or {}
            counts = self.counts_for(t.get("labelSelector"),
                                     _term_ns(t, pod.namespace, self.ns_resolver))
            per_node, has_key = self._masked_presence(
                counts, t.get("topologyKey", ""), feasible)
            row += float(term.get("weight", 1)) * np.where(
                has_key, per_node, 0.0)
        for term in pod.preferred_anti_affinity_terms:
            t = term.get("podAffinityTerm") or {}
            counts = self.counts_for(t.get("labelSelector"),
                                     _term_ns(t, pod.namespace, self.ns_resolver))
            per_node, has_key = self._masked_presence(
                counts, t.get("topologyKey", ""), feasible)
            row -= float(term.get("weight", 1)) * np.where(
                has_key, per_node, 0.0)
        from kubernetes_tpu.api.labels import from_label_selector
        pod_sig = (pod.namespace, tuple(sorted(pod.labels.items())))
        for key, (carriers, term, owner_ns, is_hard) in \
                self.resident_score.items():
            mk = ("score", key, pod_sig)
            hit = self._sym_match_cache.get(mk)
            if hit is None:
                nses = _term_ns(term, owner_ns, self.ns_resolver)
                hit = ns_contains(nses, pod.namespace) and \
                    from_label_selector(
                        term.get("labelSelector")).matches(pod.labels)
                self._sym_match_cache[mk] = hit
            if not hit:
                continue
            per_node, has_key = self._masked_presence(
                carriers, term.get("topologyKey", ""), feasible)
            w = hard_weight if is_hard else 1.0
            row += w * np.where(has_key, per_node, 0.0)
        row[self.n_real:] = 0.0
        self._score_row_cache[ck] = row
        return row

    def _self_matches(self, pod: PodInfo) -> bool:
        from kubernetes_tpu.api.labels import from_label_selector
        for t in pod.required_affinity_terms:
            if not ns_contains(
                    _term_ns(t, pod.namespace, self.ns_resolver),
                    pod.namespace):
                return False
            if not from_label_selector(t.get("labelSelector")).matches(pod.labels):
                return False
        return True

    # -- PodTopologySpread (same primitives, skew semantics) ---------------

    def eligibility_row(self, pod: PodInfo) -> np.ndarray:
        """(n_pad,) nodes eligible for domain counting under this pod's
        nodeSelector/affinity/tolerations (podtopologyspread._node_eligible),
        cached by the pod's eligibility signature."""
        key = "elig/" + repr((pod.node_selector,
                              pod.affinity.get("nodeAffinity"),
                              pod.tolerations))
        row = self._mask_cache.get(key)
        if row is None:
            from kubernetes_tpu.scheduler.plugins.podtopologyspread import (
                _node_eligible,
            )
            row = np.zeros((self.n_pad,), dtype=np.bool_)
            for n, ni in enumerate(self.snapshot.nodes):
                row[n] = _node_eligible(pod, ni)
            self._mask_cache[key] = row
        return row

    def spread_constraint_ns(self, constraint: dict,
                             pod_ns: str) -> tuple[str, ...]:
        """A spread constraint's effective namespace set (plain
        constraints count within the pod's own namespace;
        namespaceSelector resolves like an affinity term's)."""
        return _term_ns(constraint, pod_ns, self.ns_resolver)

    def _spread_domain_counts(self, pod: PodInfo, constraint: dict):
        """Per-constraint: (per_node_count, has_key, eligible, min_count).

        Host semantics (_build_state): only eligible nodes' pods count and
        only eligible domains exist; min is over eligible domains, floored
        to 0 when fewer eligible domains exist than minDomains."""
        key = "spread/" + repr((constraint, pod.namespace,
                                pod.node_selector,
                                pod.affinity.get("nodeAffinity"),
                                pod.tolerations))
        got = self._mask_cache.get(key)
        if got is None:
            sel = constraint.get("labelSelector")
            counts = self.counts_for(
                sel, self.spread_constraint_ns(constraint, pod.namespace))
            elig = self.eligibility_row(pod)
            tk = constraint["topologyKey"]
            dom_ids, num = self.topo.domains(tk)
            has_key = dom_ids > 0
            active = has_key & elig
            d = _seg_sum(np.where(active, counts, 0.0), dom_ids, num)
            # Domains with at least one eligible node "exist" (count ≥ 0);
            # others are fresh (None in the host dict → constraint passes).
            exists = _seg_sum(active.astype(np.float32), dom_ids, num) > 0
            exists[0] = False
            n_existing = int(exists.sum())
            md = int(constraint.get("minDomains") or 0)
            if md and n_existing < md:
                min_count = 0.0
            else:
                min_count = float(d[exists].min()) if n_existing else 0.0
            got = (d[dom_ids], has_key, exists[dom_ids], min_count)
            self._mask_cache[key] = got
        return got

    def spread_filter_row(self, pod: PodInfo,
                          constraints: list[dict]) -> np.ndarray:
        """(n_pad,) DoNotSchedule skew feasibility
        (podtopologyspread.filter)."""
        row = np.ones((self.n_pad,), dtype=np.bool_)
        for c in constraints:
            per_node, has_key, exists, min_count = \
                self._spread_domain_counts(pod, c)
            max_skew = c.get("maxSkew", 1)
            # selfMatchNum (filtering.go): count the incoming pod only if
            # the constraint's selector + namespace set match the pod.
            self_match = 1 if ns_contains(
                self.spread_constraint_ns(c, pod.namespace),
                pod.namespace) and from_label_selector(
                c.get("labelSelector")).matches(pod.labels) else 0
            ok = (~exists) | (per_node + self_match - min_count <= max_skew)
            row &= has_key & ok
        row[self.n_real:] = False
        return row

    def spread_raw_scores(self, pod: PodInfo,
                          constraints: list[dict]) -> np.ndarray:
        """(n_pad,) raw ScheduleAnyway score: Σ matching-pod count in the
        node's domains (podtopologyspread.score; NormalizeScore inverts)."""
        raw = np.zeros((self.n_pad,), dtype=np.float32)
        for c in constraints:
            per_node, has_key, _, _ = self._spread_domain_counts(pod, c)
            raw += np.where(has_key, per_node, 0.0)
        return raw
