"""Snapshot → dense device tensors (the scheduler's "input pipeline").

This is the tensorization point called out in SURVEY §3.1 at
`cache.UpdateSnapshot` (pkg/scheduler/internal/cache/cache.go): the host-side
`Snapshot` of `NodeInfo` records is compiled into flat arrays the batched
filter/score kernels (ops/kernels.py) and the assignment solver (ops/solver.py)
consume.

Quantization design (sound-by-construction feasibility):

Resource quantities are tracked host-side in integer milli-units
(pkg/api/resource Quantity semantics). Memory in milli-bytes overflows the
float32 mantissa (256Gi ≈ 2.7e14), so device arrays use **per-resource
power-of-two quantization into int32**:

    scale_r  = 2^k, minimal k with  max_allocatable_r / 2^k < 2^20
    alloc_q  = floor(allocatable / scale)     (node capacity rounded DOWN)
    used_q   = ceil(requested   / scale)      (resident usage rounded UP)
    podreq_q = ceil(pod request / scale)      (incoming request rounded UP)

The rounding directions make the device-side fit predicate
`used_q + podreq_q <= alloc_q` *conservative*: it can never admit a placement
the exact host predicate (plugins/noderesources.insufficient_resources) would
reject, at the cost of rejecting placements within one quantum
(≈ allocatable × 2^-20) of full — negligible, and differential-tested.

Node counts (max-pods) are small ints and carried exactly.

Shapes are padded (nodes to a multiple of `NODE_PAD`, pods to the batch size)
so XLA compiles one program per (P, N_padded, R) signature instead of one per
cycle — no data-dependent shapes inside jit (SURVEY §5.7 / XLA semantics).
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import numpy as np

from kubernetes_tpu.api.types import (
    CPU,
    MEMORY,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    toleration_tolerates_taint,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.topology.planes import TopologyPlanes, build_topology_planes
from kubernetes_tpu.utils import flags

#: Node axis is padded to a multiple of this so node add/remove churn doesn't
#: recompile the kernels every time (and tiles map cleanly onto the VPU/MXU).
NODE_PAD = 256

#: Quantized allocatable targets < 2^20 quanta → ~1e-6 relative precision.
_QUANT_BITS = 20


def _scale_for(max_value: int) -> int:
    """Smallest power-of-two scale with max_value/scale < 2^_QUANT_BITS."""
    if max_value < (1 << _QUANT_BITS):
        return 1
    return 1 << (max(0, max_value.bit_length() - _QUANT_BITS))


def _quant_floor(v: int, scale: int) -> int:
    return v // scale


def _quant_ceil(v: int, scale: int) -> int:
    return -((-v) // scale)


class TaintTable:
    """Interned (key, value, effect) taint triples split by filtering effect.

    TaintToleration's Filter only looks at NoSchedule/NoExecute; its Score
    counts untolerated PreferNoSchedule taints
    (plugins/tainttoleration — see scheduler/plugins/nodeaffinity.py).
    Node membership becomes two dense bool matrices; each pod's toleration
    list compiles to an "untolerated" bool vector host-side (tiny: pods come
    from templates, so vectors are cached by toleration signature upstream).
    """

    def __init__(self, nodes: Sequence[NodeInfo]):
        filt: dict[tuple, int] = {}
        pref: dict[tuple, int] = {}
        for ni in nodes:
            for t in ni.taints:
                trip = (t.get("key", ""), t.get("value", ""), t.get("effect", ""))
                if trip[2] in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE):
                    filt.setdefault(trip, len(filt))
                elif trip[2] == TAINT_PREFER_NO_SCHEDULE:
                    pref.setdefault(trip, len(pref))
        self.filter_taints = [dict(key=k, value=v, effect=e) for (k, v, e) in filt]
        self.prefer_taints = [dict(key=k, value=v, effect=e) for (k, v, e) in pref]
        self._filt_idx = filt
        self._pref_idx = pref

    def node_rows(self, nodes: Sequence[NodeInfo], n_pad: int):
        nf, npf = max(1, len(self.filter_taints)), max(1, len(self.prefer_taints))
        filt = np.zeros((n_pad, nf), dtype=np.bool_)
        pref = np.zeros((n_pad, npf), dtype=np.bool_)
        for i, ni in enumerate(nodes):
            for t in ni.taints:
                trip = (t.get("key", ""), t.get("value", ""), t.get("effect", ""))
                j = self._filt_idx.get(trip)
                if j is not None:
                    filt[i, j] = True
                j = self._pref_idx.get(trip)
                if j is not None:
                    pref[i, j] = True
        return filt, pref

    def untolerated(self, tolerations: list, which: str) -> np.ndarray:
        """Bool vector over the interned taints this pod does NOT tolerate."""
        taints = self.filter_taints if which == "filter" else self.prefer_taints
        out = np.zeros((max(1, len(taints)),), dtype=np.bool_)
        for j, taint in enumerate(taints):
            if not any(toleration_tolerates_taint(t, taint) for t in tolerations):
                out[j] = True
        return out


class ClusterTensors:
    """Dense, device-ready view of one Snapshot.

    Rebuilt when the snapshot generation moves; the expensive static pieces
    (taint interning) are reused while the node set + taints are unchanged.
    """

    def __init__(self, snapshot: Snapshot, resources: Sequence[str] | None = None,
                 prev: "ClusterTensors | None" = None,
                 shards: int | None = None):
        nodes = snapshot.nodes
        self.generation = snapshot.generation
        #: control-plane shard count for the prep accounting: the
        #: backing store's actual S when the caller knows it (the
        #: scheduler threads it from an in-process ShardedNodeStore),
        #: else resolved from the flagless policy.
        self._shards_override = shards
        #: incremental-prep handles (SchedulerCache stamps them on its
        #: snapshots; -1 = unknown, the legacy full-walk path).
        self.set_epoch = getattr(snapshot, "set_epoch", -1)
        self.spec_seq = getattr(snapshot, "spec_seq", -1)
        #: per-shard prep accounting (filled by both build paths):
        #: control-plane shard ids over the node axis and which shards'
        #: rows this build actually rewrote.
        self.prep_shards = 1
        self.shard_ids: np.ndarray | None = None
        self.shard_rebuilds: list[int] = []
        if self._init_delta(snapshot, resources, prev):
            return
        self.node_names = [ni.name for ni in nodes]
        self.name_to_idx = {n: i for i, n in enumerate(self.node_names)}
        self.n_real = len(nodes)
        self.n_pad = max(NODE_PAD, math.ceil(max(1, self.n_real) / NODE_PAD) * NODE_PAD)

        # Resource columns: union of any caller-pinned prefix (stable jit
        # signature ordering) with every resource allocatable on any node —
        # pinning is a minimum set, never exclusive, so a pod requesting a
        # node-present resource is always tracked. A resource absent from
        # *all* nodes stays untracked: the host path would reject such a pod
        # on every node anyway ("Insufficient <r>"), which is exactly what
        # the backend reports for it.
        seen = {r: None for r in (resources or ())}
        seen.setdefault(CPU, None)
        seen.setdefault(MEMORY, None)
        for ni in nodes:
            for r in ni.allocatable.res:
                seen.setdefault(r, None)
        self.resources = list(seen)
        self.r_index = {r: j for j, r in enumerate(self.resources)}
        R = len(self.resources)

        # Per-resource power-of-two scales (see module docstring).
        max_alloc = [1] * R
        for ni in nodes:
            for j, r in enumerate(self.resources):
                a = ni.allocatable.get(r)
                if a > max_alloc[j]:
                    max_alloc[j] = a
        self.scales = [_scale_for(m) for m in max_alloc]

        N, sc = self.n_pad, self.scales
        self.node_gens = [ni.generation for ni in nodes]

        # Incremental path (the UpdateSnapshot generation walk, SURVEY §2.3):
        # when the node set and columns are unchanged vs the previous
        # tensors, copy the previous arrays and re-quantize only nodes whose
        # generation advanced — per steady-state cycle that's ≤ the batch of
        # pods just assumed, not all N nodes. Fresh copies, never in-place:
        # jnp.asarray may alias numpy memory on the CPU backend.
        incremental = (
            prev is not None and prev.node_names == self.node_names
            and prev.resources == self.resources and prev.n_pad == N
            and prev.scales == self.scales)
        if incremental:
            self.alloc_q = prev.alloc_q.copy()
            self.used_q = prev.used_q.copy()
            self.used_nz_q = prev.used_nz_q.copy()
            self.alloc_pods = prev.alloc_pods.copy()
            self.used_pods = prev.used_pods.copy()
            changed = [i for i, g in enumerate(self.node_gens)
                       if prev.node_gens[i] != g]
        else:
            self.alloc_q = np.zeros((N, R), dtype=np.int32)
            self.used_q = np.zeros((N, R), dtype=np.int32)
            self.used_nz_q = np.zeros((N, R), dtype=np.int32)
            self.alloc_pods = np.zeros((N,), dtype=np.int32)
            self.used_pods = np.zeros((N,), dtype=np.int32)
            changed = range(len(nodes))
        for i in changed:
            ni = nodes[i]
            for j, r in enumerate(self.resources):
                self.alloc_q[i, j] = _quant_floor(ni.allocatable.get(r), sc[j])
                self.used_q[i, j] = _quant_ceil(ni.requested.get(r), sc[j])
                self.used_nz_q[i, j] = _quant_ceil(ni.nonzero_requested.get(r), sc[j])
            self.alloc_pods[i] = ni.allocatable.pods
            self.used_pods[i] = ni.requested.pods

        # Padding rows have zero capacity → never feasible; also carry an
        # explicit validity mask for score normalization.
        self.valid = np.zeros((N,), dtype=np.bool_)
        self.valid[: self.n_real] = True

        # Taints: reuse the interning when the static fingerprint matches.
        # Keyed on the monotonic spec_epoch (NOT id(node): a recycled dict
        # address could falsely match and serve stale taint matrices).
        fp = tuple((ni.name, ni.spec_epoch) for ni in nodes)
        if prev is not None and prev._static_fp == fp and prev.n_pad == N:
            self.taints = prev.taints
            self.taint_filter_mat = prev.taint_filter_mat
            self.taint_prefer_mat = prev.taint_prefer_mat
        else:
            self.taints = TaintTable(nodes)
            self.taint_filter_mat, self.taint_prefer_mat = \
                self.taints.node_rows(nodes, N)
        self._static_fp = fp
        # Topology coordinate planes (topology/planes): static per
        # node-set like the taint interning, absent entirely when the
        # kill switch is off (flat-capacity call graph, no new arrays).
        self.topology: TopologyPlanes | None = (
            build_topology_planes(
                nodes, N, getattr(prev, "topology", None))
            if flags.get("KTPU_TOPOLOGY") else None)
        self._shard_accounting(
            prev=prev if incremental else None,
            changed=changed if incremental else None)

    # -- shard-local delta build (the 200k control-plane path) --------------

    def _init_delta(self, snapshot: Snapshot,
                    resources: Sequence[str] | None,
                    prev: "ClusterTensors | None") -> bool:
        """Per-shard incremental build off the cache's event stream.

        When the node SET and every node OBJECT are unchanged since
        `prev` (set_epoch / spec_seq match) and the cache's changed-log
        still covers prev.generation, every O(N) walk of the full build
        is skipped: the static pieces (names, resource columns, scales,
        allocatable, taints) are SHARED with prev — spec_seq pins them
        identical, and the caller discards prev — while the used-state
        arrays are copied and only the rows of nodes whose generation
        advanced are re-quantized, grouped by control-plane shard for
        the rebuild accounting. O(changed) per generation instead of
        O(N): the host-prep half of ROADMAP #5's sharded scale-out.
        Node order is untouched, so assignments (and the index tie
        rule) stay bit-identical to the full build."""
        if prev is None or self.set_epoch < 0 \
                or self.set_epoch != getattr(prev, "set_epoch", -2) \
                or self.spec_seq != getattr(prev, "spec_seq", -2):
            return False
        changed_fn = getattr(snapshot, "changed_since", None)
        if changed_fn is None:
            return False
        changed = changed_fn(prev.generation)
        if changed is None:
            return False
        nodes = snapshot.nodes
        if len(nodes) != prev.n_real:
            return False  # stale epoch counters: take the full walk
        self.node_names = prev.node_names
        self.name_to_idx = prev.name_to_idx
        self.n_real = prev.n_real
        self.n_pad = prev.n_pad
        self.resources = prev.resources
        self.r_index = prev.r_index
        self.scales = prev.scales
        self.alloc_q = prev.alloc_q
        self.alloc_pods = prev.alloc_pods
        self.valid = prev.valid
        self.taints = prev.taints
        self.taint_filter_mat = prev.taint_filter_mat
        self.taint_prefer_mat = prev.taint_prefer_mat
        self._static_fp = prev._static_fp
        self.node_gens = list(prev.node_gens)
        self.used_q = prev.used_q.copy()
        self.used_nz_q = prev.used_nz_q.copy()
        self.used_pods = prev.used_pods.copy()
        sc = self.scales
        for i in changed:
            ni = nodes[i]
            self.node_gens[i] = ni.generation
            for j, r in enumerate(self.resources):
                self.used_q[i, j] = _quant_ceil(ni.requested.get(r), sc[j])
                self.used_nz_q[i, j] = _quant_ceil(
                    ni.nonzero_requested.get(r), sc[j])
            self.used_pods[i] = ni.requested.pods
        # spec_seq pins node specs identical, so the planes fingerprint
        # matches and this is a pure reuse (rebuilt=False) — unless the
        # mesh flags moved live, which forces the honest rebuild.
        self.topology = (
            build_topology_planes(
                nodes, self.n_pad, getattr(prev, "topology", None))
            if flags.get("KTPU_TOPOLOGY") else None)
        self._shard_accounting(prev=prev, changed=changed)
        return True

    def _shard_accounting(self, prev: "ClusterTensors | None",
                          changed) -> None:
        """Which control-plane shards' rows this build rewrote.
        `changed=None` means a full rebuild (every shard). Shard ids
        are computed once per node-set epoch and shared with prev."""
        from kubernetes_tpu.store.sharded import (
            control_plane_shards,
            shard_of,
        )
        S = control_plane_shards(self.n_real, self._shards_override)
        self.prep_shards = S
        if S <= 1:
            self.shard_rebuilds = [0] if (changed is None or changed) \
                else []
            return
        if prev is not None and prev.shard_ids is not None \
                and prev.prep_shards == S \
                and len(prev.shard_ids) == self.n_real:
            self.shard_ids = prev.shard_ids
        else:
            self.shard_ids = np.fromiter(
                (shard_of(n, S) for n in self.node_names),
                dtype=np.int32, count=self.n_real)
        if changed is None:
            self.shard_rebuilds = list(range(S))
        elif changed:
            self.shard_rebuilds = sorted(
                int(s) for s in np.unique(
                    self.shard_ids[np.fromiter(
                        changed, dtype=np.intp, count=len(changed))]))
        else:
            self.shard_rebuilds = []

    # -- per-pod compilation -------------------------------------------------

    def quantize_requests(self, requests: Mapping[str, int],
                          nonzero: Mapping[str, int]) -> tuple[np.ndarray, np.ndarray]:
        R = len(self.resources)
        q = np.zeros((R,), dtype=np.int32)
        qnz = np.zeros((R,), dtype=np.int32)
        for r, v in requests.items():
            j = self.r_index.get(r)
            if j is not None:
                q[j] = _quant_ceil(v, self.scales[j])
        for r, v in nonzero.items():
            j = self.r_index.get(r)
            if j is not None:
                qnz[j] = _quant_ceil(v, self.scales[j])
        return q, qnz

    def has_unknown_resource(self, requests: Mapping[str, int]) -> bool:
        """A pod requesting a resource no column tracks. Columns cover every
        resource allocatable on any node, so this means the resource exists
        nowhere in the cluster — infeasible on every node, same verdict the
        host path reaches ("Insufficient <r>"). The backend masks the pod
        out rather than silently dropping the constraint."""
        return any(r not in self.r_index for r, v in requests.items() if v)


class PodBatch:
    """Device-ready view of one batch of pending pods (padded to `p_pad`)."""

    def __init__(self, pods: Sequence[PodInfo], ct: ClusterTensors, p_pad: int):
        self.pods = list(pods)
        P = p_pad
        R = len(ct.resources)
        self.req_q = np.zeros((P, R), dtype=np.int32)
        self.req_nz_q = np.zeros((P, R), dtype=np.int32)
        tf = ct.taint_filter_mat.shape[1]
        tp = ct.taint_prefer_mat.shape[1]
        self.untol_filter = np.zeros((P, tf), dtype=np.bool_)
        self.untol_prefer = np.zeros((P, tp), dtype=np.bool_)
        # Row vectors cached by signature: workload pods come from
        # templates (the reference's equivalence-class observation), so
        # distinct request shapes / toleration lists are few per batch.
        tol_cache: dict[str, tuple[int, np.ndarray, np.ndarray]] = {}
        req_cache: dict[str, tuple[int, np.ndarray, np.ndarray]] = {}
        #: per-pod equivalence-class ids (index into the unique-row
        #: lists). These are the first two components of the backend's
        #: CLASS-DICTIONARY plane key (ops/backend._prep_chunk): the
        #: device ships (C,N) class planes + a (P,) index built on top
        #: of them, and the host score memos key their per-class
        #: normalization on the same ids — so the per-(P,N) broadcasts
        #: AND the per-pod plane uploads both collapse to per-class.
        self.req_class = np.zeros((P,), dtype=np.int32)
        self.untol_class = np.zeros((P,), dtype=np.int32)
        self.req_rows: list[np.ndarray] = []
        self.untol_rows: list[np.ndarray] = []
        for i, pi in enumerate(pods):
            rsig = repr(pi.requests) + "|" + repr(pi.nonzero_requests)
            rows = req_cache.get(rsig)
            if rows is None:
                q, qnz = ct.quantize_requests(
                    pi.requests, pi.nonzero_requests)
                rows = req_cache[rsig] = (len(self.req_rows), q, qnz)
                self.req_rows.append(q)
            cls, self.req_q[i], self.req_nz_q[i] = rows
            self.req_class[i] = cls
            sig = repr(pi.tolerations)
            cached = tol_cache.get(sig)
            if cached is None:
                uf = ct.taints.untolerated(pi.tolerations, "filter")
                up = ct.taints.untolerated(pi.tolerations, "prefer")
                cached = tol_cache[sig] = (len(self.untol_rows), uf, up)
                self.untol_rows.append(uf)
            tcls, self.untol_filter[i], self.untol_prefer[i] = cached
            self.untol_class[i] = tcls
        # Padding pods: no requests, all-false masks are applied by the
        # backend (their base mask row is zero), so they never get assigned.
        self.p_real = len(pods)
