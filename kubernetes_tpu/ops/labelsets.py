"""Label-set interning: the dense bridge for irregular label algebra.

SURVEY §7 hard-part #2: label-selector matching is set algebra over
irregular data. The observation that makes it dense: resident pods come from
templates, so the number of DISTINCT (namespace, label-dict) signatures is
tiny (tens) even in 150k-pod clusters. Interning signatures turns
"pods × selector" matching into:

    node_sig_count (N × U)   — how many resident pods of signature u on node n
    match_vec      (U,)      — does signature u match this selector (host,
                               U evaluations of the exact host Selector)
    counts (N,) = node_sig_count @ match_vec      — MXU-shaped

Topology domains intern the same way: `domain_ids (N,)` for a topology key
maps nodes to dense domain indices, so per-domain aggregation is a
segment-sum and per-node lookup is a gather — the affinity kernels
(ops/affinity.py) are built entirely from these three primitives.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from kubernetes_tpu.api.labels import from_label_selector, ns_contains
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


def _sig(pi: PodInfo) -> tuple:
    return (pi.namespace, tuple(sorted(pi.labels.items())))


class LabelSigTable:
    """Unique (namespace, labels) signatures of resident pods + per-node
    counts, split by pod population (all pods / pods with required
    anti-affinity terms need separate counting)."""

    def __init__(self, snapshot: Snapshot, n_pad: int):
        self.sigs: dict[tuple, int] = {}
        self.sig_examples: list[PodInfo] = []   # one pod per signature
        rows = []
        for ni in snapshot.nodes:
            counts: dict[int, int] = {}
            for pi in ni.pods:
                u = self._intern(pi)
                counts[u] = counts.get(u, 0) + 1
            rows.append(counts)
        U = max(1, len(self.sigs))
        self.node_sig_count = np.zeros((n_pad, U), dtype=np.float32)
        for n, counts in enumerate(rows):
            for u, c in counts.items():
                self.node_sig_count[n, u] = c
        #: selector-signature -> (U,) match vector cache
        self._match_cache: dict[str, np.ndarray] = {}

    def _intern(self, pi: PodInfo) -> int:
        s = _sig(pi)
        u = self.sigs.get(s)
        if u is None:
            u = self.sigs[s] = len(self.sig_examples)
            self.sig_examples.append(pi)
        return u

    def match_vec(self, label_selector: Mapping | None,
                  namespaces: Sequence[str]) -> np.ndarray:
        """(U,) float32: 1.0 where the signature's namespace ∈ namespaces and
        its labels match the selector — the exact host Selector semantics.
        `namespaces` may be labels.ALL_NAMESPACES ("*",) = every namespace."""
        key = repr((label_selector, tuple(namespaces)))
        vec = self._match_cache.get(key)
        if vec is None:
            sel = from_label_selector(label_selector)
            nset = set(namespaces)
            vec = np.zeros((max(1, len(self.sig_examples)),), dtype=np.float32)
            for u, pi in enumerate(self.sig_examples):
                if ns_contains(nset, pi.namespace) and sel.matches(pi.labels):
                    vec[u] = 1.0
            self._match_cache[key] = vec
        return vec


class TopologyTable:
    """Per-topology-key dense domain ids (lazily built, cached)."""

    def __init__(self, nodes: Sequence[NodeInfo], n_pad: int):
        self._nodes = nodes
        self._n_pad = n_pad
        self._cache: dict[str, tuple[np.ndarray, int]] = {}

    def domains(self, topology_key: str) -> tuple[np.ndarray, int]:
        """(domain_ids (n_pad,) int32, num_domains). Nodes WITHOUT the key
        get the reserved domain 0 ("no domain" — always treated separately
        via the has_key mask); real domains start at 1."""
        got = self._cache.get(topology_key)
        if got is None:
            ids = np.zeros((self._n_pad,), dtype=np.int32)
            interned: dict[str, int] = {}
            for n, ni in enumerate(self._nodes):
                v = ni.labels.get(topology_key)
                if v is None:
                    continue
                d = interned.get(v)
                if d is None:
                    d = interned[v] = len(interned) + 1
                ids[n] = d
            got = (ids, len(interned) + 1)
            self._cache[topology_key] = got
        return got

    def has_key(self, topology_key: str) -> np.ndarray:
        return self.domains(topology_key)[0] > 0
