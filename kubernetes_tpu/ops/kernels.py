"""Batched (P pods × N nodes) filter-mask and score kernels.

Each kernel is the tensorized twin of one in-tree plugin's Filter/Score
(SURVEY §2.3 table: NodeResourcesFit / NodeResourcesBalancedAllocation /
TaintToleration are the north-star tensorization set). The host plugins in
scheduler/plugins/ stay the correctness oracle; tests/test_tpu_backend.py
differential-tests every kernel against them on randomized clusters.

All kernels are pure jnp functions over fixed-shape arrays (no Python control
flow on data), composed and jitted once per shape signature by the backend.
Scores follow the reference's two-phase shape: raw score then per-pod
NormalizeScore over the *feasible* set only, then plugin weight — weights are
applied by the backend when summing.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100.0


# --- NodeResourcesFit: Filter ------------------------------------------------

def fit_filter_mask(alloc_q, used_q, used_pods, alloc_pods, req_q):
    """(noderesources/fit.go `fitsRequest`) feasibility of every (pod, node):
    per-resource `used + req <= alloc` AND pod-count headroom.

    alloc_q/used_q: (N,R) int32; used_pods/alloc_pods: (N,) int32;
    req_q: (P,R) int32 → (P,N) bool.
    """
    res_ok = jnp.all(
        used_q[None, :, :] + req_q[:, None, :] <= alloc_q[None, :, :], axis=-1)
    pods_ok = (used_pods + 1 <= alloc_pods)[None, :]
    return res_ok & pods_ok


# --- TaintToleration: Filter -------------------------------------------------

def taint_filter_mask(node_taints, untolerated):
    """(tainttoleration `Filter`) node is infeasible iff it carries any
    NoSchedule/NoExecute taint the pod does not tolerate.

    node_taints: (N,T) bool membership; untolerated: (P,T) bool → (P,N) bool.
    """
    conflicts = jnp.einsum("pt,nt->pn", untolerated.astype(jnp.int32),
                           node_taints.astype(jnp.int32))
    return conflicts == 0


# --- NodeResourcesFit: Score -------------------------------------------------

def fit_score(alloc_q, used_nz_q, req_nz_q, col_weights, strategy: str,
              shape_u=None, shape_s=None):
    """(resource_allocation.go score loop) weighted mean over scoring
    resources of the per-resource strategy score; columns with zero
    allocatable are excluded from the mean (host `_score_one` skip).

    alloc_q/used_nz_q: (N,R); req_nz_q: (P,R); col_weights: (R,) float32 with
    0 for non-scored columns → (P,N) float32 in [0, 100].
    """
    alloc = alloc_q.astype(jnp.float32)[None, :, :]           # (1,N,R)
    req = (used_nz_q[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    valid = (alloc > 0) & (col_weights[None, None, :] > 0)
    safe_alloc = jnp.where(alloc > 0, alloc, 1.0)
    if strategy == "MostAllocated":
        s = MAX_NODE_SCORE * req / safe_alloc
        s = jnp.where(req > alloc, 0.0, s)
    elif strategy == "RequestedToCapacityRatio":
        util = MAX_NODE_SCORE * req / safe_alloc
        s = _piecewise(util, shape_u, shape_s) * (MAX_NODE_SCORE / 10.0)
        s = jnp.where(req > alloc, 0.0, s)
    else:  # LeastAllocated
        s = MAX_NODE_SCORE * (alloc - req) / safe_alloc
        s = jnp.where(req > alloc, 0.0, s)
    w = jnp.where(valid, col_weights[None, None, :], 0.0)
    tot_w = jnp.sum(w, axis=-1)
    acc = jnp.sum(s * w, axis=-1)
    return jnp.where(tot_w > 0, acc / jnp.where(tot_w > 0, tot_w, 1.0), 0.0)


def _piecewise(util, shape_u, shape_s):
    """Piecewise-linear shape evaluation (requested_to_capacity_ratio.go);
    shape_u/shape_s are small 1-D point arrays, util broadcasts over them."""
    u = util[..., None]                                      # (...,1)
    below = u <= shape_u[0]
    above = u >= shape_u[-1]
    # Segment interpolation: for each interval i, value if u lands in it.
    u0, u1 = shape_u[:-1], shape_u[1:]
    s0, s1 = shape_s[:-1], shape_s[1:]
    t = (u - u0) / jnp.where(u1 - u0 > 0, u1 - u0, 1.0)
    seg_val = s0 + (s1 - s0) * t
    in_seg = (u > u0) & (u <= u1)
    mid = jnp.sum(jnp.where(in_seg, seg_val, 0.0), axis=-1)
    return jnp.where(below[..., 0], shape_s[0],
                     jnp.where(above[..., 0], shape_s[-1], mid))


# --- NodeResourcesBalancedAllocation: Score ---------------------------------

def balanced_allocation_score(alloc_q, used_nz_q, req_nz_q, col_mask):
    """(balanced_allocation.go) 100 × (1 − stddev of per-resource requested
    fractions); fractions clamped to 1; nodes with <2 scorable resources → 0.

    col_mask: (R,) bool — which columns the plugin scores over.
    """
    alloc = alloc_q.astype(jnp.float32)[None, :, :]
    req = (used_nz_q[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    valid = (alloc > 0) & col_mask[None, None, :]
    frac = jnp.minimum(req / jnp.where(alloc > 0, alloc, 1.0), 1.0)
    frac = jnp.where(valid, frac, 0.0)
    cnt = jnp.sum(valid, axis=-1).astype(jnp.float32)
    safe_cnt = jnp.where(cnt > 0, cnt, 1.0)
    mean = jnp.sum(frac, axis=-1) / safe_cnt
    var = jnp.sum(jnp.where(valid, (frac - mean[..., None]) ** 2, 0.0),
                  axis=-1) / safe_cnt
    score = (1.0 - jnp.sqrt(var)) * MAX_NODE_SCORE
    return jnp.where(cnt >= 2, score, 0.0)


# --- shortlist prefilter: chunk-start live scores ---------------------------

def chunk_start_scores(alloc_q, used_nz_q, req_nz_q, static_scores,
                       fit_col_w, bal_col_mask, shape_u, shape_s,
                       w_fit, w_bal, strategy: str):
    """The full live score (static + weighted fit + weighted balanced) at the
    CHUNK-START used state — the shortlist prefilter's per-node value.

    Two roles in the pruned solve (ops/solver shortlist scans):

    - ordering: per pod, the top-K nodes by this value are the candidate
      columns the narrow scan re-scores live; the (K+1)-th value is the
      exactness threshold.
    - identity: within a chunk, a node's live score changes ONLY when the
      node is debited by an assignment, so for UNTOUCHED nodes this value
      IS the in-scan score, bit-for-bit — the scans gather it back instead
      of recomputing, keeping the threshold comparison float-consistent.

    alloc_q/used_nz_q: (N,R); req_nz_q: (S,R); static_scores: (S,N)
    → (S,N) float32.
    """
    sc = static_scores + w_fit * fit_score(
        alloc_q, used_nz_q, req_nz_q, fit_col_w, strategy, shape_u, shape_s)
    return sc + w_bal * balanced_allocation_score(
        alloc_q, used_nz_q, req_nz_q, bal_col_mask)


# --- TaintToleration: Score --------------------------------------------------

def taint_toleration_score(node_prefer_taints, untol_prefer, feasible):
    """(taint_toleration.go Score+NormalizeScore) raw = count of untolerated
    PreferNoSchedule taints; normalized per pod over feasible nodes to
    100×(max−count)/max (all-100 when max is 0).

    node_prefer_taints: (N,Tp) bool; untol_prefer: (P,Tp) bool;
    feasible: (P,N) bool → (P,N) float32.
    """
    counts = jnp.einsum("pt,nt->pn", untol_prefer.astype(jnp.float32),
                        node_prefer_taints.astype(jnp.float32))
    mx = jnp.max(jnp.where(feasible, counts, -jnp.inf), axis=1, keepdims=True)
    mx = jnp.maximum(mx, 0.0)
    return jnp.where(mx > 0, MAX_NODE_SCORE * (mx - counts) / mx,
                     MAX_NODE_SCORE)
