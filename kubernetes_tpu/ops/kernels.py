"""Batched (P pods × N nodes) filter-mask and score kernels.

Each kernel is the tensorized twin of one in-tree plugin's Filter/Score
(SURVEY §2.3 table: NodeResourcesFit / NodeResourcesBalancedAllocation /
TaintToleration are the north-star tensorization set). The host plugins in
scheduler/plugins/ stay the correctness oracle; tests/test_tpu_backend.py
differential-tests every kernel against them on randomized clusters.

All kernels are pure jnp functions over fixed-shape arrays (no Python control
flow on data), composed and jitted once per shape signature by the backend.
Scores follow the reference's two-phase shape: raw score then per-pod
NormalizeScore over the *feasible* set only, then plugin weight — weights are
applied by the backend when summing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_NODE_SCORE = 100.0

#: Absolute slack folded into every block score upper bound. The bound
#: kernels evaluate the score formulas at per-block interval corners
#: with the same op sequence as the full kernels, so the REAL-arithmetic
#: corner dominates every column; the slack absorbs the f32 rounding
#: divergence between the corner evaluation and the per-column
#: evaluations (score magnitudes are O(1e3), op chains O(10) deep —
#: worst-case drift ~4e-3, so 2^-3 is a ≥30× margin). A bound padded up
#: can only LOSE pruning opportunities, never exactness.
BLOCK_UB_EPS = 0.125

#: Sentinel for masked-out columns in per-block minima: large enough to
#: never be a real min, small enough that +req never overflows int32.
_BLOCK_BIG = 2 ** 30


# --- NodeResourcesFit: Filter ------------------------------------------------

def fit_filter_mask(alloc_q, used_q, used_pods, alloc_pods, req_q):
    """(noderesources/fit.go `fitsRequest`) feasibility of every (pod, node):
    per-resource `used + req <= alloc` AND pod-count headroom.

    alloc_q/used_q: (N,R) int32; used_pods/alloc_pods: (N,) int32;
    req_q: (P,R) int32 → (P,N) bool.
    """
    res_ok = jnp.all(
        used_q[None, :, :] + req_q[:, None, :] <= alloc_q[None, :, :], axis=-1)
    pods_ok = (used_pods + 1 <= alloc_pods)[None, :]
    return res_ok & pods_ok


# --- TaintToleration: Filter -------------------------------------------------

def taint_filter_mask(node_taints, untolerated):
    """(tainttoleration `Filter`) node is infeasible iff it carries any
    NoSchedule/NoExecute taint the pod does not tolerate.

    node_taints: (N,T) bool membership; untolerated: (P,T) bool → (P,N) bool.
    """
    conflicts = jnp.einsum("pt,nt->pn", untolerated.astype(jnp.int32),
                           node_taints.astype(jnp.int32))
    return conflicts == 0


# --- NodeResourcesFit: Score -------------------------------------------------

def fit_score(alloc_q, used_nz_q, req_nz_q, col_weights, strategy: str,
              shape_u=None, shape_s=None):
    """(resource_allocation.go score loop) weighted mean over scoring
    resources of the per-resource strategy score; columns with zero
    allocatable are excluded from the mean (host `_score_one` skip).

    alloc_q/used_nz_q: (N,R); req_nz_q: (P,R); col_weights: (R,) float32 with
    0 for non-scored columns → (P,N) float32 in [0, 100].
    """
    alloc = alloc_q.astype(jnp.float32)[None, :, :]           # (1,N,R)
    req = (used_nz_q[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    valid = (alloc > 0) & (col_weights[None, None, :] > 0)
    safe_alloc = jnp.where(alloc > 0, alloc, 1.0)
    if strategy == "MostAllocated":
        s = MAX_NODE_SCORE * req / safe_alloc
        s = jnp.where(req > alloc, 0.0, s)
    elif strategy == "RequestedToCapacityRatio":
        util = MAX_NODE_SCORE * req / safe_alloc
        s = _piecewise(util, shape_u, shape_s) * (MAX_NODE_SCORE / 10.0)
        s = jnp.where(req > alloc, 0.0, s)
    else:  # LeastAllocated
        s = MAX_NODE_SCORE * (alloc - req) / safe_alloc
        s = jnp.where(req > alloc, 0.0, s)
    w = jnp.where(valid, col_weights[None, None, :], 0.0)
    tot_w = jnp.sum(w, axis=-1)
    acc = jnp.sum(s * w, axis=-1)
    return jnp.where(tot_w > 0, acc / jnp.where(tot_w > 0, tot_w, 1.0), 0.0)


def _piecewise(util, shape_u, shape_s):
    """Piecewise-linear shape evaluation (requested_to_capacity_ratio.go);
    shape_u/shape_s are small 1-D point arrays, util broadcasts over them."""
    u = util[..., None]                                      # (...,1)
    below = u <= shape_u[0]
    above = u >= shape_u[-1]
    # Segment interpolation: for each interval i, value if u lands in it.
    u0, u1 = shape_u[:-1], shape_u[1:]
    s0, s1 = shape_s[:-1], shape_s[1:]
    t = (u - u0) / jnp.where(u1 - u0 > 0, u1 - u0, 1.0)
    seg_val = s0 + (s1 - s0) * t
    in_seg = (u > u0) & (u <= u1)
    mid = jnp.sum(jnp.where(in_seg, seg_val, 0.0), axis=-1)
    return jnp.where(below[..., 0], shape_s[0],
                     jnp.where(above[..., 0], shape_s[-1], mid))


# --- NodeResourcesBalancedAllocation: Score ---------------------------------

def balanced_allocation_score(alloc_q, used_nz_q, req_nz_q, col_mask):
    """(balanced_allocation.go) 100 × (1 − stddev of per-resource requested
    fractions); fractions clamped to 1; nodes with <2 scorable resources → 0.

    col_mask: (R,) bool — which columns the plugin scores over.
    """
    alloc = alloc_q.astype(jnp.float32)[None, :, :]
    req = (used_nz_q[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    valid = (alloc > 0) & col_mask[None, None, :]
    frac = jnp.minimum(req / jnp.where(alloc > 0, alloc, 1.0), 1.0)
    frac = jnp.where(valid, frac, 0.0)
    cnt = jnp.sum(valid, axis=-1).astype(jnp.float32)
    safe_cnt = jnp.where(cnt > 0, cnt, 1.0)
    mean = jnp.sum(frac, axis=-1) / safe_cnt
    var = jnp.sum(jnp.where(valid, (frac - mean[..., None]) ** 2, 0.0),
                  axis=-1) / safe_cnt
    score = (1.0 - jnp.sqrt(var)) * MAX_NODE_SCORE
    return jnp.where(cnt >= 2, score, 0.0)


# --- shortlist prefilter: chunk-start live scores ---------------------------

def chunk_start_scores(alloc_q, used_nz_q, req_nz_q, static_scores,
                       fit_col_w, bal_col_mask, shape_u, shape_s,
                       w_fit, w_bal, strategy: str):
    """The full live score (static + weighted fit + weighted balanced) at the
    CHUNK-START used state — the shortlist prefilter's per-node value.

    Two roles in the pruned solve (ops/solver shortlist scans):

    - ordering: per pod, the top-K nodes by this value are the candidate
      columns the narrow scan re-scores live; the (K+1)-th value is the
      exactness threshold.
    - identity: within a chunk, a node's live score changes ONLY when the
      node is debited by an assignment, so for UNTOUCHED nodes this value
      IS the in-scan score, bit-for-bit — the scans gather it back instead
      of recomputing, keeping the threshold comparison float-consistent.

    alloc_q/used_nz_q: (N,R); req_nz_q: (S,R); static_scores: (S,N)
    → (S,N) float32.
    """
    sc = static_scores + w_fit * fit_score(
        alloc_q, used_nz_q, req_nz_q, fit_col_w, strategy, shape_u, shape_s)
    return sc + w_bal * balanced_allocation_score(
        alloc_q, used_nz_q, req_nz_q, bal_col_mask)


# --- block-sparse node index: aggregates + bounds ---------------------------

def _block_fold(x, block_w: int, fill):
    """Reshape the leading N axis into (B, block_w, ...) blocks, padding
    the tail block with `fill` so every aggregate below stays a plain
    fixed-shape reduce (the N % block_w != 0 case)."""
    n = x.shape[0]
    b = -(-n // block_w)
    pad = b * block_w - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)], axis=0)
    return x.reshape((b, block_w) + x.shape[1:])


def block_capacity_aggregates(alloc_q, used_nz_q, col_real, block_w: int):
    """Per-block capacity interval planes over the REAL node columns:
    (amin_pos, amin, amax, umin, umax), each (B, R) int32.

    amin/amax/umin/umax are plain real-column min/max of allocatable and
    scoring-used; amin_pos is the min over columns with alloc > 0 (the
    only columns the fit mean scores, so it is the right denominator
    corner for MostAllocated/RTCR utilization), while the plain amin
    exists for the uniform-block arm of the exactness predicate —
    amin == amax certifies every real column shares one alloc vector,
    which amin_pos cannot (it would miss a zero-alloc column hiding
    among equal nonzero ones). Masked-out columns (padding, alloc == 0
    for amin_pos) fold in as inert sentinels — a pad-only block ends up
    with min > max, which no bound below can mistake for a uniform
    block.
    """
    rmask = col_real[:, None]
    amax = jnp.max(_block_fold(
        jnp.where(rmask, alloc_q, 0), block_w, 0), axis=1)
    amin = jnp.min(_block_fold(
        jnp.where(rmask, alloc_q, _BLOCK_BIG), block_w, _BLOCK_BIG),
        axis=1)
    amin_pos = jnp.min(_block_fold(
        jnp.where(rmask & (alloc_q > 0), alloc_q, _BLOCK_BIG),
        block_w, _BLOCK_BIG), axis=1)
    umax = jnp.max(_block_fold(
        jnp.where(rmask, used_nz_q, 0), block_w, 0), axis=1)
    umin = jnp.min(_block_fold(
        jnp.where(rmask, used_nz_q, _BLOCK_BIG), block_w, _BLOCK_BIG),
        axis=1)
    return amin_pos, amin, amax, umin, umax


def block_feasible_stat(feasible, static_scores, block_w: int):
    """Per-(class, block) planes of the capacity-independent score over
    the FEASIBLE columns: (stat_max, stat_min, feas_cnt), each (C, B).

    feas_cnt is the bit-mask popcount per block; stat_max feeds the
    score upper bound (a block with no feasible column is -inf and can
    never gate a fallback), stat_min exists for the uniform-block
    equality arm of the exactness predicate (stat_min == stat_max means
    every feasible column shares one static score).
    """
    masked_max = _block_fold(
        jnp.where(feasible, static_scores, -jnp.inf).T, block_w, -jnp.inf)
    masked_min = _block_fold(
        jnp.where(feasible, static_scores, jnp.inf).T, block_w, jnp.inf)
    cnt = _block_fold(feasible.T.astype(jnp.int32), block_w, 0)
    return (jnp.max(masked_max, axis=1).T,
            jnp.min(masked_min, axis=1).T,
            jnp.sum(cnt, axis=1).T)


def block_score_upper_bound(stat_max, feas_cnt, amin_pos, amax, umin,
                            umax, req_nz_q, fit_col_w, bal_col_mask,
                            shape_u, shape_s, w_fit, w_bal,
                            strategy: str):
    """(C, B) upper bound on the chunk-start live score of any feasible
    column in each block — the block-bound scan of the two-pass
    prefilter.

    Per resource, the strategy score is evaluated at the interval
    corner that maximizes it (fit_score is monotone per strategy in
    used and alloc; RTCR additionally checks its piecewise breakpoints
    inside the utilization interval). The weighted mean over scoring
    resources is bounded by the max of the per-resource bounds (a
    weighted average never exceeds the largest capped term — exact for
    any per-column valid-resource pattern). The balanced-allocation
    term is bounded by its range cap. BLOCK_UB_EPS absorbs f32 corner
    rounding; blocks with no feasible column are -inf.
    """
    af_min = amin_pos.astype(jnp.float32)[None, :, :]       # (1,B,R)
    af_max = amax.astype(jnp.float32)[None, :, :]
    r_lo = (umin[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    r_hi = (umax[None, :, :] + req_nz_q[:, None, :]).astype(jnp.float32)
    safe_max = jnp.where(af_max > 0, af_max, 1.0)
    safe_min = jnp.where(af_min > 0, af_min, 1.0)
    if strategy == "MostAllocated":
        s_ub = jnp.clip(MAX_NODE_SCORE * r_hi / safe_min,
                        0.0, MAX_NODE_SCORE)
    elif strategy == "RequestedToCapacityRatio":
        # Utilization interval corners, widened a hair so fl rounding
        # cannot shrink the interval past a column's true utilization.
        # No 100-cap here: fit_score leaves the scaled piecewise value
        # uncapped, so the bound must not cap it either.
        u_lo = MAX_NODE_SCORE * r_lo / safe_max - 0.01
        u_hi = MAX_NODE_SCORE * r_hi / safe_min + 0.01
        ends = jnp.maximum(_piecewise(u_lo, shape_u, shape_s),
                           _piecewise(u_hi, shape_u, shape_s))
        inside = (shape_u >= u_lo[..., None]) & (shape_u <= u_hi[..., None])
        bps = jnp.max(jnp.where(inside, shape_s, -jnp.inf), axis=-1)
        s_ub = jnp.maximum(jnp.maximum(ends, bps)
                           * (MAX_NODE_SCORE / 10.0), 0.0)
    else:  # LeastAllocated
        s_ub = jnp.clip(MAX_NODE_SCORE * (af_max - r_lo) / safe_max,
                        0.0, MAX_NODE_SCORE)
    scored = (fit_col_w[None, None, :] > 0) & (af_max > 0)
    fit_ub = jnp.max(jnp.where(scored, s_ub, 0.0), axis=-1)    # (C,B)
    bal_ub = jnp.where(w_bal > 0, MAX_NODE_SCORE, 0.0)
    ub = stat_max + w_fit * fit_ub + w_bal * bal_ub + BLOCK_UB_EPS
    return jnp.where(feas_cnt > 0, ub, -jnp.inf)


def gathered_start_scores(alloc_g, used_nz_g, req_nz_q, static_g,
                          fit_col_w, bal_col_mask, shape_u, shape_s,
                          w_fit, w_bal, strategy: str):
    """chunk_start_scores over per-class GATHERED columns: alloc_g and
    used_nz_g are (C, G, R) per-class gathers of the capacity planes,
    static_g/req_nz_q the matching (C, G)/(C, R) rows → (C, G) f32.

    One vmapped single-class evaluation of the SAME kernels, the
    live_scores idiom of the shortlist-wave scan: an untouched gathered
    column's value is the same arithmetic the full-width pass runs, so
    the scans' threshold comparisons stay float-consistent with the
    block-gated prefilter's shortlist values.
    """
    def one(alloc_r, used_r, req_r, stat_r):
        sc = stat_r + w_fit * fit_score(
            alloc_r, used_r, req_r[None, :], fit_col_w, strategy,
            shape_u, shape_s)[0]
        return sc + w_bal * balanced_allocation_score(
            alloc_r, used_r, req_r[None, :], bal_col_mask)[0]
    return jax.vmap(one)(alloc_g, used_nz_g, req_nz_q, static_g)


# --- TaintToleration: Score --------------------------------------------------

def taint_toleration_score(node_prefer_taints, untol_prefer, feasible):
    """(taint_toleration.go Score+NormalizeScore) raw = count of untolerated
    PreferNoSchedule taints; normalized per pod over feasible nodes to
    100×(max−count)/max (all-100 when max is 0).

    node_prefer_taints: (N,Tp) bool; untol_prefer: (P,Tp) bool;
    feasible: (P,N) bool → (P,N) float32.
    """
    counts = jnp.einsum("pt,nt->pn", untol_prefer.astype(jnp.float32),
                        node_prefer_taints.astype(jnp.float32))
    mx = jnp.max(jnp.where(feasible, counts, -jnp.inf), axis=1, keepdims=True)
    mx = jnp.maximum(mx, 0.0)
    return jnp.where(mx > 0, MAX_NODE_SCORE * (mx - counts) / mx,
                     MAX_NODE_SCORE)
