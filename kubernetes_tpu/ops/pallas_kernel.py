"""Pallas fused wavefront solve kernel.

The lax.scan wavefront step (ops/solver.py `_rescoring_wave_scan`) emits
a CHAIN of small XLA ops per wave — class-plane gather, bit-mask unpack,
fit/balanced scoring, prefix-distinct argmax, (W,W) conflict re-score,
capacity debit — with the carry bouncing through HBM between them. The
whole working set fits VMEM at production chunk shapes ((C,N/8) bit mask
+ (C,N) class planes at C ≤ 31 is ~25 KB/chunk at 50k nodes, plus a
W ≤ 64 register-resident conflict block), so this module fuses ONE wave
step into ONE Pallas grid step with the used-state carry resident:

    grid = (K, n_waves)        # K multistart orders, waves innermost
    step(k, i):
        carry  = per-k output blocks (free_q / free_pods / used_nz),
                 seeded from the chunk state at i == 0 and persisted
                 across grid steps (index map constant in i)
        fused  = unpack packed mask bits -> gather class planes ->
                 fit/balanced score -> prefix-distinct wave argmax ->
                 pairwise (W,W) conflict re-score -> capacity debit

Bit-identity contract: the kernel body runs the SAME op sequence as the
scan's `wave_step` — it calls the identical `ops/kernels.py` score
functions and the identical `_wave_spec_picks`/`_wave_conflicts` helpers
from ops/solver.py on values read from refs — so assignments are
bit-identical to the lax.scan reference at every wave width, strategy,
and class-plane shape. The scan REMAINS the semantic reference: routing
is off by default on CPU (`KTPU_PALLAS=auto`), interpret mode validates
the kernel on CPU tier-1, and compiled mode activates only on
accelerator backends, with structural fallback to the scan (counted in
`solver_pallas_fallbacks_total`) when lowering is unavailable or the
chunk shape is unsupported.

Unsupported shapes, stated honestly: the kernel holds the full (C,N)
planes and the (W,N) wave evaluation in one grid step, so chunks whose
working set exceeds `MAX_STATE_BYTES` fall back to the scan until an
N-blocked variant exists. Spread, shortlist, and the Sinkhorn optimal
mode keep their scan forms (each is a different fusion shape); the
router counts each as a distinct fallback reason.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_tpu.ops import kernels
from kubernetes_tpu.ops import solver

NEG_INF = -jnp.inf

#: per-grid-step working-set ceiling (bytes). The fused step keeps the
#: unpacked (C,N) mask, the (C,N) score plane, the (W,N) evaluation
#: block, and the (N,R) carries resident at once; chunks above this
#: fall back to the scan with reason="shape".
MAX_STATE_BYTES = 128 * 1024 * 1024


def is_available() -> bool:
    """Pallas importability on this jax build (cheap, cached)."""
    return _import_pallas() is not None


@functools.lru_cache(maxsize=1)
def _import_pallas():
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        return pl
    except Exception:  # pragma: no cover - pallas ships with jax>=0.4
        return None


def state_bytes(n_nodes: int, n_classes: int, n_res: int,
                wave_w: int) -> int:
    """Estimate of one grid step's resident working set."""
    planes = n_classes * n_nodes * 5          # bool mask + f32 scores
    wave = wave_w * n_nodes * 9               # fits/sc/masked blocks
    carry = n_nodes * n_res * 16 + n_nodes * 8
    return planes + wave + carry


def unsupported_reason(n_nodes: int, n_classes: int, n_res: int,
                       wave_w: int) -> str | None:
    """Structural shape gate: None = the kernel supports this chunk,
    else the scan-fallback reason for `solver_pallas_fallbacks_total`."""
    if not is_available():
        return "unavailable"
    if wave_w < 2:
        return "wave_off"
    if n_nodes < 1 or n_classes < 1:
        return "shape"
    if state_bytes(n_nodes, n_classes, n_res, wave_w) > MAX_STATE_BYTES:
        return "shape"
    return None


@functools.lru_cache(maxsize=4)
def lowering_supported(platform: str) -> bool:
    """Can COMPILED (non-interpret) pallas lower on `platform`?

    Probed once per process by compiling a trivial kernel; interpret
    mode never needs this. CPU answers False without probing — the
    pallas CPU path IS interpret mode, and the scan is faster there.
    """
    if platform == "cpu" or not is_available():
        return False
    pl = _import_pallas()

    def _probe_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1

    try:
        fn = pl.pallas_call(
            _probe_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))
        jax.jit(fn).lower(
            jax.ShapeDtypeStruct((8, 128), jnp.float32)).compile()
        return True
    except Exception:
        return False


def default_interpret() -> bool:
    """Interpret mode unless a compiled lowering is actually available."""
    return not lowering_supported(jax.default_backend())


# ---------------------------------------------------------------------------
# fused wave-step solve: the whole wavefront scan as one pallas_call
# ---------------------------------------------------------------------------

def wave_solve(req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
               mask, static_scores, fit_col_w, bal_col_mask, shape_u,
               shape_s, w_fit, w_bal, strategy: str, wave_w: int,
               rows, exc, *, poison: bool, perms=None,
               interpret: bool = True):
    """Run the full wavefront solve as one fused pallas_call.

    Argument contract matches `solver._rescoring_wave_scan` (class
    planes addressed through `rows`, sparse exception column `exc`),
    plus `perms`: None runs the single identity order (K=1, the
    `greedy_assign_rescoring_wave` shape, with the exact in-step serial
    replay when `poison=False`); a (K,P) permutation batch runs all K
    orders in the SAME pallas_call — the grid's major axis — each with
    its own carry block (the vmapped-multistart shape, `poison=True`:
    speculation always commits and the first conflict poisons order k).

    Returns (assign (K, P) int32 in PERMUTED pod coordinates,
    commits (K,), replays (K,), poisoned (K,) bool) — the caller
    un-permutes and selects, exactly like the scan wrappers.
    """
    pl = _import_pallas()
    n = free_q.shape[0]
    p = req_q.shape[0]
    r = req_q.shape[1]
    W = max(1, min(wave_w, p))
    ex = jnp.full((p,), -1, jnp.int32) if exc is None else exc
    if perms is None:
        perm_ix = jnp.arange(p, dtype=jnp.int32)[None]
    else:
        perm_ix = perms
    K = perm_ix.shape[0]

    # Per-order pod streams, padded and reshaped to waves exactly like
    # solver._wave_split (zero padding; the real mask gates the rest).
    req_k = req_q[perm_ix]                                 # (K,P,R)
    rnz_k = req_nz_q[perm_ix]
    row_k = rows[perm_ix]
    ex_k = ex[perm_ix]
    pad = (-p) % W
    if pad:
        req_k = jnp.concatenate(
            [req_k, jnp.zeros((K, pad, r), req_k.dtype)], axis=1)
        rnz_k = jnp.concatenate(
            [rnz_k, jnp.zeros((K, pad, r), rnz_k.dtype)], axis=1)
        row_k = jnp.concatenate(
            [row_k, jnp.zeros((K, pad), row_k.dtype)], axis=1)
        ex_k = jnp.concatenate(
            [ex_k, jnp.zeros((K, pad), ex_k.dtype)], axis=1)
    nw = (p + pad) // W
    req_w = req_k.reshape(K, nw, W, r)
    rnz_w = rnz_k.reshape(K, nw, W, r)
    row_w = row_k.reshape(K, nw, W)
    ex_w = ex_k.reshape(K, nw, W)
    real_w = (jnp.arange(p + pad, dtype=jnp.int32) < p).reshape(nw, W)

    # The kernel receives the mask PACKED and unpacks in-step — the
    # fused form of the backend's bit-plane unpack stage. pack/unpack
    # of a bool plane is exact, so bit-identity is unaffected.
    bits = jnp.packbits(mask, axis=1)                      # (C, ceil(N/8))

    def _wave_step_kernel(req_ref, rnz_ref, row_ref, ex_ref, real_ref,
                          bits_ref, sc_ref, alloc_ref, fq0_ref, fp0_ref,
                          unz0_ref, colw_ref, balm_ref, su_ref, ss_ref,
                          wf_ref, wb_ref,
                          out_ref, stat_ref, cq_ref, cp_ref, cu_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _seed():
            # Fresh carry per order k: the chunk state enters once and
            # stays resident in the kernel's output blocks thereafter.
            cq_ref[...] = fq0_ref[...][None]
            cp_ref[...] = fp0_ref[...][None]
            cu_ref[...] = unz0_ref[...][None]
            stat_ref[...] = jnp.zeros_like(stat_ref)

        free_q = cq_ref[0]
        free_pods = cp_ref[0]
        used_nz = cu_ref[0]
        ncom = stat_ref[0, 0]
        nrep = stat_ref[0, 1]
        pois = stat_ref[0, 2]

        req = req_ref[0, 0]                                # (W,R)
        req_nz = rnz_ref[0, 0]
        row = row_ref[0, 0]                                # (W,)
        e = ex_ref[0, 0]
        real = real_ref[0]
        alloc_q = alloc_ref[...]
        static_scores = sc_ref[...]
        fit_col_w = colw_ref[...]
        bal_col_mask = balm_ref[...]
        shape_u = su_ref[...]
        shape_s = ss_ref[...]
        w_fit = wf_ref[0]
        w_bal = wb_ref[0]

        # Bit-mask unpack (big-endian, the backend's shift order).
        # A negative-step arange materializes as a captured constant,
        # which pallas kernels forbid — build the 7..0 shifts from iota.
        shifts = (7 - lax.broadcasted_iota(jnp.int32, (8,), 0)) \
            .astype(jnp.uint8)
        packed = bits_ref[...]
        mask = ((packed[:, :, None] >> shifts) & 1).reshape(
            packed.shape[0], -1).astype(jnp.bool_)[:, :n]

        # --- identical op sequence to solver's wave_step -------------
        iota_n = jnp.arange(n, dtype=jnp.int32)
        m = mask[row]
        m = m & ((e < 0)[:, None] | (iota_n[None, :] == e[:, None]))
        m = m & real[:, None]
        fits = m & jnp.all(req[:, None, :] <= free_q[None, :, :],
                           axis=-1) & (free_pods >= 1)[None, :]
        sc = static_scores[row]
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz, fit_col_w, strategy, shape_u,
            shape_s)
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz, bal_col_mask)
        masked = jnp.where(fits, sc, NEG_INF)
        node_of = jnp.broadcast_to(iota_n[None, :], masked.shape)
        b, y = solver._wave_spec_picks(masked, node_of, n, W)
        safe = jnp.minimum(y, n - 1)
        conflict = solver._wave_conflicts(
            b, y, n, req, req_nz, free_q, free_pods, used_nz, alloc_q,
            m[:, safe], static_scores[row[:, None], safe[None, :]],
            fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
            strategy)
        nreal = jnp.sum(real.astype(jnp.int32))

        def fast(st):
            fq, fp, unz, nc, nr, po = st
            hit = y < n
            fq = fq.at[safe].add(
                jnp.where(hit[:, None], -req, 0).astype(fq.dtype))
            fp = fp.at[safe].add(jnp.where(hit, -1, 0).astype(fp.dtype))
            unz = unz.at[safe].add(
                jnp.where(hit[:, None], req_nz, 0).astype(unz.dtype))
            return (fq, fp, unz, nc + nreal, nr, po), \
                jnp.where(hit, y, jnp.int32(-1))

        if poison:
            (fq, fp, unz, nc, nr, po), out = fast(
                (free_q, free_pods, used_nz, ncom, nrep,
                 pois | jnp.any(conflict).astype(jnp.int32)))
        else:
            def slow(st):
                fq, fp, unz, nc, nr, po = st

                def body(w, s):
                    fq, fp, unz, out = s
                    rq, rnz = req[w], req_nz[w]
                    fits_w = m[w] & jnp.all(rq[None, :] <= fq, axis=1) \
                        & (fp >= 1)
                    scw = static_scores[row[w]]
                    scw = scw + w_fit * kernels.fit_score(
                        alloc_q, unz, rnz[None, :], fit_col_w, strategy,
                        shape_u, shape_s)[0]
                    scw = scw + w_bal * kernels.balanced_allocation_score(
                        alloc_q, unz, rnz[None, :], bal_col_mask)[0]
                    mk = jnp.where(fits_w, scw, NEG_INF)
                    idx = jnp.argmax(mk).astype(jnp.int32)
                    idx = jnp.where(jnp.any(fits_w), idx, jnp.int32(-1))
                    hitw = idx >= 0
                    sf = jnp.clip(idx, 0, n - 1)
                    fq = fq.at[sf].add(
                        jnp.where(hitw, -rq, 0).astype(fq.dtype))
                    fp = fp.at[sf].add(
                        jnp.where(hitw, -1, 0).astype(fp.dtype))
                    unz = unz.at[sf].add(
                        jnp.where(hitw, rnz, 0).astype(unz.dtype))
                    return (fq, fp, unz, out.at[w].set(idx))

                fq2, fp2, unz2, out = lax.fori_loop(
                    0, W, body,
                    (fq, fp, unz, jnp.full((W,), -1, jnp.int32)))
                return (fq2, fp2, unz2, nc, nr + nreal, po), out

            (fq, fp, unz, nc, nr, po), out = lax.cond(
                jnp.any(conflict), slow, fast,
                (free_q, free_pods, used_nz, ncom, nrep, pois))

        cq_ref[0] = fq
        cp_ref[0] = fp
        cu_ref[0] = unz
        stat_ref[0] = jnp.stack([nc, nr, po, jnp.int32(0)])
        out_ref[0, 0] = out

    nb = bits.shape[1]
    c = bits.shape[0]
    su = jnp.asarray(shape_u)
    ss = jnp.asarray(shape_s)
    wf = jnp.asarray(w_fit, jnp.float32).reshape(1)
    wb = jnp.asarray(w_bal, jnp.float32).reshape(1)

    def _full(shape):
        return pl.BlockSpec(shape, lambda k, i: (0,) * len(shape))

    assign, stats, _, _, _ = pl.pallas_call(
        _wave_step_kernel,
        grid=(K, nw),
        in_specs=[
            pl.BlockSpec((1, 1, W, r), lambda k, i: (k, i, 0, 0)),
            pl.BlockSpec((1, 1, W, r), lambda k, i: (k, i, 0, 0)),
            pl.BlockSpec((1, 1, W), lambda k, i: (k, i, 0)),
            pl.BlockSpec((1, 1, W), lambda k, i: (k, i, 0)),
            pl.BlockSpec((1, W), lambda k, i: (i, 0)),
            _full((c, nb)),
            _full(static_scores.shape),
            _full(alloc_q.shape),
            _full(free_q.shape),
            _full(free_pods.shape),
            _full(used_nz_q.shape),
            _full(fit_col_w.shape),
            _full(bal_col_mask.shape),
            _full(su.shape),
            _full(ss.shape),
            _full((1,)),
            _full((1,)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, W), lambda k, i: (k, i, 0)),
            pl.BlockSpec((1, 4), lambda k, i: (k, 0)),
            pl.BlockSpec((1, n, r), lambda k, i: (k, 0, 0)),
            pl.BlockSpec((1, n), lambda k, i: (k, 0)),
            pl.BlockSpec((1, n, r), lambda k, i: (k, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, nw, W), jnp.int32),
            jax.ShapeDtypeStruct((K, 4), jnp.int32),
            jax.ShapeDtypeStruct((K, n, r), free_q.dtype),
            jax.ShapeDtypeStruct((K, n), free_pods.dtype),
            jax.ShapeDtypeStruct((K, n, r), used_nz_q.dtype),
        ],
        interpret=interpret,
    )(req_w, rnz_w, row_w, ex_w, real_w, bits, static_scores, alloc_q,
      free_q, free_pods, used_nz_q, fit_col_w, bal_col_mask, su, ss,
      wf, wb)

    return (assign.reshape(K, -1)[:, :p], stats[:, 0], stats[:, 1],
            stats[:, 2] > 0)


# ---------------------------------------------------------------------------
# shard-local wave evaluation: the (W, local_n) stage of the sharded
# wavefront solve as one fused kernel under shard_map. The W pmax/pmin
# ICI reduction rounds, the global-coordinate conflict OR-reduce, and
# the commit/replay cond stay in the shard_map body unchanged (SURVEY
# §5.8) — only the per-wave plane gather/gate/score/mask fuses.
# ---------------------------------------------------------------------------

def wave_eval(mask, static_sc, alloc_q, free_q, free_pods, used_nz,
              req, req_nz, row, e, el, real, fit_col_w, bal_col_mask,
              shape_u, shape_s, w_fit, w_bal, strategy: str,
              *, interpret: bool = True):
    """Fused shard-local (W, local_n) wave evaluation.

    Returns (masked (W, local_n) scores with NEG_INF = infeasible,
    m (W, local_n) gated static mask) — the exact pair the sharded
    `wave_step` computes inline; `el` is the exception column in LOCAL
    shard coordinates (e - base), `e` the global one (for the -1 gate).
    """
    pl = _import_pallas()
    local_n = free_q.shape[0]
    sc_dtype = jnp.result_type(static_sc.dtype, jnp.float32)

    def _wave_eval_kernel(mask_ref, sc_ref, alloc_ref, fq_ref, fp_ref,
                          unz_ref, req_ref, rnz_ref, row_ref, e_ref,
                          el_ref, real_ref, colw_ref, balm_ref, su_ref,
                          ss_ref, wf_ref, wb_ref, masked_ref, m_ref):
        iota = jnp.arange(local_n, dtype=jnp.int32)
        req = req_ref[...]
        req_nz = rnz_ref[...]
        row = row_ref[...]
        e = e_ref[...]
        el = el_ref[...]
        real = real_ref[...]
        free_q = fq_ref[...]
        free_pods = fp_ref[...]
        used_nz = unz_ref[...]
        alloc_q = alloc_ref[...]
        w_fit = wf_ref[0]
        w_bal = wb_ref[0]
        m = mask_ref[...][row] \
            & ((e < 0)[:, None] | (iota[None, :] == el[:, None])) \
            & real[:, None]
        fits = m & jnp.all(req[:, None, :] <= free_q[None, :, :],
                           axis=-1) & (free_pods >= 1)[None, :]
        sc = sc_ref[...][row]
        sc = sc + w_fit * kernels.fit_score(
            alloc_q, used_nz, req_nz, colw_ref[...], strategy,
            su_ref[...], ss_ref[...])
        sc = sc + w_bal * kernels.balanced_allocation_score(
            alloc_q, used_nz, req_nz, balm_ref[...])
        masked_ref[...] = jnp.where(fits, sc, NEG_INF).astype(sc_dtype)
        m_ref[...] = m

    W = req.shape[0]
    wf = jnp.asarray(w_fit, jnp.float32).reshape(1)
    wb = jnp.asarray(w_bal, jnp.float32).reshape(1)
    masked, m = pl.pallas_call(
        _wave_eval_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((W, local_n), sc_dtype),
            jax.ShapeDtypeStruct((W, local_n), jnp.bool_),
        ],
        interpret=interpret,
    )(mask, static_sc, alloc_q, free_q, free_pods, used_nz, req, req_nz,
      row, e, el, real, fit_col_w, bal_col_mask, jnp.asarray(shape_u),
      jnp.asarray(shape_s), wf, wb)
    return masked, m
