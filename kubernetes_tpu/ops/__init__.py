"""TPU compute path: tensorization, batched kernels, assignment solver.

The device-side replacement for the reference's goroutine fan-out
(pkg/scheduler/framework/parallelize) — see ops/backend.py for the map.
"""

from kubernetes_tpu.ops.backend import TPUBackend

__all__ = ["TPUBackend"]
