"""TPUBackend: the batched scheduling backend behind `Scheduler(backend=...)`.

North-star seam (BASELINE.json): the reference's per-pod
`findNodesThatFitPod` / `prioritizeNodes` 16-goroutine fan-out
(pkg/scheduler/framework/parallelize/parallelism.go, schedule_one.go) becomes
one XLA program over `(C classes × N nodes)` class-dictionary mask/score
planes (pods dedupe into C equivalence classes; a `(P,)` index maps pods to
rows) plus a batched assignment solve (ops/solver.py). The plugin contract
is preserved:

- Plugins with device kernels (ops/kernels.py) — NodeResourcesFit,
  NodeResourcesBalancedAllocation, TaintToleration — run fully on device.
- Static node-predicate plugins (NodeAffinity, NodeName, NodeUnschedulable,
  ImageLocality) run host-side ONCE per distinct pod spec signature per
  node-set epoch and are cached as dense rows (template-derived workloads
  have a handful of signatures). Semantics are *exactly* the host plugin's —
  the cached row is produced by calling its `filter()`/`score()`.
- The constraint families are DEVICE-RESIDENT end to end: InterPodAffinity
  compiles every term shape (namespaceSelector included — resolved to
  namespace sets at table-build time) into dense rows over interned label
  signatures, and PodTopologySpread rides the union scan table
  (heterogeneous templates, minDomains, restricted node eligibility,
  non-self-matching selectors).
- Device planes are CLASS-DICTIONARY native: pods dedupe into
  equivalence classes keyed by (request row, toleration row, host
  filter-row signatures, score-row signatures), and the wire ships one
  (C, N/8) bit-packed mask plane + one (C, N) float16 static-score
  plane + a (P,) int32 class index — never a per-pod (P, N) plane, on
  host OR device (the fused program computes fit/taint/score planes at
  class level and every solver scan gathers `class_idx[pod]` per step).
  Template batches have a handful of classes, so per-chunk plane work
  is O(C·N) ≈ chunk/C smaller than the per-pod format this replaces
  (and the r7 row-dictionary score wire is subsumed by it). Single-
  allowed-column host rows (NodeName, DRA allocated-claim pins) ride a
  sparse per-pod exception column instead of splitting a class. A chunk
  with more classes than KTPU_CLASS_PAD — or KTPU_CLASS_PLANES=0 —
  degrades structurally to per-pod planes (C == P, identity index),
  counted as class_split_fallbacks.
- The remaining per-pod host rows (NodePorts conflicts, volume plugins,
  DRA shapes the tensors can't answer) are Skip-gated per pod and COUNTED
  (kind="host_fallback"; bench detail `host_fallback_pods`) — residency
  regressions are data, not stderr noise.

Per-plugin unsat masks are kept (not fused away) so FailedScheduling events
retain per-plugin reasons (SURVEY §5.5 explainability requirement); they are
materialized host-side lazily, only for pods that end the cycle unassigned.

After the solve, assignments are **verified** host-side against a working
snapshot (exact integer arithmetic + full plugin re-check for pods with
stateful constraints); violators are returned unassigned and requeue — the
"solve, round, verify, re-queue" loop SURVEY §7 hard-part #1 prescribes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.labels import ns_contains
from kubernetes_tpu.utils import flags
from kubernetes_tpu.utils.locking import check_dispatch_seam
from kubernetes_tpu.ops import kernels, pallas_kernel, solver
from kubernetes_tpu.ops.tensorize import ClusterTensors, PodBatch
from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Framework,
    Status,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.scheduler.plugins.noderesources import (
    insufficient_resources,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

logger = logging.getLogger(__name__)

# jax.profiler host annotations (SURVEY §5.1): bracket the solve
# dispatch/fetch so device-solve chunks appear in the SAME jax-profiler
# timeline as the host-side work when a --profile-dir trace is taken.
# TraceMe-backed — near-free when no trace is active.
try:
    _TRACE_ANNOTATION = jax.profiler.TraceAnnotation
    _STEP_ANNOTATION = jax.profiler.StepTraceAnnotation
except AttributeError:  # pragma: no cover - stripped-down jax builds
    _TRACE_ANNOTATION = _STEP_ANNOTATION = None

#: Plugins with full device kernels.
DEVICE_FILTER_PLUGINS = {"NodeResourcesFit", "TaintToleration"}
DEVICE_SCORE_PLUGINS = {
    "NodeResourcesFit", "NodeResourcesBalancedAllocation", "TaintToleration"}

#: Pipeline-depth OVERRIDE (sweeps/debugging). Unset = the AdaptiveTuner
#: picks the depth from the measured transfer latency; see its policy
#: docstring and the BASELINE.md r6 depth sweep. Read LIVE per use —
#: the old import-time read forced callers (bench.py) to export the
#: env var before this module imported, an ordering footgun the flag
#: lint (analysis/flags_pass.py) now rejects.
def _pipeline_depth_override() -> int | None:
    return flags.get("KTPU_PIPELINE_DEPTH")


#: Solve chunk before the tuner has decided (also the latency-bound dirty
#: pick, so a wrong warmup guess is never catastrophic).
_DEFAULT_CHUNK = 1024


def _shortlist_k_override() -> int | None:
    """Shortlist OVERRIDE (sweeps/differential tests): an integer K forces
    the shortlist width regardless of the tuner's policy, 0 disables
    pruning entirely. Unset = flagless — the AdaptiveTuner derives K from
    the chunk width and the observed fallback rate (see its shortlist_k
    policy). Live read, like the pipeline depth."""
    return flags.get("KTPU_SHORTLIST_K")

#: Class-dictionary plane cap: the maximum number of REAL pod
#: equivalence classes per chunk (plane row 0 is reserved for the empty
#: class — padding pods, unknown-resource pods, conflicting pins — so
#: plane rows ≤ KTPU_CLASS_PAD + 1, bucketed to the next power of two
#: for a stable jit signature). Pods share a class when they share
#: (request row, toleration row, host filter-row set, score-row parts);
#: template batches have a handful, so the planes are (C, N) with
#: C ≪ chunk — a 1024-pod chunk at 50k nodes ships ~2 class rows
#: (~25 KB) where the per-pod format shipped a 6.4 MB packed mask and
#: materialized a 100+ MB score plane on device. A chunk with more
#: classes than this cap — or KTPU_CLASS_PLANES=0 — falls back to
#: per-pod planes (C == P, identity index): structurally the pre-class
#: dense format, bit-identical assignments, counted per pod as
#: class_split_fallbacks.
DEFAULT_CLASS_PAD = 31


def class_pad() -> int:
    """Effective class cap: 0 = class planes off (per-pod fallback).
    Read per assign() so tests/bench can flip the env knobs live."""
    if not flags.get("KTPU_CLASS_PLANES"):
        return 0
    return max(0, flags.get("KTPU_CLASS_PAD"))


def _class_rows_bucket(n_classes: int) -> int:
    """Plane row count for n_classes real classes + the reserved empty
    row 0, bucketed to a power of two (≥ 2) so jit signatures repeat."""
    rows = 2
    while rows < n_classes + 1:
        rows <<= 1
    return rows


class AdaptiveTuner:
    """Flagless solve-chunk + pipeline-depth selection (the r3→r5 adaptive
    ask): `--chunk` and KTPU_PIPELINE_DEPTH demote to overrides.

    Two signals, both measured — never configured:

    - **transfer latency**: median wall of three tiny put+fetch round
      trips at first assign. Separates a relay-attached accelerator
      (~25–100 ms per transfer regardless of size) from a locally
      attached device (sub-millisecond).
    - **dirty-upload ratio**: fraction of prepped chunks whose (C,N)
      class mask/score planes were host-written and re-uploaded — the
      signature of constraint families (affinity/spread host rows), which
      favor smaller chunks so the bit-packed uploads pipeline against
      solves.

    Policy (BASELINE.md r6 "adaptive vs manual" table is the recorded
    envelope; tests/test_tpu_backend.py + tests/test_shortlist_smoke.py
    pin it):

    | regime                      | chunk | depth |
    |-----------------------------|-------|-------|
    | latency-bound, clean masks  | 2048  | 4     |
    | latency-bound, dirty masks  | 1024  | 4     |
    | local, N ≥ 32768            | 1024  | 2     |
    | local device (any dirtiness)| 1024  | 2     |

    Latency-bound (≥ 5 ms/transfer): big chunks halve the number of
    size-independent fetch round trips (the r3 headline finding); dirty
    families keep 1024 so the bit-packed plane uploads pipeline (the r3
    packed-wire finding); depth 4 keeps solves in flight across the
    ~2-transfer pipeline bubble. Local: there is no round trip to
    amortize — 1024 measured best and stable on both clean and dirty
    families (r6 sweep) — and depth beyond 2 just delays verify feedback.
    The r6 table was tuned on the ≤5k presets; the large-N row pins the
    regime the 50k sweeps measured: the shortlist scan width is
    K+P = 2·chunk, so widening the chunk COSTS scan work faster than it
    amortizes the per-chunk fixed costs — and the r14 class-dictionary
    planes cut those fixed costs from O(P·N) to O(C·N) (prefilter,
    score materialization, and mask unpack all run over C class rows),
    which the r14 re-sweep confirmed does NOT move the optimum: 1024
    still beat 2048 and 512 at N=50k (BASELINE r10 pre-class, r14
    post-class). Node count is
    STRUCTURAL (known at the first assign), so unlike the measured
    signals this row applies without waiting out the warmup window — the
    50k preset's chunk and shortlist compile in warmup, never in a
    measured phase.

    The decision lands once, at the first assign() boundary after
    WARMUP_CHUNKS chunks have been observed (one recompile at the new
    chunk width, outside any measured phase that follows the reference
    harness's warmup convention); it re-opens only if the dirty-ratio
    regime flips.

    **Shortlist width** (the r10 pruned solve): K = chunk × boost, active
    only while the node count dwarfs the scan width (N ≥ 4·(K + chunk) —
    below that the narrow scan plus prefilter costs more than it saves;
    the 5k preset measured ~10% behind its full scan at factor 2).
    K defaults to the chunk width because the sequential-equivalent scan
    can visit one fresh node per pod: a round-robin workload (uniform
    nodes — the 50k preset) needs the whole chunk's winners inside one
    shortlist or every pod past the K-th pays the N-wide fallback. The
    boost doubles (to ×8 max) at assign() boundaries whenever the
    observed fallback rate crosses 25% — fallbacks are exact but O(N), so
    a persistently-missing shortlist must widen or it silently degrades
    to the unpruned solve plus overhead.
    """

    LATENCY_BOUND_S = 5e-3
    DIRTY_RATIO = 0.25
    WARMUP_CHUNKS = 8
    #: node count from which the large-N chunk row applies.
    LARGE_N = 32768
    #: shortlist activates when n_real ≥ FACTOR × (K + chunk). Measured
    #: on the CPU container (r10): at N=5k / chunk 1024 the pruned width
    #: (2048) plus the per-chunk prefilter/top-k ran ~10% BEHIND the
    #: r9-tuned full scan, while at N=50k it is a 3–6× win — the factor
    #: is set so the 5k headline keeps its full scan and activation
    #: starts where the width ratio pays (≥4×).
    SHORTLIST_FACTOR = 4
    SHORTLIST_MAX_BOOST = 8
    SHORTLIST_FALLBACK_RATIO = 0.25
    #: minimum solved pods before the fallback rate is trusted.
    SHORTLIST_MIN_SAMPLE = 512
    #: Block-index width (the two-pass block-sparse prefilter — see
    #: block_width()): node columns per aggregate block. 128 keeps the
    #: bound scan O(C·N/128) while M = 2·ceil((K+1)/128) selected
    #: blocks re-gather ~2K+ columns — comfortably inside the regime
    #: where the full (C,N) chunk-start pass is the measured wall
    #: (N ≥ LARGE_N with shortlist active).
    BLOCK_WIDTH = 128
    #: Wavefront policy rows (the r18 speculative solve): W pods per
    #: scan step, swept at the 5k/50k/200k presets (BASELINE r18). The
    #: win GROWS with node count — the scan-length cut frees the XLA
    #: compute threads that contend with the host path, in proportion
    #: to how big each step's arrays are: 200k median 1508 at W=64 vs
    #: ~1036 serial (+46%), 50k 1517–1677 across W∈{16,32,64} vs 1411,
    #: while 5k (full-scan multistart, host-bound) is flat within the
    #: run spread — W=32 keeps it active without cost, mirroring the
    #: shortlist's 5k finding. Replay fraction was 0% throughout (all
    #: template workloads). Node count is STRUCTURAL, so like the
    #: large-N chunk row the tier applies from the first assign.
    #: Conflict rate is WORKLOAD-dependent (packing strategies re-pick
    #: debited nodes; contested spread domains force replays), so the
    #: width halves at decide() boundaries whenever the measured replay
    #: fraction crosses the ratio — replays are exact but serial, so a
    #: persistently-conflicting wave must narrow or the speculation
    #: overhead is pure waste (the shortlist boost rule, mirrored).
    WAVE_WIDTH_SMALL = 32
    WAVE_WIDTH_LARGE = 64
    WAVE_REPLAY_RATIO = 0.25
    WAVE_MIN_SAMPLE = 512
    #: Admission-window policy row (the serving tier, ROADMAP #3 — see
    #: serving/admission.py for the state machine that consults it).
    #: Thresholds are seeded from the r15 churn knee sweep (BASELINE
    #: r15, 5k nodes): the knee sat at 1000/s and the 250/s trickle row
    #: was the p999 pathology — at or below the idle threshold (set
    #: just ABOVE the trickle row, so rate-estimate jitter around
    #: exactly 250/s can't flap it into coalescing) every pod
    #: dispatches IMMEDIATELY (the fast path is sub-ms; holding a lone
    #: pod buys nothing), above it the window is sized to coalesce
    #: ~ADMISSION_TARGET_PODS at the estimated offered rate, capped so
    #: no pod ever waits past the cap (the cap IS the p50 budget). A
    #: latency-bound (relay-attached) device quadruples the cap: each
    #: dispatch costs a size-independent RTT, so fewer, fuller batches
    #: win exactly as they do for the chunk table above.
    ADMISSION_IDLE_RATE = 300.0
    ADMISSION_TARGET_PODS = 8.0
    ADMISSION_MAX_WINDOW_S = 4e-3
    #: Fast-path dispatch cap: the largest popped dispatch worth
    #: draining pod-by-pod through the pinned C=1 solve instead of one
    #: padded chunk. The crossover is the measured ratio — a chunk's
    #: wall is fixed (scan over the padded width; ~0.35 s at 5k on the
    #: CPU container, BASELINE r15/r16) while the fast path pays
    #: ~1–2 ms per pod, so anything under chunk/fast pods is faster
    #: serially AND keeps the queue in the lone-pod regime instead of
    #: locking into batch-every-chunk-wall (the r15 trickle pathology:
    #: arrivals accumulating during one chunk guarantee the next pop is
    #: another chunk). Seeds cover the pre-measurement window; the
    #: serving tier feeds both EWMAs from its own dispatches.
    FAST_PATH_SEED_CHUNK_S = 0.25
    #: pre-measurement fast-wall seed: deliberately OPTIMISTIC (1 ms —
    #: the measured 5k wall is ~0.6 ms) so the seeded rate limit
    #: (0.5/1 ms = 500/s) clears the 250/s trickle with margin; a
    #: too-conservative seed suppressed the fast path before any
    #: sample could land and the suppression was self-sustaining.
    FAST_PATH_SEED_SOLVE_S = 1e-3
    #: node count the 1 ms solve seed was measured at; an unmeasured
    #: fast wall seeds at SEED_SOLVE_S x (n / CALIB_N) because solve_one
    #: is a full-N scan (see _fast_wall_seed).
    FAST_PATH_SEED_CALIB_N = 5000
    FAST_PATH_CAP_MIN = 8
    FAST_PATH_CAP_MAX = 512
    #: Batch-optimal (Sinkhorn) routing policy row (r20): `auto`
    #: engages only where the latency budget allows — drain/rollout-
    #: scale chunks and gang placement. The plan is a fixed per-chunk
    #: device cost (KTPU_SINKHORN_ITERS dense (C,N) passes), so a chunk
    #: below this many real pods keeps the greedy scan — the iteration
    #: cost would dominate what the rounding saves. Serving single-pod
    #: traffic never reaches this policy at all (solve_one is a separate
    #: pinned program), and gang chunks route optimal at ANY width: all-
    #: or-nothing placement is exactly where greedy's myopia strands
    #: feasible gangs.
    OPTIMAL_MIN_PODS = 64
    #: Serial fast-drain is only right while the OFFERED rate is within
    #: its capacity (1/fast_wall) with headroom: above this utilization
    #: the pipelined batch path must take over or the serial drain
    #: itself becomes the bottleneck — a sustained drain through a
    #: shared-loop wire self-throttles its own creates to the drain
    #: rate, so backlog alone never reveals the pressure.
    FAST_PATH_UTILIZATION = 0.5

    def __init__(self):
        self.latency_s: float | None = None
        self.dirty_chunks = 0
        self.total_chunks = 0
        self.decided: tuple[int, int] | None = None
        #: node count of the latest assign() — structural signal for the
        #: large-N row and the shortlist policy (set by the backend).
        self.n_nodes = 0
        self.shortlist_boost = 1
        self.solve_pods = 0
        self.solve_fallbacks = 0
        #: wavefront feedback state: the policy W divides by wave_shrink
        #: (replay-fraction feedback can only NARROW the wave; the
        #: override pins it).
        self.wave_shrink = 1
        self.wave_commits = 0
        self.wave_replays = 0

    def probe(self) -> float:
        """Median tiny put+fetch round trip (no jit, pure transfer)."""
        if self.latency_s is None:
            import time
            samples = []
            probe = np.zeros((64,), dtype=np.int32)
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(jax.device_put(probe))
                samples.append(time.perf_counter() - t0)
            self.latency_s = sorted(samples)[1]
        return self.latency_s

    def observe_chunk(self, dirty: bool) -> None:
        self.total_chunks += 1
        if dirty:
            self.dirty_chunks += 1

    @classmethod
    def pick(cls, latency_s: float, dirty_ratio: float,
             n_nodes: int = 0) -> tuple[int, int]:
        """(chunk, pipeline depth) for a measured regime — pure policy."""
        remote = latency_s >= cls.LATENCY_BOUND_S
        dirty = dirty_ratio >= cls.DIRTY_RATIO
        if not remote and n_nodes >= cls.LARGE_N:
            # Measured at N=50k on the CPU container (BASELINE r10): the
            # shortlist scan width is K+P = 2·chunk, so chunk growth
            # COSTS scan work faster than it amortizes the per-chunk
            # O(N) prefilter — 2048 → 250 pods/s, 1024 → 419, 512 → 389.
            # The row pins the measured optimum and, being structural,
            # lands before warmup (no mid-measured-phase recompile).
            return 1024, 2
        chunk = (1024 if dirty else 2048) if remote else 1024
        return chunk, 4 if remote else 2

    def observe_solve(self, pods: int, fallbacks: int) -> None:
        """Shortlist hit-rate sample from one finalized chunk."""
        self.solve_pods += pods
        self.solve_fallbacks += fallbacks

    def observe_wave(self, commits: int, replays: int) -> None:
        """Wavefront commit/replay sample from one finalized chunk."""
        self.wave_commits += commits
        self.wave_replays += replays

    def solve_mode(self, p_real: int, has_gang: bool, spread: bool,
                   class_mode: bool) -> tuple[str, bool]:
        """('greedy' | 'optimal', structural_fallback) for one chunk —
        the KTPU_SOLVE_MODE policy row. 'greedy' pins the r18 scan call
        graph (the kill switch). Optimal requires class planes (the
        (C,N) cost matrix IS the class dictionary) and a non-spread
        chunk (the spread scan's non-monotone domain gating has no
        transport relaxation); an ineligible chunk degrades structurally
        to greedy with the fallback bit set so
        solver_optimal_fallbacks_total records it. Under 'auto' the
        optimal mode engages for gang chunks and for chunks of at least
        OPTIMAL_MIN_PODS real pods (drain/rollout waves) — EXCEPT at
        the structural large-N row (n_nodes >= LARGE_N, the same signal
        as the chunk/W/block-width rows), where non-gang chunks keep
        the greedy scan: the Sinkhorn plan is a fixed
        KTPU_SINKHORN_ITERS dense (C,N) passes per chunk, so above
        LARGE_N the plan itself is the linear-in-N solve wall the block
        index removes (measured @ 200k: ~20 s/chunk optimal vs < 1 s
        greedy with the block-sparse prefilter) — the latency-budget
        rationale that routes drains optimal inverts. Gang chunks
        still route optimal at ANY node count (all-or-nothing
        placement is where greedy's myopia strands feasible gangs),
        and KTPU_SOLVE_MODE=optimal still pins every eligible chunk
        (the policy row only shapes 'auto')."""
        raw = flags.get("KTPU_SOLVE_MODE")
        if raw == "greedy":
            return "greedy", False
        eligible = class_mode and not spread
        if raw == "optimal":
            return ("optimal", False) if eligible else ("greedy", True)
        if not (has_gang or p_real >= self.OPTIMAL_MIN_PODS):
            return "greedy", False
        if not has_gang and self.n_nodes >= self.LARGE_N:
            return "greedy", False
        return ("optimal", False) if eligible else ("greedy", True)

    def pallas_mode(self, wave_w: int, shortlist_k: int, spread: bool,
                    solve_mode: str) -> tuple[str, str | None]:
        """('off' | 'interpret' | 'compiled', fallback_reason) for one
        chunk — the KTPU_PALLAS policy row. 'off' with reason None is
        off BY POLICY (the kill switch, or `auto` on CPU where the scan
        measured faster than the interpreter) and does not count as a
        fallback; 'off' with a reason is a chunk the flag WANTED on the
        kernel but whose shape the kernel does not fuse (spread /
        shortlist / optimal keep their scans; wave_off is the W<=1
        serial shape) or whose backend cannot lower it — those are the
        `solver_pallas_fallbacks_total` rows. `auto` compiles on
        accelerator backends only; `on` forces the kernel (compiled
        when lowering is available, else interpret); `interpret` pins
        the CPU tier-1 validation mode everywhere."""
        raw = flags.get("KTPU_PALLAS")
        if raw == "off":
            return "off", None
        compiled_ok = pallas_kernel.lowering_supported(
            jax.default_backend())
        if raw == "auto" and not compiled_ok:
            return "off", None
        if not pallas_kernel.is_available():
            return "off", "unavailable"
        if solve_mode != "greedy":
            return "off", "optimal"
        if spread:
            return "off", "spread"
        if shortlist_k:
            return "off", "shortlist"
        if wave_w <= 1:
            return "off", "wave_off"
        if raw == "interpret":
            return "interpret", None
        return ("compiled" if compiled_ok else "interpret"), None

    def wave_width(self, chunk: int) -> int:
        """Wavefront width for a chunk; 1 = degenerate one-member waves.
        The KTPU_WAVEFRONT kill switch is routed by the backend (it
        selects the W=1 scan FUNCTIONS, not a one-member wave), so this
        is pure width policy: the override, else the swept node-count
        tier narrowed by the replay-fraction feedback."""
        override = flags.get("KTPU_WAVE_WIDTH")
        if override is not None:
            return max(1, min(override, chunk))
        w = self.WAVE_WIDTH_LARGE if self.n_nodes >= self.LARGE_N \
            else self.WAVE_WIDTH_SMALL
        return max(1, min(w // self.wave_shrink, chunk))

    @classmethod
    def _fast_wall_seed(cls, n_nodes: int) -> float:
        """Unmeasured-wall seed for the fast-path gates. The 1 ms base
        is the measured 5k-node solve_one wall; the wall is a full-N
        scan, so the seed scales linearly from that calibration point
        (200k → 40 ms). Without the scaling, a cold estimate at large N
        reads the serial drain ~100× too fast, opens the cap to its
        512 clamp, and one big dispatch serial-drains at ~125 ms/pod
        while the self-throttled wire hides the pressure from the
        mid-drain abort (measured: 243 pods, +30 s of 200k drain
        window)."""
        return cls.FAST_PATH_SEED_SOLVE_S \
            * max(1, n_nodes / cls.FAST_PATH_SEED_CALIB_N)

    @classmethod
    def fast_path_cap(cls, chunk_wall_s: float, fast_wall_s: float,
                      n_nodes: int = 0) -> int:
        """Largest dispatch the serving tier drains pod-by-pod through
        the fast path — pure policy over the two measured walls (the
        node count only shapes the seed while the fast wall is still
        unmeasured)."""
        if fast_wall_s <= 0:
            fast_wall_s = cls._fast_wall_seed(n_nodes)
        if chunk_wall_s <= 0:
            chunk_wall_s = cls.FAST_PATH_SEED_CHUNK_S
        return int(min(max(chunk_wall_s / fast_wall_s,
                           cls.FAST_PATH_CAP_MIN), cls.FAST_PATH_CAP_MAX))

    @classmethod
    def fast_path_rate_limit(cls, fast_wall_s: float,
                             n_nodes: int = 0) -> float:
        """Highest estimated offered rate (pods/s) the serving tier
        still serial-drains at — pure policy over the measured wall."""
        if fast_wall_s <= 0:
            fast_wall_s = cls._fast_wall_seed(n_nodes)
        return cls.FAST_PATH_UTILIZATION / fast_wall_s

    @classmethod
    def admission_window(cls, latency_s: float, rate_est: float) -> float:
        """Coalesce window (seconds) for the serving admission tier —
        pure policy, like pick(). 0.0 = dispatch immediately."""
        if rate_est <= cls.ADMISSION_IDLE_RATE:
            return 0.0
        cap = cls.ADMISSION_MAX_WINDOW_S
        if latency_s >= cls.LATENCY_BOUND_S:
            cap = 4.0 * cls.ADMISSION_MAX_WINDOW_S
        return min(cls.ADMISSION_TARGET_PODS / rate_est, cap)

    def shortlist_k(self, chunk: int, n_real: int) -> int:
        """Shortlist width for a chunk, 0 = keep the full N-wide scan."""
        override = _shortlist_k_override()
        if override is not None:
            k = override
            return k if 0 < k < n_real else 0
        k = chunk * self.shortlist_boost
        if n_real < self.SHORTLIST_FACTOR * (k + chunk):
            return 0
        return k

    def block_width(self, n_pad: int, n_real: int, shortlist_k: int) -> int:
        """Block width for the two-pass block-sparse prefilter, 0 = the
        full-width r18/r21 prefilter (the structural kill-switch shape).

        Policy: the block index only composes with an active shortlist
        (it prunes the shortlist prefilter's own O(C·N) pass — without a
        threshold there is nothing to bound against), and only where the
        node count is the wall it was built for — n_real ≥ LARGE_N, the
        same STRUCTURAL signal as the large-N chunk and wavefront rows,
        so it lands on the first assign with no mid-measured-phase
        recompile. Below that the bound scan plus gather costs more than
        the pruned chunk-start pass saves (the shortlist's own 5k
        lesson, one level up). The M+1 ≤ B shape guard routes 0 for any
        width/N combination where the selection could not even leave one
        block unselected (top_k needs M+1 distinct blocks; a fully-
        selected index prunes nothing). KTPU_BLOCK_WIDTH overrides the
        width (0 disabling, like the KTPU_BLOCK_INDEX kill switch).
        """
        if not flags.get("KTPU_BLOCK_INDEX"):
            return 0
        override = flags.get("KTPU_BLOCK_WIDTH")
        bw = self.BLOCK_WIDTH if override is None else override
        if bw <= 0 or shortlist_k <= 0 or n_real < self.LARGE_N:
            return 0
        b = -(-n_pad // bw)
        m = 2 * (-(-(shortlist_k + 1) // bw))
        if m + 1 > b:
            return 0
        return bw

    def decide(self) -> tuple[int, int] | None:
        """The (chunk, depth) to apply, or None while still warming up.
        Re-decides when the observed dirty regime flips."""
        if self.solve_pods >= self.SHORTLIST_MIN_SAMPLE:
            if self.solve_fallbacks > self.SHORTLIST_FALLBACK_RATIO \
                    * self.solve_pods \
                    and self.shortlist_boost < self.SHORTLIST_MAX_BOOST:
                self.shortlist_boost *= 2
                logger.info(
                    "adaptive tuner: shortlist fallback rate %.0f%% "
                    "-> boost x%d", 100.0 * self.solve_fallbacks
                    / self.solve_pods, self.shortlist_boost)
            self.solve_pods = self.solve_fallbacks = 0
        wave_total = self.wave_commits + self.wave_replays
        if wave_total >= self.WAVE_MIN_SAMPLE:
            if self.wave_replays > self.WAVE_REPLAY_RATIO * wave_total \
                    and self.wave_shrink < self.WAVE_WIDTH_LARGE:
                self.wave_shrink *= 2
                logger.info(
                    "adaptive tuner: wavefront replay fraction %.0f%% "
                    "-> shrink x%d", 100.0 * self.wave_replays
                    / wave_total, self.wave_shrink)
            self.wave_commits = self.wave_replays = 0
        if self.total_chunks < self.WARMUP_CHUNKS:
            # The large-N row rides a STRUCTURAL signal (node count),
            # so it applies from the very first assign — the one
            # recompile lands in warmup, not a measured phase. LOCAL
            # only: the remote rows depend on the measured dirty ratio,
            # and committing one pre-warmup would lock in a guess.
            if self.n_nodes >= self.LARGE_N \
                    and self.probe() < self.LATENCY_BOUND_S:
                ratio = self.dirty_chunks / self.total_chunks \
                    if self.total_chunks else 0.0
                self.decided = self.pick(self.probe(), ratio, self.n_nodes)
            return self.decided
        ratio = self.dirty_chunks / self.total_chunks
        pick = self.pick(self.probe(), ratio, self.n_nodes)
        if self.decided is None or pick != self.decided:
            self.decided = pick
        return self.decided

#: Gang (PodGroup) slots per chunk for the solver's all-or-nothing masking;
#: fixed so the jit signature is stable. Overflow gangs keep the Permit
#: barrier as their only atomicity (the reference behavior).
_GANG_PAD = 16

#: Static node-predicate plugins whose (pod-spec → node row) is cacheable by
#: spec signature while the node set is unchanged.
STATIC_ROW_PLUGINS = {"NodeAffinity", "NodeName", "NodeUnschedulable"}
STATIC_SCORE_PLUGINS = {"NodeAffinity", "ImageLocality"}

#: O(1)-per-pod activity gates mirroring each stateful plugin's own
#: PreFilter/PreScore Skip condition. Without these, merely *asking* a plugin
#: to skip costs O(N) per pod (e.g. InterPodAffinity.pre_score scans all
#: nodes for pods-with-affinity before skipping) — the 5k-node profile's top
#: hotspot. Invariant: a gate may only say "inactive" when the plugin would
#: Skip — PodTopologySpread's gate therefore asks the plugin for its
#: effective constraints (system/profile DEFAULT constraints apply to
#: labeled pods even with no explicit spec constraints).
_FILTER_ACTIVE = {
    "InterPodAffinity": lambda plugin, pi, snap: bool(
        pi.required_affinity_terms or pi.required_anti_affinity_terms
        or snap.have_pods_with_required_anti_affinity),
    "PodTopologySpread": lambda plugin, pi, snap: bool(
        plugin._constraints_for(pi, "DoNotSchedule")),
    "NodePorts": lambda plugin, pi, snap: bool(pi.host_ports),
    "VolumeBinding": lambda plugin, pi, snap: bool(pi.pvc_names),
    "VolumeRestrictions": lambda plugin, pi, snap: bool(pi.pvc_names),
    "VolumeZone": lambda plugin, pi, snap: bool(pi.pvc_names),
    "NodeVolumeLimits": lambda plugin, pi, snap: bool(pi.pvc_names),
    "NodeResourceTopologyMatch":
        lambda plugin, pi, snap: plugin.active_for(pi),
    "DynamicResources":
        lambda plugin, pi, snap: plugin.active_for(pi),
    "TopologySlice":
        lambda plugin, pi, snap: plugin.active_for(pi),
}
_SCORE_ACTIVE = {
    "InterPodAffinity": lambda plugin, pi, snap: bool(
        pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms
        or snap.have_pods_with_affinity),
    "PodTopologySpread": lambda plugin, pi, snap: bool(
        plugin._constraints_for(pi, "ScheduleAnyway")),
    "NodeResourceTopologyMatch":
        lambda plugin, pi, snap: plugin.active_for(pi),
}


def compress_score_wire(host_scores: "np.ndarray") -> "np.ndarray":
    """Pick the wire dtype for a dirty host-score plane.

    f16 (2× relay bytes saved) only while it's faithful: weighted sums
    past 1024 sit in f16's ≥0.5-resolution band (near-ties can flip vs
    the host path) and past 65504 overflow to inf. Oversized planes
    (plugin weights ~>10) ship f32 — 2× bytes on a rare path beats
    silently diverging from host-score parity. Scaling instead would skew
    this plane against the device-computed taint/fit/balanced terms it is
    summed with (the fused program casts to f32 on device either way).
    """
    import math
    if host_scores.size:
        # Two reductions, no temporaries (this sits on the dirty-upload
        # dispatch path): NaN/inf propagate through min/max, so the
        # finiteness check falls out of the same pass.
        lo, hi = float(host_scores.min()), float(host_scores.max())
        if not (math.isfinite(lo) and math.isfinite(hi)):
            raise ValueError("host score plane contains non-finite values")
        amax = max(-lo, hi)
    else:
        amax = 0.0
    return host_scores.astype(np.float16 if amax <= 1024.0 else np.float32)


@jax.jit
def _copy_pack(pack):
    """Chain-owned copy of a used-state pack: the donated fused solve
    consumes its carry input, so a buffer someone else keeps (the
    resident planes' base) must be copied before seeding the chain.
    Only called when donation is live (see _solve_program)."""
    return pack + 0


#: Lazily-resolved fused program: the chained used-state carry is
#: DONATED on accelerator backends only. The chain is the buffer's sole
#: consumer, so donation lets XLA update the (N, 2R+1) carry in place
#: instead of allocating per chunk. On CPU-jax it is measurably
#: CATASTROPHIC: input/output aliasing forces each dispatch to wait for
#: the previous program to release the buffer, serializing the chunk
#: pipeline the backend exists to overlap — the r18 same-container 50k
#: before/after measured 1644/1635 (no donation) vs 894–978 (donated)
#: pods/s, and 200k 1410 vs ~740 (BASELINE r18). Resolved on FIRST
#: dispatch, not import: jax.default_backend() initializes the jax
#: runtime, and the platform must stay configurable until then (the
#: conftest "set platform before jax initializes" contract).
_SOLVE_PROGRAM = None


def _solve_program():
    global _SOLVE_PROGRAM
    if _SOLVE_PROGRAM is None:
        if jax.default_backend() == "cpu":
            _SOLVE_PROGRAM = _mask_solve_update
        else:
            _SOLVE_PROGRAM = partial(
                jax.jit,
                static_argnames=("strategy", "use_spread", "shortlist_k",
                                 "wave_w", "solve_mode", "pallas",
                                 "block_w"),
                donate_argnums=(1,))(_mask_solve_update.__wrapped__)
    return _SOLVE_PROGRAM


def _donation_live() -> bool:
    """True when the fused program donates its carry (accelerator
    backends) — the resident seed must be copied exactly then."""
    return _solve_program() is not _mask_solve_update


def solve_provenance() -> dict:
    """Solve-backend provenance for bench/perf output: which jax
    platform, device count and host core count produced a number, and
    whether the wave solve routes pallas/scan and donates its carry —
    so CPU-jax rows, single-core container rows and relay rows can
    never be conflated in BASELINE again (the BENCH_r05 attribution
    gap, and r22's single-core premise note, as data in every JSON). Resolves the same policy the router
    applies to an eligible greedy wave chunk; per-chunk structural
    fallbacks can still keep individual chunks on the scan (counted in
    solver_pallas_fallbacks_total)."""
    platform = jax.default_backend()
    raw = flags.get("KTPU_PALLAS")
    if raw == "off":
        resolved = "off"
    elif raw == "interpret":
        resolved = "interpret"
    elif pallas_kernel.lowering_supported(platform):
        resolved = "compiled"
    else:
        resolved = "interpret" if raw == "on" else "off"
    return {
        "jax_platform": platform,
        "jax_device_count": jax.device_count(),
        "cpu_count": os.cpu_count(),
        "solve_kernel": "scan" if resolved == "off" else "pallas",
        "pallas_mode": resolved,
        "pallas_flag": raw,
        "carry_donation": _donation_live(),
    }


def _signature(plugin_name: str, pi: PodInfo) -> str:
    if plugin_name == "NodeName":
        return pi.node_name
    if plugin_name == "NodeUnschedulable":
        return repr(sorted(
            (t.get("key", ""), t.get("operator", ""))
            for t in pi.tolerations))
    if plugin_name == "NodeAffinity":
        return repr((pi.node_selector, pi.affinity.get("nodeAffinity")))
    if plugin_name == "ImageLocality":
        return repr(sorted(
            c.get("image", "") for c in pi.pod.get("spec", {}).get("containers") or []))
    raise KeyError(plugin_name)


@partial(jax.jit,
         static_argnames=("strategy", "use_spread", "shortlist_k",
                          "wave_w", "solve_mode", "pallas", "block_w"))
def _mask_solve_update(alloc_q, used_pack, alloc_pods, class_pack,
                       cls_idx, exc_col,
                       taint_f_mat, taint_p_mat, class_mask, class_scores,
                       fit_col_w, bal_col_mask, shape_u, shape_s,
                       w_fit, w_bal, w_taint, taint_filter_on,
                       dom_onehot, cid_onehot, dom_counts, max_skew,
                       sp_min_ok, sp_haskey,
                       sp_applies, sp_contrib, perms, gang_onehot,
                       gang_required, sink_iters, sink_temp, n_real,
                       strategy: str, use_spread: bool, shortlist_k: int,
                       wave_w: int, solve_mode: str = "greedy",
                       pallas: str = "off", block_w: int = 0):
    """One fused device pass: plugin masks → scores → assignment → state.

    The used-state (used_q ‖ used_nz_q ‖ used_pods, packed into ONE (N,2R+1)
    int32 array — each host→device transfer costs ~25–100 ms of relay
    latency regardless of size, so inputs are packed to one upload apiece)
    is device-resident and CHAINED: the program returns the post-assignment
    state so the next chunk's solve can be dispatched without any host
    round-trip — SURVEY §2.8's pipelining row (solve batch k+1 overlaps
    verify/bind of batch k). Capacity accounting inside the solver is exact
    (quantized-conservative integers), so the chain is as correct as
    re-uploading from the host.

    CLASS-DICTIONARY planes (the native format — see _prep_chunk):

    - class_mask: (C, N/8) uint8 bit-packed host filter rows per pod
      equivalence class (row 0 = the reserved EMPTY class).
    - class_scores: (C, N) f16/f32 host score rows per class.
    - class_pack: (C, 2R+tf+tp) int32 — req_q ‖ req_nz_q ‖ untol_f ‖
      untol_p of each class's representative pod (identical across the
      class by the class key).
    - cls_idx: (P,) int32 pod → class row; exc_col: (P,) int32 — the
      sparse exception list: -1 = none, else the ONE column the pod is
      additionally restricted to (single-allowed-column host rows ride
      here instead of splitting a class).

    Every O(N) plane — mask unpack, fit/taint filter, taint score,
    chunk-start prefilter — is computed over C class rows, never P pod
    rows; the scans gather `cls_idx[pod]` per step (ops/solver.py
    `rows=`), so no (P, N) array exists anywhere in the program. The
    per-pod degenerate form (C == P, cls_idx == arange, the
    KTPU_CLASS_PLANES=0 kill switch / class-overflow fallback) runs the
    SAME program and is bit-identical by construction.

    shortlist_k > 0 switches the solve to the SHORTLIST-PRUNED scans
    (ops/solver.py): the prefilter computes chunk-start live scores per
    CLASS directly off the class planes, takes the per-class top-K
    columns plus the (K+1)-th value as exactness threshold, and the scan
    re-scores K + P candidate columns per step instead of N — falling
    back to the full row exactly when the bound check cannot prove the
    narrow winner global. Assignments are bit-identical to the full scan
    by construction (tests/test_shortlist_solver.py is the differential
    guard).

    solve_mode == "optimal" is the r20 BATCH-OPTIMAL mode: an entropic
    transport plan (ops/solver.sinkhorn_plan) over the same (C,N) class
    planes replaces the greedy scorer for this chunk. The plan's cost
    matrix is the greedy scorer's own chunk-start scores (the warm
    start — it refines exactly the preferences the r18 scan would have
    ranked), its marginals are pods-per-class and remaining pod slots,
    and its log becomes the scan's `static_scores` with the live
    re-scoring weights zeroed — so the ROUNDING pass is the unmodified
    r18 scan machinery against live capacity planes and every emitted
    assignment is feasible by construction (gang all-or-nothing masking
    and multistart orders apply unchanged). "greedy" (the
    KTPU_SOLVE_MODE kill switch and the structural-fallback route for
    spread/per-pod chunks) traces the r18 call graph verbatim —
    `sink_iters`/`sink_temp` are dead inputs there and XLA drops them.

    wave_w > 1 switches to the SPECULATIVE WAVEFRONT scans: W pods per
    scan step against the same carry, prefix-distinct argmax commits,
    and exact serial replay of conflicted waves — assignments stay
    bit-identical at every W (tests/test_wavefront_solver.py), the scan
    length drops P → P/W on low-conflict workloads, and W is part of the
    chunk program key (one compile per (shapes, strategy, spread, K, W)).
    The spread∩shortlist combination keeps its W=1 scan — wavefront and
    shortlist compose, spread composes with wavefront, all three
    together would multiply the replay conditions for a chunk shape the
    presets never hit. wave_w == 0 is the KTPU_WAVEFRONT kill-switch
    shape: the pre-wavefront call graph, structurally.

    `pallas` ("off" | "interpret" | "compiled", static — part of the
    fused-program key like the other routing statics) swaps the
    wavefront scan for the FUSED PALLAS KERNEL (ops/pallas_kernel.py):
    one grid step per wave with the carry resident, same op sequence,
    bit-identical assignments. It only affects the plain wave branch
    (greedy, non-spread, no shortlist) — every other shape keeps its
    scan, and the router (_dispatch_chunk_jit) records those as counted
    structural fallbacks rather than passing "on" here. "off" traces
    the r20 scan call graph verbatim — the KTPU_PALLAS kill switch.

    `used_pack` is DONATED on accelerator backends (the _solve_program
    variant): the chunk chain is its only consumer — each dispatch
    consumes the previous chunk's output (or the one-off seed _start
    uploads/copies), so XLA may update the carry in place instead of
    allocating a fresh (N, 2R+1) buffer per chunk. On CPU the aliasing
    serializes the pipeline and donation stays off (measured ~1.7–1.9×
    worse; see the _solve_program note and BASELINE r18). When donation
    is live, the resident planes' base pack is never passed here
    directly (the serving seed is copied first; see _start).

    `block_w > 0` (static, part of the program key) swaps the shortlist
    prefilter for the TWO-PASS BLOCK-SPARSE form (ops/solver.py
    `block_bound_prefilter`): per-block aggregate bounds gate which node
    columns the chunk-start score pass touches, exactly — an in-program
    lax.cond falls back to the full-width pass whenever the bound
    predicate cannot prove the gathered top-K global. `n_real` (traced)
    excludes bucket-padding columns from the aggregates. block_w == 0 is
    the KTPU_BLOCK_INDEX kill-switch shape: the full-width r18/r21
    prefilter call graph, structurally.

    Returns (assign (P+5,) — the tail is [shortlist fallbacks, wave
    commits, wave replays, blocks scanned, blocks pruned] riding the one
    fetch — used_pack', fit0 (C,N), taint_ok (C,N), dom_counts'). The
    diagnostic planes are CLASS-level; consumers gather through cls_idx
    host-side.
    """
    # Wire decompression (see _prep_chunk): masks arrive bit-packed
    # uint8 (C, N/8) big-endian, scores float16 — unpack/cast on device
    # where the FLOPs are free and the relay bytes are not.
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    cmask = ((class_mask[:, :, None] >> shifts) & 1).reshape(
        class_mask.shape[0], -1).astype(jnp.bool_)[:, : alloc_q.shape[0]]
    host_scores = class_scores.astype(jnp.float32)

    r = alloc_q.shape[1]
    tf = taint_f_mat.shape[1]
    used_q = used_pack[:, :r]
    used_nz_q = used_pack[:, r:2 * r]
    used_pods = used_pack[:, 2 * r]
    c_req_q = class_pack[:, :r]
    c_req_nz_q = class_pack[:, r:2 * r]
    c_untol_f = class_pack[:, 2 * r:2 * r + tf].astype(jnp.bool_)
    c_untol_p = class_pack[:, 2 * r + tf:].astype(jnp.bool_)
    # Per-pod request rows are class gathers (tiny: (P,R)); the scans
    # debit with them while every plane stays (C,N).
    req_q = c_req_q[cls_idx]
    req_nz_q = c_req_nz_q[cls_idx]

    fit0 = kernels.fit_filter_mask(
        alloc_q, used_q, used_pods, alloc_pods, c_req_q)        # (C,N)
    taint_ok = kernels.taint_filter_mask(taint_f_mat, c_untol_f)
    taint_ok = taint_ok | jnp.logical_not(taint_filter_on)
    mask = cmask & taint_ok
    feasible = mask & fit0

    # Capacity-independent score components; the capacity-dependent plugins
    # (fit/balanced) are re-scored live inside the greedy scan. Taint
    # normalization runs over the CLASS feasible set: exception-pinned
    # pods keep their class row (their argmax ranges over one column, so
    # scores cannot change their assignment).
    static_scores = host_scores + w_taint * kernels.taint_toleration_score(
        taint_p_mat, c_untol_p, feasible)

    free_q = alloc_q - used_q
    free_pods = alloc_pods - used_pods
    dom_counts2 = dom_counts
    nfall = jnp.int32(0)
    wave_com = jnp.int32(0)
    wave_rep = jnp.int32(0)
    blk_scanned = jnp.int32(0)
    blk_pruned = jnp.int32(0)
    n_pad = alloc_q.shape[0]
    if solve_mode == "optimal" and not use_spread:
        # Batch-optimal mode (see docstring): transport plan over the
        # class planes, then the SAME scans round it against live
        # capacity with the re-scoring weights zeroed. Runs BEFORE the
        # shortlist prefilter so a composed shortlist prunes the plan
        # scores it will scan (exactness preserved).
        sc0_cost = kernels.chunk_start_scores(
            alloc_q, used_nz_q, c_req_nz_q, static_scores,
            fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
            strategy)
        row_counts = jnp.zeros(
            (cmask.shape[0],), jnp.float32).at[cls_idx].add(1.0)
        static_scores, _ = solver.sinkhorn_plan(
            feasible, sc0_cost, row_counts, jnp.maximum(free_pods, 0),
            sink_iters, sink_temp)
        w_fit = jnp.float32(0.0)
        w_bal = jnp.float32(0.0)
    if shortlist_k:
        # Shortlist prefilter: chunk-start live scores per pod CLASS
        # (C rows, not P — the planes already ARE class rows), top-K
        # columns + the (K+1)-th value as the scans' exactness
        # threshold. Chunk-start capacity feasibility folds in (capacity
        # only decreases within a chunk); spread gating deliberately
        # does not (it is non-monotone and exact in-scan — see the
        # spread solver).
        # block_w > 0 routes the TWO-PASS BLOCK-SPARSE form: an O(C·B)
        # per-block bound scan gates which columns the chunk-start pass
        # touches, falling back to the full-width pass in-program
        # whenever its exactness predicate cannot prove the gathered
        # top-K global (solver.block_bound_prefilter). Static routing:
        # block_w is part of the fused-program key like wave_w, and 0
        # (the KTPU_BLOCK_INDEX kill switch / small-N tuner decision /
        # M+1 > B shape guard) traces the r18/r21 full-width call graph
        # verbatim.
        if block_w > 0:
            sc0, cand_s, thresh_s, blk_scanned, blk_pruned = \
                solver.block_bound_prefilter(
                    alloc_q, used_nz_q, c_req_nz_q, static_scores,
                    feasible, fit_col_w, bal_col_mask, shape_u, shape_s,
                    w_fit, w_bal, strategy, n_real, shortlist_k,
                    block_w)
        else:
            sc0 = kernels.chunk_start_scores(
                alloc_q, used_nz_q, c_req_nz_q, static_scores,
                fit_col_w, bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                strategy)
            cand_s, thresh_s = solver.shortlist_prefilter(
                feasible, sc0, shortlist_k)
        sl_cand = cand_s[cls_idx]                               # (P, K)
        sl_thresh = thresh_s[cls_idx]                           # (P,)
        # has_node: class-level any(), narrowed to the pinned column for
        # exception pods (their only possibly-feasible node).
        has_c = jnp.any(mask, axis=1)                           # (C,)
        has_node = has_c[cls_idx]
        safe_e = jnp.clip(exc_col, 0, n_pad - 1)
        has_node = jnp.where(exc_col >= 0, mask[cls_idx, safe_e], has_node)
    if use_spread:
        # Spread batches run the identity order only (domain counts and
        # permutations don't commute cheaply); gang masking still applies.
        if shortlist_k:
            a0, dom_counts2, nfall = \
                solver.greedy_assign_rescoring_spread_shortlist(
                    req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
                    mask, static_scores, fit_col_w, bal_col_mask, shape_u,
                    shape_s, w_fit, w_bal, strategy,
                    dom_onehot, cid_onehot, dom_counts, max_skew,
                    sp_min_ok, sp_haskey, sp_applies, sp_contrib,
                    sc0, cls_idx, sl_cand, sl_thresh, has_node,
                    rows=cls_idx, exc=exc_col)
        elif wave_w > 1:
            a0, dom_counts2, wave_com, wave_rep = \
                solver.greedy_assign_rescoring_spread_wave(
                    req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
                    mask, static_scores, fit_col_w, bal_col_mask, shape_u,
                    shape_s, w_fit, w_bal, strategy, wave_w,
                    dom_onehot, cid_onehot, dom_counts, max_skew,
                    sp_min_ok, sp_haskey, sp_applies, sp_contrib,
                    rows=cls_idx, exc=exc_col)
        else:
            a0, dom_counts2 = solver.greedy_assign_rescoring_spread(
                req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
                static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
                w_fit, w_bal, strategy,
                dom_onehot, cid_onehot, dom_counts, max_skew,
                sp_min_ok, sp_haskey, sp_applies, sp_contrib,
                rows=cls_idx, exc=exc_col)
        assign = solver.gang_filter(a0, gang_onehot, gang_required)
        # Gang-dropped pods bumped the chained counts in-scan (for the
        # constraints they CONTRIBUTE to) — fold them back out so later
        # chunks see the truth.
        dropped = (a0 >= 0) & (assign < 0)
        safe = jnp.clip(a0, 0, alloc_q.shape[0] - 1)
        contrib_d = sp_contrib @ cid_onehot.T                   # (P, D)
        dom_counts2 = dom_counts2 - jnp.sum(
            jnp.where(dropped[:, None],
                      dom_onehot[safe] * contrib_d, 0.0), axis=0)
    else:
        if shortlist_k and wave_w > 1:
            assign, nfall, wave_com, wave_rep = \
                solver.multistart_greedy_assign_shortlist_wave(
                    req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
                    mask, static_scores, fit_col_w, bal_col_mask, shape_u,
                    shape_s, w_fit, w_bal, strategy, wave_w, perms,
                    gang_onehot, gang_required, sc0, cls_idx, sl_cand,
                    sl_thresh, has_node, rows=cls_idx, exc=exc_col)
        elif shortlist_k:
            assign, nfall = solver.multistart_greedy_assign_shortlist(
                req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q,
                mask, static_scores, fit_col_w, bal_col_mask, shape_u,
                shape_s, w_fit, w_bal, strategy, perms, gang_onehot,
                gang_required, sc0, cls_idx, sl_cand, sl_thresh, has_node,
                rows=cls_idx, exc=exc_col)
        elif wave_w > 1:
            if pallas != "off":
                assign, wave_com, wave_rep = \
                    solver.multistart_greedy_assign_wave_pallas(
                        req_q, req_nz_q, free_q, free_pods, used_nz_q,
                        alloc_q, mask, static_scores, fit_col_w,
                        bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                        strategy, wave_w, perms, gang_onehot,
                        gang_required, rows=cls_idx, exc=exc_col,
                        interpret=(pallas != "compiled"))
            else:
                assign, wave_com, wave_rep = \
                    solver.multistart_greedy_assign_wave(
                        req_q, req_nz_q, free_q, free_pods, used_nz_q,
                        alloc_q, mask, static_scores, fit_col_w,
                        bal_col_mask, shape_u, shape_s, w_fit, w_bal,
                        strategy, wave_w, perms, gang_onehot,
                        gang_required, rows=cls_idx, exc=exc_col)
        else:
            assign = solver.multistart_greedy_assign(
                req_q, req_nz_q, free_q, free_pods, used_nz_q, alloc_q, mask,
                static_scores, fit_col_w, bal_col_mask, shape_u, shape_s,
                w_fit, w_bal, strategy, perms, gang_onehot, gang_required,
                rows=cls_idx, exc=exc_col)

    # Post-assignment state update (scatter-add of assigned requests).
    # Padding/unassigned rows scatter to a dummy row (index N, dropped).
    n = alloc_q.shape[0]
    hit = assign >= 0
    tgt = jnp.where(hit, assign, n)
    inc = jnp.concatenate(
        [req_q, req_nz_q, hit.astype(jnp.int32)[:, None]], axis=1)
    used_pack2 = used_pack + jnp.zeros(
        (n + 1, used_pack.shape[1]), used_pack.dtype
    ).at[tgt].add(jnp.where(hit[:, None], inc, 0))[:n]
    # The observability tail rides the assign fetch (one transfer, not
    # six): consumers slice [:p_real] for assignments, then [-5] =
    # shortlist fallbacks, [-4]/[-3] = wavefront commits/replays,
    # [-2]/[-1] = block-prefilter blocks scanned/pruned.
    assign_out = jnp.concatenate(
        [assign, nfall[None], wave_com[None], wave_rep[None],
         blk_scanned[None], blk_pruned[None]])
    return assign_out, used_pack2, fit0, taint_ok, dom_counts2


class TPUBackend:
    """Batched backend: `assign(pods, snapshot, fwk)` →
    ({pod_key: node_name|None}, {pod_key: {node_name: Status}})."""

    def __init__(self, max_batch: int | None = None, multistart: int = 4,
                 resources: Sequence[str] | None = None,
                 mesh: object = "auto"):
        #: None = flagless: the AdaptiveTuner picks the solve chunk from
        #: warmup-measured transfer latency + dirty-upload ratio. An
        #: explicit value (tests, --chunk sweeps) is an override the
        #: tuner never touches.
        self._chunk_override = max_batch is not None
        self.max_batch = max_batch if max_batch is not None \
            else _DEFAULT_CHUNK
        self._tuner = AdaptiveTuner()
        depth_override = _pipeline_depth_override()
        self.pipeline_depth = depth_override \
            if depth_override is not None else 4
        #: parallel permuted-order scans per chunk (1 = oracle-only order).
        #: Selection: most pods placed, then most request volume placed,
        #: identity on full ties — never fewer pods than the oracle order,
        #: and priority-block-stable permutations keep priority fairness.
        self.multistart = max(1, int(multistart))
        self._pinned_resources = list(resources) if resources else None
        #: SchedulerMetrics, injected by the Scheduler — degradation
        #: counters (spread poisoning, gang overflow) report through it.
        self.metrics = None
        #: control-plane shard count of the backing store, injected by
        #: Scheduler.attach_backend when the store advertises one
        #: (ShardedNodeStore.node_shards); None = the flagless policy.
        self.control_shards = None
        #: utils/tracing.Tracer, injected by Scheduler.attach_backend —
        #: per-chunk solver.dispatch/solver.solve spans nest under the
        #: scheduler's attempt span when tracing is on.
        self.tracer = None
        # Multi-device: shard the nodes axis over an ICI mesh
        # (SURVEY §5.7 — the TP-like axis). Inputs are placed with
        # NamedSharding and the SAME jit program auto-partitions (XLA
        # inserts the cross-shard reductions for the solver's per-step
        # argmax). mesh="auto" builds a 1-D nodes mesh over the largest
        # power-of-two device count (divides NODE_PAD, so any padded N
        # shards evenly); None forces single-device.
        if mesh == "auto":
            try:
                ndev = len(jax.devices())
            except Exception:  # pragma: no cover - no backend at all
                ndev = 1
            if ndev > 1:
                from kubernetes_tpu.parallel import build_mesh
                n = 1 << (ndev.bit_length() - 1)  # largest power of two ≤ ndev
                mesh = build_mesh(n)
            else:
                mesh = None
        self.mesh = mesh
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from kubernetes_tpu.parallel import NODES_AXIS, SLICE_AXIS
            # A multi-slice mesh (config #5) shards the node dimension over
            # BOTH axes, slice-major: XLA then lowers reductions over the
            # pair hierarchically (ICI within a slice, DCN across).
            axis = (SLICE_AXIS, NODES_AXIS) \
                if SLICE_AXIS in self.mesh.axis_names else NODES_AXIS
            self._sh_nodes_mat = NamedSharding(
                self.mesh, PartitionSpec(axis, None))
            self._sh_nodes_vec = NamedSharding(
                self.mesh, PartitionSpec(axis))
            self._sh_pn = NamedSharding(
                self.mesh, PartitionSpec(None, axis))
            self._sh_rep = NamedSharding(self.mesh, PartitionSpec())
        self._ct: ClusterTensors | None = None
        # (plugin, sig) -> np row; valid while _row_fp matches.
        self._row_cache: dict[
            tuple[str, str], tuple[np.ndarray, bool]] = {}
        self._row_fp: tuple | None = None
        # Device-resident constants for the common "no host rows" case:
        # clean chunks' class planes depend only on (plane rows, real
        # classes, node count), so one cached (C,N/8)+(C,N) pair serves
        # every clean chunk of that shape — the per-pod fallback's
        # (P,N)-shaped equivalents ride the same dicts.
        self._dev_base_mask: dict[tuple, object] = {}
        self._dev_zero_scores: dict[tuple, object] = {}
        # Static per-snapshot arrays (alloc, taints) re-uploaded only when
        # the node-static fingerprint moves.
        self._dev_static: dict[str, object] = {}
        self._dev_static_fp: tuple | None = None
        self._fwk_params_cache: dict[tuple, dict] = {}
        # Chained device-resident used-state, ONE packed (N, 2R+1) int32
        # array (used_q ‖ used_nz_q ‖ used_pods): uploaded fresh from the
        # snapshot at each assign() entry, then updated ON DEVICE by each
        # chunk's solve so successive chunks dispatch with no host
        # round-trip.
        self._dev_used = None
        #: serving/resident.ResidentPlanes, attached by the serving tier:
        #: when present, _start refreshes the used-state pack O(changed)
        #: from the cache's dirty-set deltas (scatter of re-quantized
        #: rows) instead of re-uploading the whole (N, 2R+1) array per
        #: assign() — the device-side twin of r13's incremental host
        #: prep. None (the KTPU_SERVING=0 shape) keeps the full upload.
        self.resident = None
        # Vectorized NodeResourceTopologyMatch zone state, cached per
        # (snapshot generation, snapshot identity) — see _nrt_state.
        self._nrt_cache: tuple | None = None
        self._dra_cache: tuple | None = None
        # Fixed-shape placeholder device arrays for the fused program's
        # spread slots when use_spread=False (stable jit signature).
        self._spread_dummy_cache: dict[tuple, tuple] = {}
        # Device-resident permutation sets (keyed by sizes+priorities) and
        # the all-zeros gang arrays for the common no-gang case — each
        # host→device transfer costs relay latency regardless of size.
        self._dev_perms_cache: dict[tuple, object] = {}
        self._dev_zero_gang: dict[int, tuple] = {}
        #: cached identity class index / no-exception vectors for the
        #: per-pod fallback and clean class chunks (tiny, but uploaded
        #: per chunk otherwise).
        self._dev_arange: dict[int, object] = {}
        self._dev_no_exc: dict[int, object] = {}

    # -- device placement ----------------------------------------------------

    def _put(self, arr, kind: str = "rep"):
        """Upload with the mesh sharding for `kind` ("nodes_mat",
        "nodes_vec", "pn", "rep"); plain transfer on a single device."""
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, getattr(self, "_sh_" + kind))

    # -- snapshot compilation ----------------------------------------------

    def _tensors(self, snapshot: Snapshot) -> ClusterTensors:
        if self._ct is None or self._ct.generation != snapshot.generation:
            self._ct = ClusterTensors(
                snapshot, resources=self._pinned_resources, prev=self._ct,
                shards=self.control_shards)
            self._affinity = None  # resident pods changed → recompile
            # Per-shard host-prep accounting (ROADMAP #5): only shards
            # whose rows this build rewrote count a rebuild — the
            # incremental delta path's observable witness.
            if self.metrics is not None:
                for s in self._ct.shard_rebuilds:
                    self.metrics.shard_tensor_rebuilds.inc(shard=str(s))
                topo = getattr(self._ct, "topology", None)
                if topo is not None and topo.rebuilt:
                    self.metrics.topology_plane_rebuilds.inc()
        if self._row_fp != self._ct._static_fp:
            self._row_cache.clear()
            self._row_fp = self._ct._static_fp
        return self._ct

    def _affinity_compiler(self, snapshot: Snapshot, ct: ClusterTensors):
        resolver = getattr(self, "_ns_resolver", None)
        epoch = resolver.epoch if resolver is not None else -1
        cached = getattr(self, "_affinity", None)
        if cached is not None and \
                getattr(self, "_affinity_ns_epoch", -1) != epoch:
            cached = None  # namespace relabel: resolved sets are stale
        if cached is None:
            from kubernetes_tpu.ops.affinity import AffinityCompiler
            cached = self._affinity = AffinityCompiler(
                snapshot, ct.n_pad, ns_resolver=resolver)
            self._affinity_ns_epoch = epoch
        return cached

    # -- NodeResourceTopologyMatch vectorization (BASELINE config #4) -----

    def _nrt_state(self, plugin, snapshot: Snapshot,
                   ct: ClusterTensors) -> dict | None:
        """Batch-start zone-free tensors for NodeResourceTopologyMatch:
        free/cap (N, Zmax, T), zone_valid (N, Zmax), tracked (N, T) over
        the union T of zone-listed resources. Running the host plugin's
        pack_zones per (pod × node) is O(P·N·residents); this packs each
        node ONCE per assign() and answers rows with numpy broadcasting.
        Within-batch drift is caught by the stateful full re-check in
        _verify (same delta pattern as PodTopologySpread)."""
        # nrt_seq invalidates on NRT object churn (which does not move the
        # snapshot generation); id(plugin) separates per-profile instances.
        key = (ct.generation, id(snapshot), id(plugin), plugin.nrt_seq)
        if self._nrt_cache is not None and self._nrt_cache[0] == key:
            return self._nrt_cache[1]
        from kubernetes_tpu.scheduler.plugins.noderesourcetopology import (
            SINGLE_NUMA_POLICIES, _zone_caps, pack_zones)
        T = sorted(plugin._zone_resources)
        t_index = {r: j for j, r in enumerate(T)}
        N = ct.n_real
        per_node: list[tuple | None] = []
        zmax = 1
        for ni in snapshot.nodes:
            nrt = plugin._nrt(ni.name)
            if nrt is None or not (
                    set(nrt.get("topologyPolicies") or [])
                    & SINGLE_NUMA_POLICIES):
                per_node.append(None)
                continue
            caps = [c for _, c in _zone_caps(nrt)]
            per_node.append((pack_zones(nrt, ni), caps))
            zmax = max(zmax, len(caps))
        free = np.zeros((N, zmax, len(T)), dtype=np.int64)
        cap = np.zeros_like(free)
        zone_valid = np.zeros((N, zmax), dtype=np.bool_)
        tracked = np.zeros((N, len(T)), dtype=np.bool_)
        for n, entry in enumerate(per_node):
            if entry is None:
                continue
            zfree, zcaps = entry
            for z, (zf, zc) in enumerate(zip(zfree, zcaps)):
                zone_valid[n, z] = True
                for r, v in zc.items():
                    j = t_index[r]
                    cap[n, z, j] = v
                    tracked[n, j] = True
                for r, v in zf.items():
                    free[n, z, t_index[r]] = v
        state = {"T": T, "t_index": t_index, "free": free, "cap": cap,
                 "zone_valid": zone_valid, "tracked": tracked,
                 "strategy": plugin.strategy}
        self._nrt_cache = (key, state)
        return state

    @staticmethod
    def _nrt_req_vec(st: dict, pi: PodInfo) -> np.ndarray:
        q = np.zeros(len(st["T"]), dtype=np.int64)
        for r, v in pi.requests.items():
            j = st["t_index"].get(r)
            if j is not None and v > 0:
                q[j] = v
        return q

    def _nrt_pod_eval(self, st: dict, pi: PodInfo, memo: dict, i: int):
        """Per-pod (q, constrained, zone_fit), memoized per chunk — the
        Filter and Score phases share the (N, Zmax, T) reduction."""
        hit = memo.get(i)
        if hit is None:
            q = self._nrt_req_vec(st, pi)
            qpos = (q > 0)[None, None, :]
            constrained = (st["tracked"] & (q > 0)[None, :]).any(-1)
            viol = st["tracked"][:, None, :] & qpos \
                & (st["free"] < q[None, None, :])
            zone_fit = st["zone_valid"] & ~viol.any(-1)
            hit = memo[i] = (q, constrained, zone_fit)
        return hit

    def _nrt_filter_row(self, st: dict, pi: PodInfo, memo: dict,
                        i: int) -> np.ndarray:
        """(n_real,) bool: host plugin's filter() vectorized."""
        _, constrained, zone_fit = self._nrt_pod_eval(st, pi, memo, i)
        return ~constrained | zone_fit.any(-1)

    def _nrt_score_row(self, st: dict, pi: PodInfo, memo: dict,
                       i: int) -> np.ndarray:
        """(n_real,) float: host plugin's score() vectorized (best zone by
        the configured strategy; 0 for unconstrained/unfitting nodes)."""
        q, constrained, zone_fit = self._nrt_pod_eval(st, pi, memo, i)
        qpos = (q > 0)[None, None, :]
        m = (st["cap"] > 0) & qpos
        cnt = m.sum(-1)
        safe_cap = np.maximum(st["cap"], 1)
        fr = np.where(m, (st["free"] - q[None, None, :]) / safe_cap, 0.0)
        denom = np.maximum(cnt, 1)
        mean = fr.sum(-1) / denom
        if st["strategy"] == "MostAllocated":
            s = 100.0 * (1.0 - mean)
        elif st["strategy"] == "BalancedAllocation":
            var = (np.where(m, fr * fr, 0.0).sum(-1) / denom) - mean * mean
            s = 100.0 * (1.0 - np.sqrt(np.maximum(var, 0.0)))
        else:  # LeastAllocated
            s = 100.0 * mean
        s = np.where(zone_fit & (cnt > 0), s, -np.inf)
        best = s.max(-1)
        return np.where(constrained & np.isfinite(best),
                        np.maximum(best, 0.0), 0.0)

    def _ipa_score_relevant(self, pi: PodInfo, snapshot: Snapshot) -> bool:
        """InterPodAffinity Score is nonzero only if the pod has preferred
        terms, or some resident pod contributes symmetry weight (preferred
        terms, or required affinity terms × hardPodAffinityWeight)."""
        if pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms:
            return True
        cached = getattr(self, "_ipa_resident_relevant", None)
        if cached is not None and cached[0] == snapshot.generation:
            return cached[1]
        relevant = any(
            e.preferred_affinity_terms or e.preferred_anti_affinity_terms
            or e.required_affinity_terms
            for ni in snapshot.have_pods_with_affinity
            for e in ni.pods_with_affinity)
        self._ipa_resident_relevant = (snapshot.generation, relevant)
        return relevant

    # -- host rows -----------------------------------------------------------

    def _static_filter_row(self, plugin, pi: PodInfo, snapshot: Snapshot,
                           ct: ClusterTensors) -> tuple[np.ndarray, bool]:
        """Returns (row, all_true). all() is cached with the row: re-scanning
        a 5k-wide row per pod per plugin was a top-3 host cost at perf scale."""
        key = (plugin.NAME, _signature(plugin.NAME, pi))
        hit = self._row_cache.get(key)
        if hit is None:
            state = CycleState()
            st = plugin.pre_filter(state, pi, snapshot)
            if st.is_skip() or st.is_success():
                row = np.fromiter(
                    (plugin.filter(state, pi, ni).is_success()
                     for ni in snapshot.nodes),
                    dtype=np.bool_, count=ct.n_real)
            else:
                row = np.zeros((ct.n_real,), dtype=np.bool_)
            hit = self._row_cache[key] = (row, bool(row.all()))
        return hit

    def _static_score_row(self, plugin, pi: PodInfo, snapshot: Snapshot,
                          ct: ClusterTensors) -> tuple[np.ndarray, bool]:
        """Returns (row, any_nonzero); see _static_filter_row on caching."""
        key = (plugin.NAME + "/score", _signature(plugin.NAME, pi))
        hit = self._row_cache.get(key)
        if hit is None:
            state = CycleState()
            row = np.fromiter(
                (plugin.score(state, pi, ni) for ni in snapshot.nodes),
                dtype=np.float32, count=ct.n_real)
            hit = self._row_cache[key] = (row, bool(row.any()))
        return hit

    # -- profiling (SURVEY §5.1: jax.profiler hook) -----------------------

    def start_profile(self, log_dir: str) -> bool:
        """Begin a device trace (TensorBoard/Perfetto readable). Returns
        False when the platform's profiler is unavailable (the axon relay
        may not support it) rather than failing the run."""
        try:
            jax.profiler.start_trace(log_dir)
            self._profiling = True
            return True
        except Exception as e:  # pragma: no cover - platform dependent
            logger.warning("jax profiler unavailable: %s", e)
            self._profiling = False
            return False

    def stop_profile(self) -> None:
        if getattr(self, "_profiling", False):
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                logger.warning("jax profiler stop failed: %s", e)
            self._profiling = False

    def _gang_args(self, prep: dict, batch) -> tuple:
        """(gang_onehot, gang_required) device arrays; the no-gang case
        reuses one cached zero pair per batch width."""
        if prep["gang_onehot"] is not None:
            return (self._put(prep["gang_onehot"]),
                    self._put(prep["gang_required"]))
        P = batch.req_q.shape[0]
        z = self._dev_zero_gang.get(P)
        if z is None:
            z = self._dev_zero_gang[P] = (
                self._put(np.zeros((P, _GANG_PAD), np.float32)),
                self._put(np.zeros((_GANG_PAD,), np.float32)))
        return z

    def _spread_dummies(self, n_pad: int, p: int) -> tuple:
        key = (n_pad, p)
        d = self._spread_dummy_cache.get(key)
        if d is None:
            d = (self._put(np.zeros((n_pad, 1), np.float32), "nodes_mat"),
                 self._put(np.zeros((1, 1), np.float32)),
                 self._put(np.zeros((1,), np.float32)),
                 self._put(np.zeros((1,), np.float32)),
                 self._put(np.zeros((1,), np.float32)),
                 self._put(np.zeros((n_pad, 1), np.float32), "nodes_mat"),
                 self._put(np.zeros((p, 1), np.float32)),
                 self._put(np.zeros((p, 1), np.float32)))
            self._spread_dummy_cache[key] = d
        return d

    @staticmethod
    def _spread_tpl_key(cs: list, pj: PodInfo) -> str:
        # EVERY semantic field participates: two templates differing only
        # in minDomains/namespaceSelector must NOT collide. The pod's
        # node-eligibility signature participates too — eligibility folds
        # into the template's constraint COLUMNS (domain membership and
        # counts are per eligible-node set), so pods with different
        # nodeSelector/affinity/tolerations need different columns even
        # for identical constraint lists.
        return repr((sorted((c.get("topologyKey", ""),
                             repr(c.get("labelSelector")),
                             c.get("maxSkew", 1),
                             repr(c.get("minDomains")),
                             repr(c.get("namespaceSelector")))
                            for c in cs), pj.namespace,
                     pj.node_selector,
                     pj.affinity.get("nodeAffinity"), pj.tolerations))

    def _build_spread_table(self, ctx, snapshot, ct, compiler,
                            plugin) -> None:
        """Union spread table, built ONCE per assign() from ALL chunks.

        Every distinct DoNotSchedule template in the batch contributes
        its constraints to one union list C; the scan gates each pod on
        ITS template's columns (`applies`) and counts every placed pod in
        the constraints its labels match (`contributes`) — heterogeneous
        batches and cross-matching non-spread pods stay on device. Every
        template shape compiles: namespaceSelector resolves to a
        namespace set at build time, minDomains becomes the per-
        constraint `min_ok` floor, restricted node eligibility folds into
        the template's domain columns, and non-self-matching selectors
        ride the per-pod selfMatch term (`contributes`). Templates whose
        constraints have NO domains anywhere get a static row (reject
        keyless nodes, fresh-pass the rest) instead of host fallback."""
        from kubernetes_tpu.api.labels import from_label_selector
        from kubernetes_tpu.ops.affinity import _seg_sum

        templates: dict[str, dict] = {}
        for chunk in ctx.chunks:
            for pj in chunk:
                if not pj.topology_spread_constraints:
                    continue
                cs = plugin._constraints_for(pj, "DoNotSchedule")
                if not cs:
                    continue
                key = self._spread_tpl_key(cs, pj)
                t = templates.get(key)
                if t is None:
                    t = templates[key] = {
                        "cons": cs, "ns": pj.namespace, "rep": pj,
                        "sels": [from_label_selector(
                            c.get("labelSelector")) for c in cs],
                    }

        cons: list[dict] = []      # union constraint list
        con_ns: list[tuple] = []   # resolved namespace set per constraint
        con_sels: list = []
        con_elig: list[np.ndarray] = []
        tpl_cols: dict[str, list[int]] = {}
        static_rows: dict[str, np.ndarray] = {}
        for key, t in templates.items():
            # A template whose every constraint has zero eligible domains
            # imposes only the static has-key gate (each keyed node is a
            # "fresh" domain that placements never populate — counting is
            # over eligible nodes only), so its pods take one static row
            # and skip the scan entirely.
            elig = compiler.eligibility_row(t["rep"])
            if not any(
                    (compiler.topo.has_key(c["topologyKey"]) & elig).any()
                    for c in t["cons"]):
                row = np.ones((ct.n_real,), dtype=np.bool_)
                for c in t["cons"]:
                    row &= compiler.topo.has_key(
                        c["topologyKey"])[: ct.n_real]
                static_rows[key] = row
                continue
            cols = []
            for cidx, c in enumerate(t["cons"]):
                cols.append(len(cons))
                cons.append(c)
                con_ns.append(
                    compiler.spread_constraint_ns(c, t["ns"]))
                con_sels.append(t["sels"][cidx])
                con_elig.append(elig)
            tpl_cols[key] = cols

        dom_slices = [compiler.topo.domains(c["topologyKey"])
                      for c in cons]
        if not cons:
            ctx.spread = {"cons": [], "tpl_cols": {},
                          "static_rows": static_rows, "ineligible": set()}
            return

        N = ct.n_pad
        C = len(cons)
        D = 0
        for cidx, (dom_ids, num) in enumerate(dom_slices):
            active = (dom_ids > 0) & con_elig[cidx]
            D += len(np.unique(dom_ids[active]))
        dom_onehot = np.zeros((N, D), dtype=np.float32)
        cid_onehot = np.zeros((D, C), dtype=np.float32)
        counts0 = np.zeros((D,), dtype=np.float32)
        has_key_nc = np.zeros((N, C), dtype=np.float32)
        min_ok = np.ones((C,), dtype=np.float32)
        g = 0
        for cidx, (dom_ids, num) in enumerate(dom_slices):
            counts = compiler.counts_for(
                cons[cidx].get("labelSelector"), con_ns[cidx])
            elig = con_elig[cidx]
            active = (dom_ids > 0) & elig
            d = _seg_sum(np.where(active, counts, 0.0), dom_ids, num)
            has_key_nc[:, cidx] = (dom_ids > 0).astype(np.float32)
            existing = np.unique(dom_ids[active])
            md = int(cons[cidx].get("minDomains") or 0)
            if md and len(existing) < md:
                min_ok[cidx] = 0.0  # minDomains deficit → global min = 0
            for k in existing:
                # Domain membership over ELIGIBLE nodes only: placements
                # on keyed-but-ineligible nodes neither count nor gate.
                dom_onehot[(dom_ids == k) & elig, g] = 1.0
                cid_onehot[g, cidx] = 1.0
                counts0[g] = d[k]
                g += 1
        # The table is built in _start BEFORE any chunk dispatches, so
        # ctx.delta is empty here by construction — every same-assign
        # placement is counted by the scan itself (sp_contrib).
        ctx.spread = {
            "cons": cons, "con_ns": con_ns, "con_sels": con_sels,
            "tpl_cols": tpl_cols,
            "static_rows": static_rows,
            "ineligible": set(),
            "dom_onehot_host": dom_onehot,
            "cid_onehot_host": cid_onehot,
            "dev_dom": self._put(dom_onehot, "nodes_mat"),
            "dev_cid": self._put(cid_onehot),
            "dev_skew": self._put(np.array(
                [float(c.get("maxSkew", 1)) for c in cons], np.float32)),
            "dev_min_ok": self._put(min_ok),
            "dev_haskey": self._put(has_key_nc, "nodes_mat"),
            "dev_counts": self._put(counts0),
        }

    def _process_spread_pods(self, spread_pods, pods, ctx, snapshot, ct,
                             apply_row, stateful_pods, dyn_states,
                             fwk) -> list[int]:
        """Hard (DoNotSchedule) PodTopologySpread routing.

        Every template rides the DEVICE scan
        (solver.greedy_assign_rescoring_spread): domain counts ride the
        scan carry, so tight maxSkew stays sequential-exact without the
        batch-then-verify requeue collapse — heterogeneous batches,
        namespaceSelector/minDomains constraints, restricted node
        eligibility, and non-self-matching selectors included. Templates
        with zero eligible domains take one static has-key row (exact —
        placements never move their counts). Host rows + stateful verify
        remain ONLY as the missing-table escape hatch, counted as
        spread_poisoned degradations (one per pod) — at steady state that
        counter stays zero."""
        if not spread_pods:
            return []
        compiler = self._affinity_compiler(snapshot, ct)
        plugin = next(p for p in fwk.filter_plugins
                      if p.NAME == "PodTopologySpread")
        sp = ctx.spread
        if sp is None:
            # _start builds the table eagerly whenever the batch carries
            # spread constraints; reaching here without one means the
            # batch mutated mid-assign — fall back rather than run the
            # scan against counts that missed in-flight chunks.
            logger.error("spread table missing at chunk prep; routing "
                         "%d pods to host rows", len(spread_pods))
            sp = {"tpl_cols": {}, "static_rows": {}}

        active: list[int] = []
        fallback: list[tuple[int, object, list]] = []
        for i, pi, cs in spread_pods:
            key = self._spread_tpl_key(cs, pi)
            if key in sp["tpl_cols"]:
                active.append(i)
                continue
            srow = sp["static_rows"].get(key)
            if srow is not None:
                # Zero-domain template: keyless nodes reject, keyed nodes
                # are fresh — static, no verify needed.
                if not srow.all():
                    apply_row("PodTopologySpread", i, srow)
                continue
            fallback.append((i, pi, cs))

        if fallback:
            if not ctx.spread_poisoned:
                logger.warning(
                    "PodTopologySpread: %d pods missed the union table "
                    "(batch mutated mid-assign?) — host rows + stateful "
                    "verify for them", len(fallback))
            ctx.spread_poisoned = True
            if self.metrics is not None:
                self.metrics.backend_degradations.inc(
                    len(fallback), kind="spread_poisoned")
            for i, pi, cs in fallback:
                row = compiler.spread_filter_row(pi, cs)[: ct.n_real]
                if not row.all():
                    apply_row("PodTopologySpread", i, row)
                stateful_pods.add(i)
        return active

    # -- DynamicResources (DRA) vectorization -------------------------------

    def _dra_state(self, plugin, snapshot: Snapshot,
                   ct: ClusterTensors) -> dict:
        """Batch-start free-device tensors for DynamicResources: per
        (node, class) total free counts plus, per device-attribute key,
        the largest single-value group — enough to answer count-N claims
        with or without a single matchAttribute constraint via numpy
        rows instead of O(N·claims) host plugin calls. Claims are charged
        from the allocation ledger + resident unallocated demand + the
        assume ledger ONCE per batch (same shape as _nrt_state); in-batch
        drift is caught by the stateful re-verify."""
        from kubernetes_tpu.scheduler.plugins.dynamicresources import (
            claim_allocated_node,
            pod_claim_keys,
        )
        key = (ct.generation, id(snapshot), id(plugin), plugin.dra_seq,
               plugin.assume_seq)
        if self._dra_cache is not None and self._dra_cache[0] == key:
            return self._dra_cache[1]
        classes = plugin._classes()
        class_names = sorted(classes)
        c_index = {c: j for j, c in enumerate(class_names)}
        N, C = ct.n_real, len(class_names)
        free_total = np.zeros((N, C), dtype=np.int32)
        #: attr key -> (N, C) best single-value group size
        max_group: dict[str, np.ndarray] = {}

        # One pass over the claim ledgers, grouped per node.
        charges: dict[str, dict[str, dict]] = {}

        def charge(node_name: str, claim: dict) -> None:
            from kubernetes_tpu.api.meta import namespaced_name as nn
            charges.setdefault(node_name, {})[nn(claim)] = claim

        for n, bucket in plugin._alloc_by_node.items():
            for claim in bucket.values():
                charge(n, claim)
        for ni in snapshot.nodes:
            for pi in ni.pods:
                for ckey in pod_claim_keys(pi):
                    claim = plugin._claim_informer.indexer.get(ckey) \
                        if plugin._claim_informer is not None else None
                    if claim is not None and \
                            claim_allocated_node(claim) is None:
                        charge(ni.name, claim)
        for a in plugin._assumed.values():
            charge(a["node"], a["claim"])

        attr_keys: set[str] = set()
        per_node_free: list[list[dict]] = []
        for idx, ni in enumerate(snapshot.nodes):
            devices = plugin.node_devices(ni.name)  # indexed by node
            if not devices:
                per_node_free.append([])
                continue
            taken: set[str] = set()
            for claim in (charges.get(ni.name) or {}).values():
                alloc = (claim.get("status") or {}).get("allocation")
                if alloc:
                    if alloc.get("nodeName") == ni.name:
                        taken.update(alloc.get("devices") or [])
                    continue
                picked = plugin._pick_devices(
                    claim, [d for d in devices if d["name"] not in taken],
                    classes)
                if picked is not None:
                    taken.update(picked)
            free = [d for d in devices if d["name"] not in taken]
            per_node_free.append(free)
            for d in free:
                attr_keys.update((d.get("attributes") or {}).keys())
        for a in attr_keys:
            max_group[a] = np.zeros((N, C), dtype=np.int32)
        for idx, free in enumerate(per_node_free):
            if not free:
                continue
            for j, cname in enumerate(class_names):
                cls = classes[cname]
                matching = [d for d in free
                            if plugin._class_matches(cls, d)]
                free_total[idx, j] = len(matching)
                for a in attr_keys:
                    groups: dict = {}
                    for d in matching:
                        v = (d.get("attributes") or {}).get(a)
                        groups[v] = groups.get(v, 0) + 1
                    if groups:
                        max_group[a][idx, j] = max(groups.values())
        state = {"c_index": c_index, "free_total": free_total,
                 "max_group": max_group, "_name_idx": ct.name_to_idx}
        self._dra_cache = (key, state)
        return state

    def _dra_filter_row(self, st: dict, plugin, pi: PodInfo,
                        memo: dict, i: int) -> np.ndarray | None:
        """(n_real,) bool row, or None when the pod's claims use a shape
        the tensors can't answer (multi-attribute constraints, unknown
        class/claim) — caller falls back to the host plugin row."""
        hit = memo.get(i)
        if hit is not None:
            return hit if hit is not False else None
        from kubernetes_tpu.scheduler.plugins.dynamicresources import (
            claim_allocated_node,
            claim_match_attrs,
            claim_requests,
            pod_claim_keys,
        )
        N = st["free_total"].shape[0]
        row = np.ones((N,), dtype=np.bool_)
        for ckey in pod_claim_keys(pi):
            claim = plugin._claim_informer.indexer.get(ckey) \
                if plugin._claim_informer is not None else None
            if claim is None:
                memo[i] = False
                return None
            pinned = claim_allocated_node(claim)
            if pinned is not None:
                pin_row = np.zeros((N,), dtype=np.bool_)
                # restrict to the allocated node (PreFilter pinning)
                # via positional lookup in the snapshot ordering
                idx = st.get("_name_idx")
                if idx is None:
                    memo[i] = False
                    return None
                j = idx.get(pinned)
                if j is not None:
                    pin_row[j] = True
                row &= pin_row
                continue
            attrs = claim_match_attrs(claim)
            if len(attrs) > 1 or (attrs and
                                  len(claim_requests(claim)) > 1):
                # Multi-attribute constraints, or a claim-wide constraint
                # spanning several requests, need whole-claim group
                # packing — host row answers exactly.
                memo[i] = False
                return None
            for req in claim_requests(claim):
                j = st["c_index"].get(req.get("deviceClassName", ""))
                if j is None:
                    row[:] = False
                    continue
                count = int(req.get("count", 1))
                if attrs:
                    mg = st["max_group"].get(attrs[0])
                    avail = mg[:, j] if mg is not None else 0
                else:
                    avail = st["free_total"][:, j]
                row &= avail >= count
        memo[i] = row
        return row

    def _dynamic_filter_row(self, plugin, pi: PodInfo, snapshot: Snapshot,
                            ct: ClusterTensors,
                            state: CycleState) -> np.ndarray | None:
        """Stateful plugins (InterPodAffinity/PodTopologySpread/NodePorts):
        None = plugin inactive for this pod (PreFilter Skip)."""
        st = plugin.pre_filter(state, pi, snapshot)
        if st.is_skip():
            return None
        if not st.is_success():
            return np.zeros((ct.n_real,), dtype=np.bool_)
        return np.fromiter(
            (plugin.filter(state, pi, ni).is_success() for ni in snapshot.nodes),
            dtype=np.bool_, count=ct.n_real)

    # -- main entry ----------------------------------------------------------

    def assign(self, pods: Sequence[PodInfo], snapshot: Snapshot,
               fwk: Framework):
        """Synchronous driver. Batches larger than max_batch are chunked
        internally and PIPELINED: chunk k+1's solve is dispatched (device
        state chains on device) before chunk k's assignments are fetched,
        so the host verify of chunk k overlaps the device solve of k+1."""
        ctx = self._start(pods, snapshot, fwk)
        for run in self._pipeline(ctx):
            self._finalize_chunk(run, self._fetch_assign(run), ctx)
        return ctx.assignments, ctx.diagnostics

    async def assign_async(self, pods: Sequence[PodInfo], snapshot: Snapshot,
                           fwk: Framework):
        """Pipelined driver for the scheduler's event loop: same chunk
        pipeline as assign(), with the device→host fetch awaited in a worker
        thread so binding tasks keep draining during the device/relay wait."""
        ctx = None
        async for _chunk_pods, ctx in self.assign_stream(pods, snapshot, fwk):
            pass
        if ctx is None:  # empty batch
            return {}, {}
        return ctx.assignments, ctx.diagnostics

    async def assign_stream(self, pods: Sequence[PodInfo], snapshot: Snapshot,
                            fwk: Framework):
        """Chunk-streaming driver: yields (chunk_pods, ctx) as each chunk's
        host verify completes, so the CALLER's per-pod work (assume →
        Reserve → bindingCycle wire writes) overlaps the NEXT chunk's
        device solve instead of waiting for the whole super-batch — the
        schedule_one/bind asynchrony of SURVEY §2.8 applied between device
        and API boundary. ctx.assignments/diagnostics accumulate; the
        chunk's own keys are final once yielded."""
        import asyncio

        ctx = self._start(pods, snapshot, fwk)
        for run in self._pipeline(ctx):
            got = await asyncio.to_thread(self._fetch_assign, run)
            if (got[: run["batch"].p_real] < 0).any():
                # Solver failures → _finalize_chunk will need the unsat
                # planes for diagnostics. Fetch them HERE, off-loop and
                # overlapped (copy_to_host_async both, then block in the
                # worker): the synchronous np.asarray inside finalize
                # stalled the event loop one relay round-trip per plane —
                # over half the wall on dense-failure (preemption) waves.
                await asyncio.to_thread(self._fetch_diag_planes, run)
            self._finalize_chunk(run, got, ctx)
            yield run["pods"], ctx

    def _fetch_assign(self, run: dict) -> np.ndarray:
        """Blocking device→host fetch of a chunk's assignments, timed.

        The r8 50k profile showed 98.3% main-thread idle with the cost
        hidden in XLA's compute threads — this wall (dispatch-to-ready of
        the fused solve, as seen by the consumer) is the observability
        for that blind spot: scheduler_tpu_solve_seconds per chunk, plus
        the solver scan width / shortlist fallback counters extracted
        from the same fetch in _finalize_chunk."""
        check_dispatch_seam("backend.fetch_assign")
        tr = self.tracer
        span = tr.span("solver.solve", chunk=run.get("chunk_idx"),
                       pods=run["batch"].p_real) \
            if tr is not None and tr.enabled else contextlib.nullcontext()
        t0 = time.perf_counter()
        with span:
            if _TRACE_ANNOTATION is not None:
                with _TRACE_ANNOTATION("ktpu.solve.fetch"):
                    got = np.asarray(run["assign_d"])
            else:
                got = np.asarray(run["assign_d"])
        run["solve_wall_s"] = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.solve_duration.observe(run["solve_wall_s"])
        return got

    def _pipeline(self, ctx: "_AssignCtx"):
        """Yield dispatched chunk runs in finalize order, keeping up to
        `pipeline_depth` solves in flight ahead of the consumer's fetch
        (tuner-chosen; KTPU_PIPELINE_DEPTH overrides for sweeps)."""
        from collections import deque

        pending: deque = deque()
        for chunk in ctx.chunks:
            pending.append(
                self._dispatch_chunk(self._prep_chunk(chunk, ctx), ctx))
            if len(pending) > self.pipeline_depth:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    def _start(self, pods: Sequence[PodInfo], snapshot: Snapshot,
               fwk: Framework) -> "_AssignCtx":
        # Adaptive chunk/depth land at assign() boundaries only (a chunk
        # change is one recompile at the new jit width; mid-batch it would
        # thrash the signature). Overrides pin their respective knob.
        # Node count is a structural signal (the large-N row + shortlist
        # policy read it) — recorded before the decision.
        self._tuner.n_nodes = len(snapshot.nodes)
        decision = self._tuner.decide()
        if decision is not None:
            chunk, depth = decision
            if not self._chunk_override and chunk != self.max_batch:
                logger.info("adaptive tuner: solve chunk %d -> %d "
                            "(latency %.1f ms, dirty ratio %.2f)",
                            self.max_batch, chunk,
                            1e3 * (self._tuner.latency_s or 0),
                            self._tuner.dirty_chunks
                            / max(1, self._tuner.total_chunks))
                self.max_batch = chunk
            if _pipeline_depth_override() is None:
                self.pipeline_depth = depth
        ct = self._tensors(snapshot)
        pods = list(pods)
        # namespaceSelector terms resolve through the framework's
        # InterPodAffinity plugin (its namespaces informer); spread
        # constraints share the mechanism, so PodTopologySpread's
        # resolver backs it when no InterPodAffinity profile exists.
        # Without either, resolve_term_namespaces' static rule applies.
        src = next((p for p in fwk.plugins
                    if p.NAME == "InterPodAffinity"), None) or next(
            (p for p in fwk.plugins
             if p.NAME == "PodTopologySpread"), None)
        self._ns_resolver = getattr(src, "ns_resolver", None)
        ctx = _AssignCtx()
        ctx.snapshot, ctx.fwk, ctx.ct = snapshot, fwk, ct
        # Class-plane cap resolved once per assign() (env-driven so tests
        # and the bench --class-pad sweep can flip it between calls).
        ctx.class_pad = class_pad()
        ctx.chunks = [pods[lo:lo + self.max_batch]
                      for lo in range(0, len(pods), self.max_batch)]
        ctx.assignments, ctx.diagnostics = {}, {}
        # Shared verify state: later chunks are checked against earlier
        # chunks' accepted placements (working snapshot + delta list).
        ctx.working = {}
        ctx.delta = []
        ctx.delta_has_terms = False
        ctx.sel_cache = {}
        ctx.delta_idx = _DeltaAffinityIndex(ctx.sel_cache,
                                            self._ns_resolver)
        ctx.wsnap = None
        # Device-side PodTopologySpread union table: built EAGERLY when
        # any pod in the batch carries spread constraints, so chunks
        # dispatched before the first spread pod still count their
        # selector-matching placements. Every template shape compiles;
        # the host fallback remains only as the missing-table escape
        # hatch (spread_poisoned observability, steady-state zero).
        ctx.spread = None
        ctx.spread_poisoned = False
        ctx.spread_last_gated = -1
        ctx.chunk_seq = -1
        if any(pj.topology_spread_constraints
               for chunk in ctx.chunks for pj in chunk):
            sp_plugin = next((p for p in fwk.filter_plugins
                              if p.NAME == "PodTopologySpread"), None)
            if sp_plugin is not None:
                self._build_spread_table(
                    ctx, snapshot, ct,
                    self._affinity_compiler(snapshot, ct), sp_plugin)
                # Last chunk with scan-GATED pods: contribute-only chunks
                # after it can keep the multistart solver (their counts
                # no longer influence any gating decision).
                if ctx.spread.get("cons"):
                    cols = ctx.spread["tpl_cols"]
                    for k, chunk in enumerate(ctx.chunks):
                        for pj in chunk:
                            if not pj.topology_spread_constraints:
                                continue
                            cs = sp_plugin._constraints_for(
                                pj, "DoNotSchedule")
                            if cs and self._spread_tpl_key(
                                    cs, pj) in cols:
                                ctx.spread_last_gated = k
                                break
        ctx.params = self._fwk_params(fwk, ct)
        # Used-state seed for the on-device chunk chain: the serving
        # tier's resident planes refresh it O(changed) from the cache's
        # dirty set; without them, one fresh full upload per call.
        # Either way the chain's post-chunk arrays are NEW device values
        # — the resident base is never mutated by a batch. When the
        # fused program DONATES its used_pack input (accelerator
        # backends; see _solve_program), the resident base must be
        # copied into a chain-owned buffer first or the first chunk
        # would invalidate the planes the serving tier keeps warm; on
        # CPU (no donation) the base is safe as a plain input.
        if self.resident is not None:
            base = self.resident.used_pack(ct, snapshot)
            self._dev_used = _copy_pack(base) if _donation_live() else base
        else:
            self._dev_used = self._put(np.concatenate(
                [ct.used_q, ct.used_nz_q,
                 ct.used_pods.astype(np.int32)[:, None]], axis=1),
                "nodes_mat")
        return ctx

    def _fwk_params(self, fwk: Framework, ct: ClusterTensors) -> dict:
        # Cached per (framework, resource columns): the device scalars are
        # ~9 separate host→device transfers, each costing relay latency.
        # The entry HOLDS the framework so its id can't be recycled by a
        # new Framework and serve stale weights; identity is re-checked.
        key = (id(fwk), tuple(ct.resources))
        cached = self._fwk_params_cache.get(key)
        if cached is not None and cached[0] is fwk:
            return cached[1]
        if len(self._fwk_params_cache) > 64:
            self._fwk_params_cache.clear()
        score_plugins = {p.NAME: p for p in fwk.score_plugins}
        fit_plugin = score_plugins.get("NodeResourcesFit")
        strategy = getattr(fit_plugin, "strategy_type", "LeastAllocated")
        fit_col_w = np.zeros((len(ct.resources),), dtype=np.float32)
        if fit_plugin is not None:
            for spec in fit_plugin.score_resources:
                j = ct.r_index.get(spec["name"])
                if j is not None:
                    fit_col_w[j] = spec.get("weight", 1)
        bal_plugin = score_plugins.get("NodeResourcesBalancedAllocation")
        bal_col_mask = np.zeros((len(ct.resources),), dtype=np.bool_)
        if bal_plugin is not None:
            for r in bal_plugin.resources:
                j = ct.r_index.get(r)
                if j is not None:
                    bal_col_mask[j] = True
        shape_pts = getattr(fit_plugin, "shape", None) or [
            {"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]
        w = fwk.score_weights
        filter_names = {p.NAME for p in fwk.filter_plugins}
        params = {
            "strategy": strategy,
            "fit_col_w": self._put(fit_col_w),
            "bal_col_mask": self._put(bal_col_mask),
            "shape_u": self._put(
                np.array([p["utilization"] for p in shape_pts], np.float32)),
            "shape_s": self._put(
                np.array([p["score"] for p in shape_pts], np.float32)),
            "w_fit": jnp.float32(
                w.get("NodeResourcesFit", 1) if fit_plugin else 0),
            "w_bal": jnp.float32(
                w.get("NodeResourcesBalancedAllocation", 1) if bal_plugin else 0),
            "w_taint": jnp.float32(
                w.get("TaintToleration", 3)
                if "TaintToleration" in score_plugins else 0),
            "taint_filter_on": jnp.bool_("TaintToleration" in filter_names),
            "filter_names": filter_names,
        }
        self._fwk_params_cache[key] = (fwk, params)
        return params

    def _prep_chunk(self, pods: list[PodInfo], ctx: "_AssignCtx") -> dict:
        prep_t0 = time.perf_counter()
        ct, snapshot, fwk = ctx.ct, ctx.snapshot, ctx.fwk
        ctx.chunk_seq += 1
        chunk_idx = ctx.chunk_seq
        P = self.max_batch
        batch = PodBatch(pods, ct, P)
        N = ct.n_pad

        filter_names = {p.NAME for p in fwk.filter_plugins}
        score_plugins = {p.NAME: p for p in fwk.score_plugins}

        # Host filter rows accumulate as INTERNED references, never a
        # (P,N) plane: each applied row is content-interned once (shared
        # row objects — the static/IPA caches — memoize by identity, so
        # the O(N) tobytes runs once per distinct row, not per pod), each
        # pod carries the list of its row ids, and the class build below
        # materializes ONE AND-folded row per distinct row-set. Rows with
        # exactly one allowed column (NodeName, DRA allocated-claim pins)
        # become the pod's sparse EXCEPTION column instead — they would
        # otherwise split every pinned pod into its own class.
        row_store: dict[int, np.ndarray] = {}      # cid -> ok row (n_real,)
        _row_bytes: dict[bytes, int] = {}
        _row_memo: dict[int, tuple] = {}           # id(row) -> (cid,nnz,col)
        _row_refs: list = []                       # pin ids against reuse
        pod_rows: dict[int, list[int]] = {}        # pod -> ordered cids
        pod_pin: dict[int, int] = {}               # pod -> exception column
        infeasible: set[int] = set()               # empty mask (class 0)

        def _intern_row(row: np.ndarray) -> tuple:
            got = _row_memo.get(id(row))
            if got is None:
                b = row.tobytes()
                cid = _row_bytes.get(b)
                if cid is None:
                    cid = _row_bytes[b] = len(_row_bytes)
                    row_store[cid] = row
                nnz = int(row.sum())
                col = int(np.argmax(row)) if nnz == 1 else -1
                got = _row_memo[id(row)] = (cid, nnz, col)
                _row_refs.append(row)
            return got

        # Pods requesting resources no tracked column covers are infeasible
        # everywhere (would silently drop a constraint on device).
        unknown_res: set[int] = set()
        for i, pi in enumerate(pods):
            if ct.has_unknown_resource(pi.requests):
                infeasible.add(i)
                unknown_res.add(i)

        # Host-side rows: static predicate plugins (signature-cached) and
        # stateful irregular plugins (per pod, Skip-gated).
        dyn_states: dict[int, CycleState] = {}
        nrt_memo: dict[int, tuple] = {}
        dra_memo: dict[int, object] = {}
        #: hard-spread pods deferred for template detection (see
        #: _process_spread_pods): (chunk index, PodInfo, constraints).
        spread_pods: list[tuple[int, PodInfo, list[dict]]] = []
        #: plugin -> {pod -> its (n_real,) ok row} for the lazy per-pod
        #: diagnostics (shared row objects — no plane, no copies).
        host_filter_fail: dict[str, dict[int, np.ndarray]] = {}
        #: pods whose NON-affinity stateful filter gate fired (full host
        #: re-verification). Affinity-handled pods are covered by the cheap
        #: delta verify inside _verify (routed by delta_has_terms /
        #: has_affinity_constraints), not by this set.
        stateful_pods: set[int] = set()
        #: DISTINCT pods that took at least one per-pod host plugin row
        #: this chunk — counted once per pod (not per plugin) into
        #: backend_degradations{kind="host_fallback"} below.
        fallback_pods: set[int] = set()

        def _apply_interned(i: int, cid: int, nnz: int, col: int) -> None:
            if nnz == 0:
                infeasible.add(i)
            elif col >= 0:
                prev = pod_pin.get(i)
                if prev is None:
                    pod_pin[i] = col
                elif prev != col:    # two pins disagree: no node survives
                    infeasible.add(i)
            else:
                lst = pod_rows.get(i)
                if lst is None:
                    lst = pod_rows[i] = []
                if cid not in lst:
                    lst.append(cid)

        def apply_row(pname: str, i: int, row: np.ndarray) -> None:
            # All-true rows are no-ops; applying them would dirty the
            # planes and force a re-upload every batch.
            if row.all():
                return
            fmap = host_filter_fail.get(pname)
            if fmap is None:
                fmap = host_filter_fail[pname] = {}
            prev = fmap.get(i)
            fmap[i] = row if prev is None else (prev & row)
            _apply_interned(i, *_intern_row(row))

        #: shared-row groups for the tensorized InterPodAffinity rows:
        #: template batches produce ONE row object per signature, so the
        #: per-pod O(N) mask AND collapses to one vectorized write per
        #: distinct row (id-keyed — filter_row returns cached objects).
        ipa_groups: dict[int, tuple[np.ndarray, list[int]]] = {}
        compiler = None

        for plugin in fwk.filter_plugins:
            if plugin.NAME in DEVICE_FILTER_PLUGINS:
                continue
            if plugin.NAME in STATIC_ROW_PLUGINS:
                for i, pi in enumerate(pods):
                    if i in unknown_res:
                        continue
                    row, all_true = self._static_filter_row(
                        plugin, pi, snapshot, ct)
                    if not all_true:
                        apply_row(plugin.NAME, i, row)
            else:
                gate = _FILTER_ACTIVE.get(plugin.NAME)
                for i, pi in enumerate(pods):
                    if i in unknown_res:
                        continue
                    if gate is not None and not gate(plugin, pi, snapshot):
                        continue
                    if plugin.NAME == "InterPodAffinity":
                        # Tensorized path (ops/affinity.py): dense per-term
                        # masks over interned label signatures instead of
                        # O(N) host plugin calls per pod. Rows group by
                        # identity for one vectorized apply below.
                        if compiler is None:
                            compiler = self._affinity_compiler(snapshot, ct)
                        row_full = compiler.filter_row(pi)
                        grp = ipa_groups.get(id(row_full))
                        if grp is None:
                            grp = ipa_groups[id(row_full)] = (row_full, [])
                        grp[1].append(i)
                        continue
                    if plugin.NAME == "NodeResourceTopologyMatch":
                        # Vectorized zone-alignment rows from batch-start
                        # zone state; in-batch drift → stateful re-check.
                        st_nrt = self._nrt_state(plugin, snapshot, ct)
                        row = self._nrt_filter_row(st_nrt, pi, nrt_memo, i)
                        if not row.all():
                            apply_row(plugin.NAME, i, row)
                        stateful_pods.add(i)
                        continue
                    if plugin.NAME == "DynamicResources":
                        # Vectorized claim-fit rows from batch-start free-
                        # device tensors; in-batch consumption → stateful
                        # re-check (over-admission is corrected there).
                        st_dra = self._dra_state(plugin, snapshot, ct)
                        row = self._dra_filter_row(
                            st_dra, plugin, pi, dra_memo, i)
                        if row is None:
                            state = dyn_states.setdefault(i, CycleState())
                            row = self._dynamic_filter_row(
                                plugin, pi, snapshot, ct, state)
                            if row is not None:
                                fallback_pods.add(i)
                        if row is not None and not row.all():
                            apply_row(plugin.NAME, i, row)
                        stateful_pods.add(i)
                        continue
                    if plugin.NAME == "PodTopologySpread":
                        constraints = plugin._constraints_for(
                            pi, "DoNotSchedule")
                        if not constraints:
                            continue  # gate was conservative; nothing to do
                        spread_pods.append((i, pi, constraints))
                        continue
                    state = dyn_states.setdefault(i, CycleState())
                    row = self._dynamic_filter_row(plugin, pi, snapshot, ct, state)
                    if row is not None:
                        apply_row(plugin.NAME, i, row)
                        # Per-pod host-row residency is DATA (bench detail
                        # host_fallback_pods), not just stderr noise.
                        fallback_pods.add(i)
                    # NodePorts conflicts only affect pods with ports (each
                    # is individually re-verified); cross-pod plugins flip
                    # the whole batch into full re-verification. row None
                    # means the plugin itself skipped after all.
                    if plugin.NAME != "NodePorts" and row is not None:
                        stateful_pods.add(i)

        if fallback_pods and self.metrics is not None:
            self.metrics.backend_degradations.inc(
                len(fallback_pods), kind="host_fallback")

        for row_full, idxs in ipa_groups.values():
            row = row_full[: ct.n_real]
            if row.all():
                continue
            # One interned row per signature group — every member pod
            # references it (class sharing falls out of the shared cid).
            cid, nnz, col = _intern_row(row)
            fmap = host_filter_fail.get("InterPodAffinity")
            if fmap is None:
                fmap = host_filter_fail["InterPodAffinity"] = {}
            for i in idxs:
                prev = fmap.get(i)
                fmap[i] = row if prev is None else (prev & row)
                _apply_interned(i, cid, nnz, col)

        spread_active_idx = self._process_spread_pods(
            spread_pods, pods, ctx, snapshot, ct, apply_row, stateful_pods,
            dyn_states, fwk)
        # Per-pod constraint matrices over the UNION spread table:
        # applies gates the pod's own template's columns; contributes
        # marks which constraints count the pod when placed — built for
        # EVERY pod (non-spread pods can match a template's selector).
        sp_applies = sp_contrib = None
        spt = ctx.spread
        if spt is not None and spt.get("cons"):
            C = len(spt["cons"])
            sp_applies = np.zeros((P, C), dtype=np.float32)
            sp_contrib = np.zeros((P, C), dtype=np.float32)
            active_set = set(spread_active_idx)
            for i, pi, cs in spread_pods:
                if i in active_set:
                    key = self._spread_tpl_key(cs, pi)
                    for c in spt["tpl_cols"].get(key, ()):
                        sp_applies[i, c] = 1.0
            memo = spt.setdefault("contrib_memo", {})
            con_ns = spt["con_ns"]
            con_sels = spt["con_sels"]
            for i, pi in enumerate(pods):
                sig = (pi.namespace,
                       tuple(sorted(pi.labels.items())) if pi.labels
                       else ())
                row = memo.get(sig)
                if row is None:
                    row = np.fromiter(
                        (1.0 if (ns_contains(con_ns[c], pi.namespace)
                                 and con_sels[c].matches(pi.labels))
                         else 0.0 for c in range(C)),
                        dtype=np.float32, count=C)
                    memo[sig] = row
                if row.any():
                    sp_contrib[i] = row

        # Host score rows: computed over each pod's *feasible* node set only
        # (PreScore/Score receive filtered nodes in the reference), then the
        # plugin's own NormalizeScore, then the profile weight. Feasibility
        # here must match the full Filter outcome — static rows ∧ taints ∧
        # exact fit — or min-max normalizations get skewed by scores of
        # nodes the solver will mask anyway.
        # Scores accumulate as interned PARTS, mirroring the filter rows:
        # each contribution is one (n_real,) float32 row shared by every
        # pod of the signature, each pod carries its ordered part list,
        # and the class build sums parts once per class — the per-pod
        # (P,N) float32 plane (~170 MB at 8k×5k) never exists.
        score_store: dict[int, np.ndarray] = {}    # sid -> weighted row
        _score_bytes: dict[bytes, int] = {}
        pod_parts: dict[int, list[int]] = {}       # pod -> ordered sids
        fit_np: np.ndarray | None = None
        taint_np: np.ndarray | None = None

        def _intern_score(row: np.ndarray) -> int:
            b = row.tobytes()
            sid = _score_bytes.get(b)
            if sid is None:
                sid = _score_bytes[b] = len(_score_bytes)
                score_store[sid] = row
            return sid

        def add_score_row(i: int, row: np.ndarray) -> None:
            pod_parts.setdefault(i, []).append(_intern_score(row))

        #: pod FEASIBILITY-CLASS key: (fit class, taint class, the pod's
        #: host filter-row ids + exception column) — pods of one template
        #: share it, so the per-pod O(N) nonzero/normalize work below runs
        #: once per class. This is the SAME key the device-plane class
        #: build uses (plus score parts there).
        feas_memo: dict[tuple, np.ndarray] = {}
        norm_memo: dict[tuple, tuple] = {}

        _pck_memo: dict[int, tuple] = {}

        def pod_class_key(i: int) -> tuple:
            got = _pck_memo.get(i)
            if got is None:
                got = _pck_memo[i] = (
                    batch.req_class[i], batch.untol_class[i],
                    tuple(pod_rows.get(i, ())), pod_pin.get(i, -1),
                    i in infeasible)
            return got

        def feasible_idx(i: int) -> np.ndarray:
            # Class-level masks: one row per DISTINCT request/toleration
            # shape (equivalence classes), not per pod — the (P,N,R)
            # broadcast was a top host cost for score-bearing families.
            nonlocal fit_np, taint_np
            pk = pod_class_key(i)
            got = feas_memo.get(pk)
            if got is not None:
                return got
            if i in infeasible:
                got = feas_memo[pk] = np.zeros((0,), dtype=np.intp)
                return got
            if fit_np is None:
                uq = np.stack(batch.req_rows)  # (n_classes, R)
                fit_np = np.all(
                    ct.used_q[None, :, :] + uq[:, None, :]
                    <= ct.alloc_q[None, :, :], axis=-1)
                fit_np &= (ct.used_pods + 1 <= ct.alloc_pods)[None, :]
                if "TaintToleration" in filter_names:
                    ut = np.stack(batch.untol_rows)
                    taint_np = (ut.astype(np.int32)
                                @ ct.taint_filter_mat.T.astype(np.int32)) == 0
                else:
                    taint_np = np.ones(
                        (len(batch.untol_rows),
                         ct.taint_filter_mat.shape[0]), dtype=np.bool_)
            feas = fit_np[batch.req_class[i], : ct.n_real] \
                & taint_np[batch.untol_class[i], : ct.n_real]
            rows_i = pod_rows.get(i)
            if rows_i:
                feas = feas.copy()
                for cid in rows_i:
                    feas &= row_store[cid]
            pin = pod_pin.get(i)
            if pin is not None:
                keep = bool(feas[pin])
                feas = np.zeros_like(feas)
                feas[pin] = keep
            got = feas_memo[pk] = np.nonzero(feas)[0]
            return got

        for name, plugin in score_plugins.items():
            if name in DEVICE_SCORE_PLUGINS:
                continue
            w = fwk.score_weights.get(name, 1)
            for i, pi in enumerate(pods):
                if i in unknown_res:
                    continue
                if name in STATIC_SCORE_PLUGINS:
                    if name == "NodeAffinity" and not (
                            (pi.affinity.get("nodeAffinity") or {})
                            .get("preferredDuringSchedulingIgnoredDuringExecution")):
                        continue
                    row, any_nonzero = self._static_score_row(
                        plugin, pi, snapshot, ct)
                    if not any_nonzero:
                        continue
                    raw = {ct.node_names[j]: float(row[j])
                           for j in feasible_idx(i)}
                else:
                    gate = _SCORE_ACTIVE.get(name)
                    if gate is not None and not gate(plugin, pi, snapshot):
                        continue
                    if name == "NodeResourceTopologyMatch":
                        st_nrt = self._nrt_state(plugin, snapshot, ct)
                        srow = self._nrt_score_row(st_nrt, pi, nrt_memo, i)
                        if srow.any():
                            add_score_row(
                                i, (w * srow).astype(np.float32))
                        continue
                    if name == "PodTopologySpread":
                        # Tensorized raw counts + vectorized NormalizeScore
                        # (min-max inversion over the feasible set) — every
                        # constraint shape, namespaceSelector included.
                        # Memoized per (feasibility class, pod signature):
                        # template batches normalize once.
                        constraints = plugin._constraints_for(
                            pi, "ScheduleAnyway")
                        nk = ("pts", pod_class_key(i), pi.namespace,
                              tuple(sorted(pi.labels.items())),
                              repr(constraints),
                              repr(pi.node_selector),
                              repr(pi.affinity.get("nodeAffinity")),
                              repr(pi.tolerations))
                        got = norm_memo.get(nk)
                        if got is None:
                            if compiler is None:
                                compiler = self._affinity_compiler(
                                    snapshot, ct)
                            raw_row = compiler.spread_raw_scores(
                                pi, constraints)[: ct.n_real]
                            feas = feasible_idx(i)
                            wnorm = None
                            if feas.size:
                                vals = raw_row[feas]
                                mx, mn = vals.max(), vals.min()
                                if mx > mn:
                                    wnorm = w * 100.0 * (mx - vals) \
                                        / (mx - mn)
                                else:
                                    wnorm = np.full_like(vals, w * 100.0)
                            got = norm_memo[nk] = (feas, wnorm, [])
                        got[2].append(i)
                        continue
                    if name == "InterPodAffinity":
                        if not self._ipa_score_relevant(pi, snapshot):
                            # No preferred terms anywhere and no
                            # hard-affinity symmetry sources → every score
                            # is 0; skip the O(N × residents) walk.
                            continue
                        # Tensorized for every term shape
                        # (namespaceSelector terms resolve at compile
                        # time); memoized per (feasibility class, pod
                        # signature), so template batches compute and
                        # normalize once.
                        nk = ("ipa", pod_class_key(i), pi.namespace,
                              tuple(sorted(pi.labels.items())),
                              repr(pi.preferred_affinity_terms),
                              repr(pi.preferred_anti_affinity_terms))
                        got = norm_memo.get(nk)
                        if got is None:
                            if compiler is None:
                                compiler = self._affinity_compiler(
                                    snapshot, ct)
                            feas = feasible_idx(i)
                            feas_mask = np.zeros(
                                (ct.n_pad,), dtype=np.bool_)
                            feas_mask[feas] = True
                            raw_row = compiler.score_row(
                                pi, float(getattr(
                                    plugin, "hard_pod_affinity_weight", 1)),
                                feas_mask)[: ct.n_real]
                            wnorm = None
                            if feas.size:
                                vals = raw_row[feas]
                                mx, mn = vals.max(), vals.min()
                                if mx > mn:
                                    wnorm = w * 100.0 * (vals - mn) \
                                        / (mx - mn)
                            got = norm_memo[nk] = (feas, wnorm, [])
                        got[2].append(i)
                        continue
                    state = dyn_states.setdefault(i, CycleState())
                    nodes_i = [snapshot.nodes[j] for j in feasible_idx(i)]
                    st = plugin.pre_score(state, pi, nodes_i)
                    if st.is_skip() or not st.is_success():
                        continue
                    raw = {ni.name: plugin.score(state, pi, ni)
                           for ni in nodes_i}
                state = dyn_states.get(i) or CycleState()
                plugin.normalize_scores(state, pi, raw)
                if raw:
                    srow = np.zeros((ct.n_real,), dtype=np.float32)
                    for nname, s in raw.items():
                        srow[ct.name_to_idx[nname]] += w * s
                    add_score_row(i, srow)

        # Flush of the memoized normalized score rows: each group's
        # sparse (feas, wnorm) pair densifies ONCE into an interned part
        # row shared by every member pod — the r7 row-dictionary wire
        # generalized; the class build below folds parts into (C,N)
        # class score rows, so no per-pod plane exists for ANY number of
        # distinct rows.
        for feas, wnorm, idxs in norm_memo.values():
            if wnorm is None or not idxs:
                continue
            srow = np.zeros((ct.n_real,), dtype=np.float32)
            srow[feas] = wnorm
            sid = _intern_score(srow)
            for i in idxs:
                pod_parts.setdefault(i, []).append(sid)

        # ---- class-dictionary plane build (the native device format) --
        # Pods dedupe into equivalence classes keyed by (request row,
        # toleration row, filter-row ids, ordered score-part ids) —
        # exception pins deliberately EXCLUDED, they ride the sparse
        # exc vector so a pinned pod shares its template's class. Class
        # 0 is reserved EMPTY (padding pods, unknown resources,
        # conflicting pins). Overflowing the cap — or the
        # KTPU_CLASS_PLANES=0 kill switch (cap 0) — falls back to
        # per-pod planes (C == P, identity index): structurally the
        # pre-class dense format, bit-identical assignments.
        cap = ctx.class_pad
        mask_dirty = bool(pod_rows or pod_pin or infeasible)
        scores_dirty = bool(pod_parts)
        dirty = mask_dirty or scores_dirty
        R = len(ct.resources)
        tf = batch.untol_filter.shape[1]
        tp = batch.untol_prefer.shape[1]
        class_reps: list[int] | None = None
        class_parts: list[tuple] = []
        cls_np = exc_np = None
        if cap:
            cls_map: dict[tuple, int] = {}
            class_reps = []
            cls_np = np.zeros((P,), dtype=np.int32)
            exc_np = np.full((P,), -1, dtype=np.int32)
            for i in range(batch.p_real):
                if i in infeasible:
                    continue                                   # class 0
                pin = pod_pin.get(i)
                # A pinned pod's argmax ranges over AT MOST one column,
                # so its score row cannot change its assignment — drop
                # its parts from the key (and the plane) rather than
                # let per-pin normalization split every pinned pod into
                # its own class. The class score row sums the KEY's
                # parts (class_parts), never the rep's, so a pinned rep
                # can't smuggle its dropped parts into a shared class.
                eff_parts = () if pin is not None \
                    else tuple(pod_parts.get(i, ()))
                ckey = (batch.req_class[i], batch.untol_class[i],
                        tuple(pod_rows.get(i, ())), eff_parts)
                c = cls_map.get(ckey)
                if c is None:
                    if len(class_reps) >= cap:
                        class_reps = None
                        break
                    c = cls_map[ckey] = len(class_reps) + 1
                    class_reps.append(i)
                    class_parts.append(eff_parts)
                cls_np[i] = c
                if pin is not None:
                    exc_np[i] = pin

        plane_bytes = 0
        if class_reps is not None:
            crows = _class_rows_bucket(len(class_reps))
            n_cls = len(class_reps)
            pack_np = np.zeros((crows, 2 * R + tf + tp), dtype=np.int32)
            if n_cls:
                ridx = np.asarray(class_reps, dtype=np.intp)
                pack_np[1: n_cls + 1] = np.concatenate(
                    [batch.req_q[ridx], batch.req_nz_q[ridx],
                     batch.untol_filter[ridx].astype(np.int32),
                     batch.untol_prefer[ridx].astype(np.int32)], axis=1)
            # Mask and score planes are cached INDEPENDENTLY (the r6
            # packed-wire discipline): a chunk with only score rows
            # keeps its clean cached mask and uploads scores alone, and
            # vice versa. Cache keys carry a format tag — the class and
            # per-pod keys are both 4-int tuples otherwise and could
            # collide at toy node pads.
            if mask_dirty:
                mask_np = np.zeros((crows, N), dtype=np.bool_)
                rowset_memo: dict[tuple, np.ndarray] = {}
                for c, rep in enumerate(class_reps, start=1):
                    rs = tuple(pod_rows.get(rep, ()))
                    row = rowset_memo.get(rs)
                    if row is None:
                        row = np.ones((ct.n_real,), dtype=np.bool_)
                        for cid in rs:
                            row = row & row_store[cid]
                        rowset_memo[rs] = row
                    mask_np[c, : ct.n_real] = row
                packed = np.packbits(mask_np, axis=1)
                dev_mask = self._put(packed, "pn")
                plane_bytes += packed.nbytes
            else:
                # Clean mask: all-true for every real class — depends
                # only on (plane rows, class count, node count), so one
                # cached upload serves every such chunk of the shape.
                mkey = ("cls", crows, n_cls, N, ct.n_real)
                dev_mask = self._dev_base_mask.get(mkey)
                if dev_mask is None:
                    mask_np = np.zeros((crows, N), dtype=np.bool_)
                    mask_np[1: n_cls + 1, : ct.n_real] = True
                    packed = np.packbits(mask_np, axis=1)
                    dev_mask = self._dev_base_mask[mkey] = \
                        self._put(packed, "pn")
                    plane_bytes += packed.nbytes
            if scores_dirty:
                scores_np = np.zeros((crows, N), dtype=np.float32)
                for c, parts in enumerate(class_parts, start=1):
                    for sid in parts:
                        scores_np[c, : ct.n_real] += score_store[sid]
                wire_scores = compress_score_wire(scores_np)
                dev_scores = self._put(wire_scores, "pn")
                plane_bytes += wire_scores.nbytes
            else:
                dev_scores = self._dev_zero_scores.get((crows, N))
                if dev_scores is None:
                    dev_scores = self._dev_zero_scores[(crows, N)] = \
                        self._put(np.zeros((crows, N), dtype=np.float16),
                                  "pn")
                    plane_bytes += crows * N * 2
        else:
            # Per-pod fallback (kill switch / class overflow): C == P,
            # identity index — the planes the pre-class format shipped.
            crows = P
            cls_np = None  # identity: served from the _dev_arange cache
            exc_np = np.full((P,), -1, dtype=np.int32)
            pack_np = np.concatenate(
                [batch.req_q, batch.req_nz_q,
                 batch.untol_filter.astype(np.int32),
                 batch.untol_prefer.astype(np.int32)], axis=1)
            if cap and self.metrics is not None:
                # Genuine class overflow (not the kill switch): counted
                # per pod, like the other degradation kinds.
                self.metrics.class_split_fallbacks.inc(batch.p_real)
            if mask_dirty:
                mask_np = np.zeros((P, N), dtype=np.bool_)
                mask_np[: batch.p_real, : ct.n_real] = True
                for i, lst in pod_rows.items():
                    for cid in lst:
                        mask_np[i, : ct.n_real] &= row_store[cid]
                for i, pin in pod_pin.items():
                    keep = mask_np[i, pin]
                    mask_np[i, :] = False
                    mask_np[i, pin] = keep
                for i in infeasible:
                    mask_np[i, :] = False
                packed = np.packbits(mask_np, axis=1)
                dev_mask = self._put(packed, "pn")
                plane_bytes += packed.nbytes
            else:
                mkey = ("pod", P, N, batch.p_real, ct.n_real)
                dev_mask = self._dev_base_mask.get(mkey)
                if dev_mask is None:
                    mask_np = np.zeros((P, N), dtype=np.bool_)
                    mask_np[: batch.p_real, : ct.n_real] = True
                    packed = np.packbits(mask_np, axis=1)
                    dev_mask = self._dev_base_mask[mkey] = \
                        self._put(packed, "pn")
                    plane_bytes += packed.nbytes
            if scores_dirty:
                scores_np = np.zeros((P, N), dtype=np.float32)
                for i, parts in pod_parts.items():
                    for sid in parts:
                        scores_np[i, : ct.n_real] += score_store[sid]
                wire_scores = compress_score_wire(scores_np)
                dev_scores = self._put(wire_scores, "pn")
                plane_bytes += wire_scores.nbytes
            else:
                dev_scores = self._dev_zero_scores.get((P, N))
                if dev_scores is None:
                    dev_scores = self._dev_zero_scores[(P, N)] = \
                        self._put(np.zeros((P, N), dtype=np.float16), "pn")
                    plane_bytes += P * N * 2

        # The (P,) class index + exception vector + (C, ·) rep-row pack
        # ride every chunk (tiny); the identity index (per-pod fallback)
        # and the no-exception vector reuse one cached upload per width.
        if cls_np is None:
            cls_np = np.arange(P, dtype=np.int32)
            dev_cls = self._dev_arange.get(P)
            if dev_cls is None:
                dev_cls = self._dev_arange[P] = self._put(cls_np)
                plane_bytes += cls_np.nbytes
        else:
            dev_cls = self._put(cls_np)
            plane_bytes += cls_np.nbytes
        dev_pack = self._put(pack_np)
        plane_bytes += pack_np.nbytes
        if pod_pin and class_reps is not None:
            dev_exc = self._put(exc_np)
            plane_bytes += exc_np.nbytes
        else:
            dev_exc = self._dev_no_exc.get(P)
            if dev_exc is None:
                dev_exc = self._dev_no_exc[P] = self._put(
                    np.full((P,), -1, dtype=np.int32))

        # Shortlist activation: the chunk-start prefilter reads the
        # class planes directly (O(C·N)), so the pruned solve runs for
        # EVERY class-mode chunk the tuner's width policy accepts —
        # heterogeneous score rows no longer defeat it (they are class
        # rows now). The per-pod fallback keeps the full N-wide scan: a
        # (P,N) prefilter would cost more than the pruning saves.
        shortlist_k = 0
        if class_reps is not None:
            shortlist_k = self._tuner.shortlist_k(P, ct.n_real)

        # Wavefront width: 0 = the KTPU_WAVEFRONT kill switch (the W=1
        # scan functions, structurally), else the tuner's policy W
        # (override-pinned or replay-feedback-narrowed). W is a static
        # arg of the fused program, so it is part of the chunk program
        # key like the shortlist width.
        wave_w = 0
        if flags.get("KTPU_WAVEFRONT"):
            wave_w = self._tuner.wave_width(P)

        # Block-index width: the two-pass block-sparse prefilter rides
        # the shortlist (it prunes the prefilter's own O(C·N) pass), so
        # it activates only with it — the tuner's structural large-N
        # row plus the KTPU_BLOCK_INDEX/KTPU_BLOCK_WIDTH knobs. 0 is
        # the full-width prefilter, structurally (a static arg of the
        # fused program, part of the chunk program key like W and K).
        block_w = self._tuner.block_width(
            ct.n_pad, ct.n_real, shortlist_k) if shortlist_k else 0

        # Multi-start orders: identity first (ties → oracle-equivalent),
        # then size-desc / size-asc / seeded shuffles. Permutations are
        # PRIORITY-BLOCK-STABLE: pods only move within runs of equal
        # priority (queue order is priority order — reordering across
        # blocks could strand a high-priority pod behind a bulkier
        # low-priority order, a starvation the reference can't exhibit).
        # Padding stays in place; its mask is all-False anyway.
        K = self.multistart
        pr = batch.p_real
        if K > 1 and pr > 1:
            sizes = batch.req_q[:pr].sum(axis=1)
            prios = np.fromiter((p.priority for p in pods), dtype=np.int64,
                                count=pr)
            perms_key = (K, P, pr, sizes.tobytes(), prios.tobytes())
        else:
            perms_key = (K, P)
        dev_perms = self._dev_perms_cache.get(perms_key)
        if dev_perms is None:
            perms = np.tile(np.arange(P, dtype=np.int32), (K, 1))
            if K > 1 and pr > 1:
                blocks = []
                lo = 0
                for hi in range(1, pr + 1):
                    if hi == pr or prios[hi] != prios[lo]:
                        blocks.append((lo, hi))
                        lo = hi
                rng = np.random.default_rng(0xC0FFEE + pr)

                def fill(k, order_of):
                    for lo, hi in blocks:
                        perms[k, lo:hi] = lo + order_of(lo, hi)
                if K > 1:
                    fill(1, lambda lo, hi: np.argsort(
                        -sizes[lo:hi], kind="stable").astype(np.int32))
                if K > 2:
                    fill(2, lambda lo, hi: np.argsort(
                        sizes[lo:hi], kind="stable").astype(np.int32))
                for k in range(3, K):
                    fill(k, lambda lo, hi: rng.permutation(
                        hi - lo).astype(np.int32))
            dev_perms = self._put(perms)
            if len(self._dev_perms_cache) > 64:
                self._dev_perms_cache.clear()
            self._dev_perms_cache[perms_key] = dev_perms

        # Gang membership (Coscheduling): all-or-nothing inside the solve.
        # The quota is what the gang still NEEDS: minMember minus members
        # already assembled (bound or parked at Permit) — a fully-assembled
        # gang's stragglers place individually, like the Permit path.
        gang_onehot = None
        gang_required = None
        cosched = next(
            (pl for pl in fwk.plugins if pl.NAME == "Coscheduling"), None)
        if cosched is not None and getattr(cosched, "pg_informer", None) \
                is not None:
            groups: dict[str, list[int]] = {}
            for i, pi in enumerate(pods):
                gk = cosched.group_key(pi)
                if gk:
                    groups.setdefault(gk, []).append(i)
            if groups:
                gang_onehot = np.zeros((P, _GANG_PAD), dtype=np.float32)
                gang_required = np.zeros((_GANG_PAD,), dtype=np.float32)
                if len(groups) > _GANG_PAD:
                    # Overflow gangs lose in-solver all-or-nothing and
                    # fall back to the Permit barrier alone — weaker
                    # atomicity under contention; observable, not silent.
                    logger.warning(
                        "%d gangs in chunk exceed solver capacity %d; "
                        "%d gangs degrade to Permit-barrier-only "
                        "atomicity", len(groups), _GANG_PAD,
                        len(groups) - _GANG_PAD)
                    if self.metrics is not None:
                        self.metrics.backend_degradations.inc(
                            len(groups) - _GANG_PAD, kind="gang_overflow")
                for g, (gk, idxs) in enumerate(groups.items()):
                    if g >= _GANG_PAD:
                        break  # overflow gangs: Permit barrier only
                    pg = cosched._pod_group(gk)
                    mm = int(((pg or {}).get("spec") or {})
                             .get("minMember", 1))
                    assembled = len(cosched._bound.get(gk) or ()) + \
                        len(cosched._waiting.get(gk) or ())
                    for i in idxs:
                        gang_onehot[i, g] = 1.0
                    gang_required[g] = min(max(mm - assembled, 0), len(idxs))

        self._tuner.observe_chunk(dirty)
        if self.metrics is not None:
            self.metrics.plane_classes.set(
                len(class_reps) if class_reps is not None else batch.p_real)
            if plane_bytes:
                self.metrics.plane_bytes.inc(plane_bytes)
            self.metrics.prep_duration.observe(
                time.perf_counter() - prep_t0)
        return {
            "pods": pods, "batch": batch,
            "dev_mask": dev_mask, "dev_scores": dev_scores,
            "dev_cls": dev_cls, "dev_exc": dev_exc, "dev_pack": dev_pack,
            "cls_np": cls_np,
            "host_filter_fail": host_filter_fail,
            "unknown_res": unknown_res, "stateful_pods": stateful_pods,
            "spread_active_idx": spread_active_idx,
            "sp_applies": sp_applies, "sp_contrib": sp_contrib,
            "chunk_idx": chunk_idx,
            "dev_perms": dev_perms, "gang_onehot": gang_onehot,
            "gang_required": gang_required,
            "shortlist_k": shortlist_k,
            "wave_w": wave_w,
            "block_w": block_w,
            "class_mode": class_reps is not None,
            "scan_width": (shortlist_k + P) if shortlist_k else ct.n_real,
        }

    def _dispatch_chunk(self, prep: dict, ctx: "_AssignCtx") -> dict:
        """Dispatch the fused solve for one chunk; device used-state chains
        through self._dev_used without host sync. Bracketed with a
        StepTraceAnnotation (one profiler step per chunk) and, when
        tracing is on, a solver.dispatch span under the attempt."""
        tr = self.tracer
        if tr is not None and tr.enabled:
            with tr.span("solver.dispatch", chunk=prep.get("chunk_idx"),
                         pods=prep["batch"].p_real):
                return self._dispatch_chunk_inner(prep, ctx)
        return self._dispatch_chunk_inner(prep, ctx)

    def _dispatch_chunk_inner(self, prep: dict, ctx: "_AssignCtx") -> dict:
        if _STEP_ANNOTATION is not None:
            with _STEP_ANNOTATION("ktpu.solve",
                                  step_num=prep.get("chunk_idx", 0)):
                return self._dispatch_chunk_jit(prep, ctx)
        return self._dispatch_chunk_jit(prep, ctx)

    def ensure_static(self, ct: ClusterTensors) -> dict:
        """Device-resident node-static arrays (alloc, taints), refreshed
        only when the static fingerprint moves — shared by the chunk
        dispatch and the serving tier's single-pod fast path."""
        if self._dev_static_fp != ct._static_fp or \
                self._dev_static.get("alloc_shape") != ct.alloc_q.shape:
            self._dev_static = {
                "alloc_q": self._put(ct.alloc_q, "nodes_mat"),
                "alloc_pods": self._put(ct.alloc_pods, "nodes_vec"),
                "taint_f": self._put(ct.taint_filter_mat, "nodes_mat"),
                "taint_p": self._put(ct.taint_prefer_mat, "nodes_mat"),
                "alloc_shape": ct.alloc_q.shape,
            }
            self._dev_static_fp = ct._static_fp
        return self._dev_static

    def _dispatch_chunk_jit(self, prep: dict, ctx: "_AssignCtx") -> dict:
        ct, p = ctx.ct, ctx.params
        batch = prep["batch"]
        self.ensure_static(ct)

        sp = ctx.spread
        # The spread scan must run for any chunk whose pods contribute to
        # the table's counts (a non-spread pod matching a template's
        # selector still moves domain counts) — UNLESS no later chunk has
        # gated pods, in which case the counts can't influence anything
        # and the chunk keeps the multistart solver.
        use_spread = bool(
            sp is not None and sp.get("cons")
            and prep["sp_contrib"] is not None
            and (prep["spread_active_idx"]
                 or (prep["sp_contrib"].any()
                     and prep["chunk_idx"] < ctx.spread_last_gated)))
        prep["spread_used"] = use_spread
        # spread∩shortlist keeps its W=1 scan (see _mask_solve_update);
        # pinning the static arg to 0 here avoids minting per-W program
        # variants that would all route to the same W=1 body.
        if use_spread and prep["shortlist_k"]:
            prep["wave_w"] = 0
        # Solve-mode policy row (r20): greedy pins the r18 call graph;
        # optimal routes the Sinkhorn plan + rounding. Transport plans
        # tie across equally-attractive columns, so under optimal mode
        # wave speculation would conflict-replay nearly every wave and
        # the shortlist prefilter would re-derive what the plan already
        # encodes — the rounding keeps the W=1 kill-switch scan shape
        # (assignments are bit-identical at any W regardless; the
        # differential suite pins it) and the full-row scan.
        solve_mode, opt_fallback = self._tuner.solve_mode(
            batch.p_real,
            has_gang=prep["gang_onehot"] is not None,
            spread=use_spread,
            class_mode=prep.get("class_mode", False))
        if solve_mode == "optimal":
            prep["shortlist_k"] = 0
            prep["wave_w"] = 0
        # The block index rides the shortlist; any route that zeroed K
        # (optimal mode) zeroes the block width with it.
        if not prep["shortlist_k"]:
            prep["block_w"] = 0
        prep["solve_mode"] = solve_mode
        prep["optimal_fallback"] = opt_fallback
        # Pallas routing (the KTPU_PALLAS policy row + structural shape
        # gate): the kernel fuses only the plain greedy wave branch, and
        # holds the whole (C,N) planes + (W,N) evaluation resident per
        # grid step — a chunk above the kernel's working-set ceiling
        # keeps the scan, counted under reason="shape".
        pallas_mode, pallas_fall = self._tuner.pallas_mode(
            prep["wave_w"], prep["shortlist_k"], use_spread, solve_mode)
        if pallas_mode != "off":
            shape_reason = pallas_kernel.unsupported_reason(
                ct.n_pad, prep["dev_mask"].shape[0],
                ct.alloc_q.shape[1], prep["wave_w"])
            if shape_reason is not None:
                pallas_mode, pallas_fall = "off", shape_reason
        prep["pallas_mode"] = pallas_mode
        prep["pallas_fallback"] = pallas_fall
        if use_spread:
            sp_args = (sp["dev_dom"], sp["dev_cid"], sp["dev_counts"],
                       sp["dev_skew"], sp["dev_min_ok"], sp["dev_haskey"],
                       self._put(prep["sp_applies"]),
                       self._put(prep["sp_contrib"]))
        else:
            sp_args = self._spread_dummies(ct.n_pad, batch.req_q.shape[0])
        assign_d, used_pack2, fit0_d, taint_ok_d, dom_counts2 = \
            _solve_program()(
                self._dev_static["alloc_q"], self._dev_used,
                self._dev_static["alloc_pods"], prep["dev_pack"],
                prep["dev_cls"], prep["dev_exc"],
                self._dev_static["taint_f"], self._dev_static["taint_p"],
                prep["dev_mask"], prep["dev_scores"],
                p["fit_col_w"], p["bal_col_mask"], p["shape_u"], p["shape_s"],
                p["w_fit"], p["w_bal"], p["w_taint"], p["taint_filter_on"],
                *sp_args,
                prep["dev_perms"], *self._gang_args(prep, batch),
                np.int32(max(1, flags.get("KTPU_SINKHORN_ITERS"))),
                np.float32(flags.get("KTPU_SINKHORN_TEMP")),
                np.int32(ct.n_real),
                p["strategy"], use_spread, prep["shortlist_k"],
                prep["wave_w"], solve_mode, pallas_mode,
                prep["block_w"],
            )
        self._dev_used = used_pack2
        if use_spread:
            sp["dev_counts"] = dom_counts2
        # Start the device→host copy now; the fetch in _finalize_chunk then
        # overlaps the next chunk's solve (and, in assign_async, bind tasks).
        try:
            assign_d.copy_to_host_async()
        except AttributeError:
            pass
        prep["assign_d"] = assign_d
        prep["fit0_d"] = fit0_d
        prep["taint_ok_d"] = taint_ok_d
        return prep

    def _finalize_chunk(self, run: dict, assign_np: np.ndarray,
                        ctx: "_AssignCtx") -> None:
        pods, batch = run["pods"], run["batch"]
        assign = assign_np[: batch.p_real]

        # Solve-side observability: the fused program appends the chunk's
        # [shortlist fallbacks, wave commits, wave replays, blocks
        # scanned, blocks pruned] tail to the assign vector (one fetch).
        # The tuner's hit-rate feedback widens K when fallbacks climb and
        # narrows W when replays climb. A poisoned multistart chunk
        # reports the PADDED width — clamp to real pods so rates never
        # exceed 100%. The block counters are (class, block) pair counts,
        # not pod counts — no clamp.
        nfall = min(int(assign_np[-5]), batch.p_real)
        wave_com = min(int(assign_np[-4]), batch.p_real)
        wave_rep = min(int(assign_np[-3]), batch.p_real)
        blk_scanned = int(assign_np[-2])
        blk_pruned = int(assign_np[-1])
        if run.get("shortlist_k"):
            self._tuner.observe_solve(batch.p_real, nfall)
        if run.get("wave_w", 0) > 1:
            self._tuner.observe_wave(wave_com, wave_rep)
        if self.metrics is not None:
            self.metrics.solver_scan_width.set(run["scan_width"])
            self.metrics.solver_wave_width.set(max(1, run.get("wave_w", 0)))
            if wave_com:
                self.metrics.solver_wave_commits.inc(wave_com)
            if wave_rep:
                self.metrics.solver_wave_replays.inc(wave_rep)
            if run.get("shortlist_k"):
                self.metrics.solver_shortlist_pods.inc(batch.p_real)
                if nfall:
                    self.metrics.solver_shortlist_fallbacks.inc(nfall)
            # Block-prefilter accounting: scanned counts every (class,
            # block) pair the bound scan walked for chunks routed with
            # block_w > 0; pruned counts the pairs the exactness
            # predicate proved losers (0 for a chunk whose predicate
            # fell back full-width in-program). block_w == 0 chunks
            # report neither — the zero-counter structural degrade the
            # smoke test pins.
            if run.get("block_w"):
                if blk_scanned:
                    self.metrics.solver_blocks_scanned.inc(blk_scanned)
                if blk_pruned:
                    self.metrics.solver_blocks_pruned.inc(blk_pruned)
            # Optimal-mode accounting (r20): solves count CHUNKS routed
            # through the Sinkhorn plan; fallbacks count chunks the
            # policy WANTED optimal but structure (spread / per-pod
            # planes) degraded to greedy. The iterations gauge records
            # what the latest optimal solve actually ran — fori_loop
            # runs the flag's count exactly.
            if run.get("solve_mode") == "optimal":
                self.metrics.solver_optimal_solves.inc()
                self.metrics.solver_sinkhorn_iterations.set(
                    max(1, flags.get("KTPU_SINKHORN_ITERS")))
            elif run.get("optimal_fallback"):
                self.metrics.solver_optimal_fallbacks.inc()
            # Pallas accounting: solves count chunks whose wave solve
            # ran the fused kernel; fallbacks count chunks the flag
            # wanted on the kernel but that kept the scan, labeled by
            # why. Off-by-policy (kill switch, auto-on-CPU) records
            # neither — the zero-counter degrade the smoke test pins.
            if run.get("pallas_mode") not in (None, "off"):
                self.metrics.solver_pallas_solves.inc()
            elif run.get("pallas_fallback"):
                self.metrics.solver_pallas_fallbacks.inc(
                    reason=run["pallas_fallback"])
            if ctx.ct.prep_shards > 1:
                # Sharded-path solve accounting: the fused program spans
                # every shard, so the wall is labeled with the shard
                # COUNT; the top-level argmax merges once per pod step.
                self.metrics.shard_solve_seconds.inc(
                    run.get("solve_wall_s", 0.0),
                    shards=str(ctx.ct.prep_shards))
                self.metrics.cross_shard_reductions.inc(batch.p_real)

        # Host verify + working-state accumulation (hard part #1). The
        # verify context is shared across chunks, so later chunks are
        # checked against earlier chunks' accepted placements. Scan-trusted
        # spread pods skip the host re-check — UNLESS the template was
        # poisoned after this chunk was dispatched (a mixed chunk appeared):
        # then they re-enter the stateful set, restoring exactness.
        stateful = run["stateful_pods"]
        # (Templates are fixed at table-build time from ALL chunks, so a
        # later chunk can no longer invalidate scan-trusted placements.)
        rejects = self._verify(pods, assign, ctx, stateful)

        # Fold verify rejections back into the device-chained used-state so
        # later chunks don't see the rejected pods' resources as consumed.
        # Chunks already in flight were dispatched against the inflated
        # state — conservative only (a reject can make a later in-flight pod
        # look unschedulable; it just requeues). Adds commute, so
        # subtracting from the CURRENT chained state is exact for every
        # chunk dispatched after this point.
        if rejects:
            used = np.asarray(self._dev_used).copy()
            r = batch.req_q.shape[1]
            for i, idx in rejects:
                used[idx, :r] -= batch.req_q[i]
                used[idx, r:2 * r] -= batch.req_nz_q[i]
                used[idx, 2 * r] -= 1
            self._dev_used = self._put(used, "nodes_mat")
            # Rejected pods that CONTRIBUTED to spread counts fold out of
            # the chained domain counts (adds commute, same argument as
            # the used-state) — masked per constraint the pod matches.
            sp = ctx.spread
            contrib = run.get("sp_contrib")
            if sp is not None and run.get("spread_used") \
                    and contrib is not None:
                cid = sp["cid_onehot_host"]
                adj = None
                for i, idx in rejects:
                    row = contrib[i]
                    if not row.any():
                        continue
                    if adj is None:
                        adj = np.zeros(
                            sp["dom_onehot_host"].shape[1], np.float32)
                    adj -= sp["dom_onehot_host"][idx] * (cid @ row)
                if adj is not None:
                    sp["dev_counts"] = self._put(
                        np.asarray(sp["dev_counts"]) + adj)

        # Lazy per-plugin diagnostics for unassigned pods.
        need_diag = [i for i, pi in enumerate(pods)
                     if ctx.assignments.get(pi.key) is None
                     and pi.key not in ctx.diagnostics]
        if need_diag:
            fit0 = run.get("fit0_np")
            if fit0 is None:
                fit0 = np.asarray(run["fit0_d"])
            taint_ok = run.get("taint_ok_np")
            if taint_ok is None:
                taint_ok = np.asarray(run["taint_ok_d"])
            self._build_diagnostics(
                need_diag, pods, ctx.ct, batch, fit0, taint_ok,
                run["cls_np"],
                run["host_filter_fail"], ctx.params["filter_names"],
                ctx.diagnostics, run["unknown_res"])

    @staticmethod
    def _fetch_diag_planes(run: dict) -> None:
        """Worker-thread fetch of the diagnostic unsat planes: start both
        device→host copies before blocking so the relay trips overlap."""
        check_dispatch_seam("backend.fetch_diag_planes")
        for k in ("fit0_d", "taint_ok_d"):
            a = run.get(k)
            if a is not None and hasattr(a, "copy_to_host_async"):
                try:
                    a.copy_to_host_async()
                except Exception:
                    pass
        run["fit0_np"] = np.asarray(run["fit0_d"])
        run["taint_ok_np"] = np.asarray(run["taint_ok_d"])

    # -- verification --------------------------------------------------------

    def _verify(self, pods, assign, ctx: "_AssignCtx", stateful_pods
                ) -> list[tuple[int, int]]:
        """Post-solve verification (hard part #1: solve → verify → requeue).
        Returns [(chunk index, node index)] for solver assignments the host
        rejected, so the caller can fold them out of the device used-state.

        The batch-start masks are EXACT w.r.t. the snapshot (host rows use
        the host plugins; the tensorized affinity rows are differential-
        tested), so verification only has to account for the *delta* —
        pods placed earlier in this same batch:

        - resources: exact integer re-check against the working node
        - inter-pod affinity (incl. symmetry both ways): checked against
          the delta placements only — O(|delta| × terms), not O(cluster)
        - host ports: against the working node's accumulated ports
        - anything else stateful (PodTopologySpread & friends in
          `stateful_pods`): full host re-check against a working snapshot

        The working snapshot / delta list live on ctx and are SHARED across
        chunks of one assign() call, so chunk k+1 is verified against chunk
        k's accepted placements.
        """
        snapshot, fwk, ct = ctx.snapshot, ctx.fwk, ctx.ct
        compiler = getattr(self, "_affinity", None)
        assignments = ctx.assignments
        diagnostics = ctx.diagnostics
        working = ctx.working
        delta = ctx.delta
        delta_has_terms = ctx.delta_has_terms
        sel_cache = ctx.sel_cache

        def node_for(idx: int) -> NodeInfo:
            name = ct.node_names[idx]
            ni = working.get(name)
            if ni is None:
                ni = snapshot.get(name).clone()
                working[name] = ni
                # Patch the shared working snapshot in place (clones mutate
                # in place afterwards, so list entries stay current).
                w = ctx.wsnap
                if w is not None:
                    old = w._by_name.get(name)
                    w.nodes[idx] = ni
                    w._by_name[name] = ni
                    for lst in (w.have_pods_with_affinity,
                                w.have_pods_with_required_anti_affinity):
                        for k, entry in enumerate(lst):
                            if entry is old:
                                lst[k] = ni
                                break
            return ni

        full_check_batch = bool(stateful_pods)
        contention = Status.unschedulable(
            "node(s) exhausted by earlier pods in the batch"
        ).with_plugin("NodeResourcesFit")
        affinity_conflict = Status.unschedulable(
            "node(s) conflicted with pod affinity/anti-affinity of pods "
            "placed earlier in the batch").with_plugin("InterPodAffinity")
        port_conflict = Status.unschedulable(
            "node(s) didn't have free ports for the requested pod ports"
        ).with_plugin("NodePorts")

        rejects: list[tuple[int, int]] = []
        for i, pi in enumerate(pods):
            idx = int(assign[i])
            if idx < 0:
                assignments[pi.key] = None
                continue
            ni = node_for(idx)
            if insufficient_resources(pi, ni):
                assignments[pi.key] = None
                diagnostics[pi.key] = {ni.name: contention}
                rejects.append((i, idx))
                continue
            if pi.host_ports and any(
                    (ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip)
                    and proto == uproto and port == uport
                    for (ip, proto, port) in pi.host_ports
                    for (uip, uproto, uport) in ni.used_ports):
                assignments[pi.key] = None
                diagnostics[pi.key] = {ni.name: port_conflict}
                rejects.append((i, idx))
                continue
            if full_check_batch:
                # Non-IPA stateful plugins in play → full host re-check.
                # The working snapshot is built ONCE per assign() and kept
                # current: working clones mutate in place, and node_for
                # patches in new clones — rebuilding a Snapshot per pod was
                # O(N) per pod (the spread/NRT families' top host cost).
                wsnap = ctx.wsnap
                if wsnap is None:
                    wsnap = ctx.wsnap = Snapshot(
                        [working.get(n.name, n) for n in snapshot.nodes],
                        snapshot.generation)
                state = CycleState()
                st = fwk.run_pre_filter(state, pi, wsnap)
                if st.is_success():
                    st = fwk.run_filters(state, pi, working.get(ni.name, ni))
                if not st.is_success():
                    assignments[pi.key] = None
                    diagnostics[pi.key] = {ni.name: st}
                    rejects.append((i, idx))
                    continue
            elif delta_has_terms or pi.has_affinity_constraints:
                if not _delta_affinity_ok(pi, ni, delta, ct, compiler,
                                          sel_cache, ctx.delta_idx):
                    assignments[pi.key] = None
                    diagnostics[pi.key] = {ni.name: affinity_conflict}
                    rejects.append((i, idx))
                    continue
            assignments[pi.key] = ni.name
            ni.add_pod(pi)
            # Keep the shared working snapshot's affinity indexes current
            # (Snapshot.__init__ derives them; add_pod bypasses that).
            if ctx.wsnap is not None:
                if pi.has_affinity_constraints and \
                        ni not in ctx.wsnap.have_pods_with_affinity:
                    ctx.wsnap.have_pods_with_affinity.append(ni)
                if pi.has_required_anti_affinity and ni not in \
                        ctx.wsnap.have_pods_with_required_anti_affinity:
                    ctx.wsnap.have_pods_with_required_anti_affinity.append(ni)
            delta.append((pi, ni.labels))
            ctx.delta_idx.add(pi, ni.labels)
            if pi.required_affinity_terms or pi.required_anti_affinity_terms:
                delta_has_terms = True
        ctx.delta_has_terms = delta_has_terms
        return rejects

    # -- explainability ------------------------------------------------------

    def _build_diagnostics(self, idxs, pods, ct, batch, fit0, taint_ok,
                           cls_np, host_filter_fail, filter_names,
                           diagnostics, unknown_res):
        """Per-node, per-plugin failure reasons from the preserved unsat
        masks — feeds FitError's "0/N nodes are available: ..." summary.

        fit0/taint_ok are CLASS-level (C, N) planes; each pod reads its
        class row through cls_np (exact — the class shares the pod's
        request/toleration rows by construction). Host plugin failures
        come from the per-pod ok-row dicts the prep recorded (shared row
        objects, no plane)."""
        taint_st = Status.unschedulable(
            "node(s) had untolerated taint", resolvable=False
        ).with_plugin("TaintToleration")
        contention = Status.unschedulable(
            "node(s) exhausted by earlier pods in the batch"
        ).with_plugin("NodeResourcesFit")
        host_statuses = {
            name: Status.unschedulable(_HOST_REASONS.get(name, "node(s) filtered"),
                                       resolvable=name not in _UNRESOLVABLE)
            .with_plugin(name)
            for name in host_filter_fail
        }
        n_real = ct.n_real
        names = list(ct.node_names[:n_real])
        names_hash = hash(tuple(names))
        R = ct.alloc_q.shape[1]
        weights = 1 << np.arange(R, dtype=np.int64)
        too_many = (ct.used_pods + 1 > ct.alloc_pods)[:n_real]
        #: insufficiency bitmask (bit R = pod count) -> interned Status;
        #: shared across the whole wave — a dense failure wave repeats the
        #: same handful of shortage shapes across thousands of pods.
        res_status_cache: dict[int, Status] = {}
        taint_on = "TaintToleration" in filter_names
        for i in idxs:
            pi = pods[i]
            if i in unknown_res:
                st = Status.unschedulable(
                    "Insufficient " + ", ".join(
                        r for r in pi.requests if r not in ct.r_index),
                    resolvable=True).with_plugin("NodeResourcesFit")
                dm = DiagMap((n, st) for n in ct.node_names)
                dm.reason_counts = {r: len(ct.node_names)
                                    for r in st.reasons}
                dm.plugins = {st.plugin}
                dm.resolvable = True
                dm.banned_mask = np.zeros((n_real,), dtype=bool)
                dm.banned_nodes_hash = names_hash
                diagnostics[pi.key] = dm
                continue
            # One interned-Status object row per pod instead of a Python
            # loop per node — the per-node next()/nonzero() chain was the
            # top host cost of dense failure (preemption) waves.
            statuses = np.empty((n_real,), dtype=object)
            assigned = np.zeros((n_real,), dtype=bool)
            banned = np.zeros((n_real,), dtype=bool)
            agg: list[tuple[Status, int]] = []
            ci = int(cls_np[i])
            if taint_on:
                m = ~taint_ok[ci, :n_real]
                statuses[m] = taint_st
                assigned |= m
                banned |= m
                c = int(m.sum())
                if c:
                    agg.append((taint_st, c))
            for pname, okmap in host_filter_fail.items():
                ok_row = okmap.get(i)
                if ok_row is None:
                    continue
                m = ~ok_row[:n_real] & ~assigned
                statuses[m] = host_statuses[pname]
                assigned |= m
                if host_statuses[pname].code == \
                        UNSCHEDULABLE_AND_UNRESOLVABLE:
                    banned |= m
                c = int(m.sum())
                if c:
                    agg.append((host_statuses[pname], c))
            short = (ct.used_q + batch.req_q[i][None, :]
                     > ct.alloc_q)[:n_real]
            bits = (short @ weights) + (too_many.astype(np.int64) << R)
            bits[assigned] = -1
            for b in np.unique(bits):
                if b < 0:
                    continue
                m = bits == b
                if b == 0:
                    # Feasible at batch start but taken by earlier pods.
                    statuses[m] = contention
                    agg.append((contention, int(m.sum())))
                    continue
                st = res_status_cache.get(int(b))
                if st is None:
                    msgs = [f"Insufficient {ct.resources[r]}"
                            for r in range(R) if b & (1 << r)]
                    if b >> R:
                        msgs = ["Too many pods"] + msgs
                    st = Status.unschedulable(*msgs).with_plugin(
                        "NodeResourcesFit")
                    res_status_cache[int(b)] = st
                statuses[m] = st
                agg.append((st, int(m.sum())))
            dm = DiagMap(zip(names, statuses))
            for st, c in agg:
                for r in st.reasons:
                    dm.reason_counts[r] = dm.reason_counts.get(r, 0) + c
                if st.plugin:
                    dm.plugins.add(st.plugin)
                if st.code != UNSCHEDULABLE_AND_UNRESOLVABLE:
                    dm.resolvable = True
            dm.banned_mask = banned
            dm.banned_nodes_hash = names_hash
            diagnostics[pi.key] = dm


class DiagMap(dict):
    """Per-pod {node: Status} map with the two aggregates every consumer
    recomputes by iterating all N entries — FitError's reason counts and
    handleSchedulingFailure's plugin set — precomputed from the vectorized
    masks. At wave scale (1k failed pods × 5k nodes) the per-pod O(N)
    re-iterations were a measured top-3 host cost."""

    __slots__ = ("reason_counts", "plugins", "resolvable", "banned_mask",
                 "banned_nodes_hash")

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.reason_counts: dict[str, int] = {}
        self.plugins: set[str] = set()
        #: any node failed with a preemption-resolvable status
        self.resolvable: bool = False
        #: (n_real,) bool — nodes rejected UnschedulableAndUnresolvable
        #: (snapshot node order); preemption's banned set without an O(N)
        #: per-pod re-scan.
        self.banned_mask = None
        #: hash of the node-name tuple the mask indexes — consumers run
        #: against a LATER snapshot whose node set may have churned; a
        #: bare length check would let bans land on the wrong nodes.
        self.banned_nodes_hash = 0


class _AssignCtx:
    """Per-assign()-call state: the chunk list, per-framework device params,
    accumulated results, and the cross-chunk verify context."""

    __slots__ = ("snapshot", "fwk", "ct", "chunks", "params",
                 "assignments", "diagnostics",
                 "working", "delta", "delta_has_terms", "sel_cache",
                 "delta_idx", "wsnap", "spread", "spread_poisoned",
                 "spread_last_gated", "chunk_seq", "class_pad")


def _cached_matcher(term: dict, owner_ns: str, sel_cache: dict,
                    resolver=None):
    """Compiled (namespace-set, Selector) per unique term — the delta loop
    is O(batch²) pairs, so per-pair selector re-parsing would dominate.
    The namespace set may be the ALL_NAMESPACES wildcard; membership goes
    through labels.ns_contains."""
    key = (id(term), owner_ns)
    got = sel_cache.get(key)
    if got is None:
        from kubernetes_tpu.api.labels import from_label_selector
        from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
            resolve_term_namespaces,
        )
        nses = frozenset(resolve_term_namespaces(term, owner_ns, resolver))
        got = sel_cache[key] = (nses, from_label_selector(
            term.get("labelSelector")))
    return got


def _term_sig(term: dict, owner_ns: str, sel_cache: dict) -> tuple:
    """CONTENT-keyed term signature: pods stamped from one template carry
    equal-but-distinct term dicts, so id()-keyed indexes would grow one
    entry per pod and make delta maintenance O(batch) again."""
    key = ("sig", id(term), owner_ns)
    sig = sel_cache.get(key)
    if sig is None:
        sig = sel_cache[key] = (
            term.get("topologyKey", ""),
            tuple(sorted(term.get("namespaces") or [owner_ns])),
            repr(term.get("namespaceSelector")),
            repr(term.get("labelSelector")))
    return sig


class _DeltaAffinityIndex:
    """Incremental index over same-batch placements, answering the three
    delta-affinity questions in O(terms) per query instead of O(|delta|):

    - fwd[sig]: for a queried term, count of delta pods matching its
      selector, grouped by their NODE's topology value.
    - anti[sig]: for anti-affinity terms CARRIED BY delta pods, the same
      node-topology-value counts (symmetry: they forbid the querier).

    add() is O(registered signatures) per accepted pod — one per distinct
    template in the batch, not one per pod."""

    __slots__ = ("sel_cache", "fwd", "anti", "resolver")

    def __init__(self, sel_cache: dict, resolver=None):
        self.sel_cache = sel_cache
        self.resolver = resolver
        #: sig -> [nses, sel, tk, {node tk value -> count}, total]
        self.fwd: dict[tuple, list] = {}
        self.anti: dict[tuple, list] = {}

    def register(self, term: dict, owner_ns: str, delta: list) -> list:
        sig = _term_sig(term, owner_ns, self.sel_cache)
        e = self.fwd.get(sig)
        if e is None:
            nses, sel = _cached_matcher(term, owner_ns, self.sel_cache,
                                        self.resolver)
            tk = term.get("topologyKey", "")
            counts: dict = {}
            total = 0
            for d, labels_m in delta:  # back-fill placements so far
                if ns_contains(nses, d.namespace) and sel.matches(d.labels):
                    v = labels_m.get(tk)
                    counts[v] = counts.get(v, 0) + 1
                    total += 1
            e = self.fwd[sig] = [nses, sel, tk, counts, total]
        return e

    def add(self, d, node_labels: Mapping) -> None:
        for e in self.fwd.values():
            nses, sel, tk, counts, _total = e
            if ns_contains(nses, d.namespace) and sel.matches(d.labels):
                v = node_labels.get(tk)
                counts[v] = counts.get(v, 0) + 1
                e[4] += 1
        for term in d.required_anti_affinity_terms:
            sig = _term_sig(term, d.namespace, self.sel_cache)
            e = self.anti.get(sig)
            if e is None:
                nses, sel = _cached_matcher(
                    term, d.namespace, self.sel_cache, self.resolver)
                e = self.anti[sig] = [
                    nses, sel, term.get("topologyKey", ""), {}, 0]
            v = node_labels.get(e[2])
            e[3][v] = e[3].get(v, 0) + 1
            e[4] += 1


def _delta_affinity_ok(pi, ni, delta, ct, compiler, sel_cache,
                       delta_idx: "_DeltaAffinityIndex | None" = None) -> bool:
    """Inter-pod affinity check of `pi` on node `ni` against only the pods
    placed earlier in this batch (the batch-start tensor rows already cover
    the snapshot exactly). With a `_DeltaAffinityIndex` the three checks
    are O(terms) dictionary lookups; the list-walk fallback remains for
    callers without one."""
    labels_n = ni.labels

    if delta_idx is not None:
        # (1) pi's own anti-affinity vs delta placements.
        for term in pi.required_anti_affinity_terms:
            e = delta_idx.register(term, pi.namespace, delta)
            tv = labels_n.get(e[2])
            if tv is not None and e[3].get(tv):
                return False
        # (2) symmetry: delta pods' anti-affinity vs pi.
        for e in delta_idx.anti.values():
            nses, sel, tk, counts, _total = e
            tv = labels_n.get(tk)
            if tv is not None and counts.get(tv) \
                    and ns_contains(nses, pi.namespace) \
                    and sel.matches(pi.labels):
                return False
        # (3) pi's required affinity: delta pods can only ADD matches; the
        # one invalidation is the first-pod-in-group escape — once a
        # matching pod exists (placed in this batch), the term must be
        # satisfied in n's domain for real.
        for term in pi.required_affinity_terms:
            tk = term.get("topologyKey", "")
            tv = labels_n.get(tk)
            if tv is None:
                return False
            e = delta_idx.register(term, pi.namespace, delta)
            if e[3].get(tv):
                continue  # satisfied by a batch sibling in this domain
            if compiler is not None:
                per_node, _, total = compiler.affinity_term_presence(
                    term, pi.namespace)
                idx = ct.name_to_idx.get(ni.name)
                if idx is not None and per_node[idx] > 0:
                    continue  # satisfied by the snapshot already
                if total == 0 and e[4] == 0:
                    continue  # escape still valid: no match anywhere
                return False
            if e[4]:
                return False
        return True

    def matches(term, owner_ns, other) -> bool:
        nses, sel = _cached_matcher(term, owner_ns, sel_cache,
                                    getattr(compiler, "ns_resolver", None))
        return ns_contains(nses, other.namespace) and sel.matches(other.labels)

    # (1) pi's own anti-affinity vs delta placements.
    for term in pi.required_anti_affinity_terms:
        tk = term.get("topologyKey", "")
        tv = labels_n.get(tk)
        if tv is None:
            continue
        for d, labels_m in delta:
            if labels_m.get(tk) == tv and matches(term, pi.namespace, d):
                return False
    # (2) symmetry: delta pods' anti-affinity vs pi.
    for d, labels_m in delta:
        for term in d.required_anti_affinity_terms:
            tk = term.get("topologyKey", "")
            tv = labels_n.get(tk)
            if tv is not None and labels_m.get(tk) == tv \
                    and matches(term, d.namespace, pi):
                return False
    # (3) pi's required affinity: delta pods can only ADD matches; the one
    # invalidation is the first-pod-in-group escape — once a matching pod
    # exists (placed in this batch), the term must be satisfied in n's
    # domain for real.
    for term in pi.required_affinity_terms:
        tk = term.get("topologyKey", "")
        tv = labels_n.get(tk)
        if tv is None:
            return False
        delta_matches = [labels_m for d, labels_m in delta
                         if matches(term, pi.namespace, d)]
        if any(labels_m.get(tk) == tv for labels_m in delta_matches):
            continue  # satisfied by a batch sibling in this domain
        if compiler is not None:
            per_node, _, total = compiler.affinity_term_presence(
                term, pi.namespace)
            idx = ct.name_to_idx.get(ni.name)
            if idx is not None and per_node[idx] > 0:
                continue  # satisfied by the snapshot already
            if total == 0 and not delta_matches:
                continue  # escape still valid: no match exists anywhere
            return False
        # No compiler (shouldn't happen on this path) → be conservative.
        if delta_matches:
            return False
    return True


_HOST_REASONS = {
    "NodeAffinity": "node(s) didn't match Pod's node affinity/selector",
    "NodeName": "node didn't match the requested node name",
    "NodeUnschedulable": "node(s) were unschedulable",
    "NodePorts": "node(s) didn't have free ports for the requested pod ports",
    "InterPodAffinity": "node(s) didn't match pod affinity/anti-affinity rules",
    "PodTopologySpread": "node(s) didn't match pod topology spread constraints",
}
_UNRESOLVABLE = {"NodeAffinity", "NodeName", "NodeUnschedulable"}
