"""Deployment controller: Deployment → ReplicaSets (rolling update).

Parity target: pkg/controller/deployment/ (deployment_controller.go
`syncDeployment`, sync.go `getAllReplicaSetsAndSyncRevision`, rolling.go
`rolloutRolling`): one "new" RS per pod-template hash; rolling update scales
the new RS up and old RSes down within maxSurge/maxUnavailable bounds;
Recreate strategy scales old to 0 first.
"""

from __future__ import annotations

import hashlib
import json
import logging

from kubernetes_tpu.api.meta import namespaced_name, new_object, uid_of
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_ref
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)


def make_deployment(name: str, replicas: int, selector: dict,
                    pod_template: dict, namespace: str = "default",
                    strategy: dict | None = None) -> dict:
    return new_object(
        "Deployment", name, namespace,
        spec={"replicas": replicas, "selector": selector,
              "template": pod_template,
              "strategy": strategy or {"type": "RollingUpdate",
                                       "rollingUpdate": {"maxSurge": 1,
                                                         "maxUnavailable": 0}}},
        status={})


def pod_template_hash(template: dict) -> str:
    """Deterministic hash of the pod template (util/hash ComputeHash)."""
    js = json.dumps(template, sort_keys=True)
    return hashlib.sha1(js.encode()).hexdigest()[:10]


def _resolve_bound(value, total: int) -> int:
    """maxSurge/maxUnavailable: int or percentage string."""
    if isinstance(value, str) and value.endswith("%"):
        import math
        return math.ceil(total * int(value[:-1]) / 100)
    return int(value or 0)


class DeploymentController(Controller):
    NAME = "deployment"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.dep_informer = factory.informer("deployments")
        self.rs_informer = factory.informer("replicasets")
        self.watch_resource(factory, "deployments")

        import asyncio

        def rs_to_dep(obj):
            for ref in obj.get("metadata", {}).get("ownerReferences") or []:
                if ref.get("kind") == "Deployment" and ref.get("controller"):
                    ns = obj["metadata"].get("namespace", "default")
                    asyncio.ensure_future(
                        self.queue.add(f"{ns}/{ref['name']}"))

        from kubernetes_tpu.client import ResourceEventHandler
        self.rs_informer.add_event_handler(ResourceEventHandler(
            on_add=rs_to_dep, on_update=lambda o, n: rs_to_dep(n),
            on_delete=rs_to_dep))

    async def resync_keys(self):
        return [namespaced_name(d) for d in self.dep_informer.indexer.list()]

    def _owned_rs(self, dep: dict) -> list[dict]:
        dep_uid = uid_of(dep)
        out = []
        for rs in self.rs_informer.indexer.list():
            for ref in rs.get("metadata", {}).get("ownerReferences") or []:
                if ref.get("kind") == "Deployment" and (
                        not ref.get("uid") or not dep_uid
                        or ref["uid"] == dep_uid):
                    if ref.get("name") == dep["metadata"]["name"]:
                        out.append(rs)
        return out

    async def sync(self, key: str) -> None:
        dep = self.dep_informer.indexer.get(key)
        if dep is None:
            return
        spec = dep["spec"]
        replicas = int(spec.get("replicas", 0))
        template = spec.get("template") or {}
        thash = pod_template_hash(template)
        ns = dep["metadata"].get("namespace", "default")
        name = dep["metadata"]["name"]

        owned = self._owned_rs(dep)
        new_rs = next((rs for rs in owned
                       if rs["metadata"].get("labels", {})
                       .get("pod-template-hash") == thash), None)
        old_rses = [rs for rs in owned if rs is not new_rs]

        if new_rs is None:
            # Create the new-revision RS with the hash folded into the
            # selector + template labels (sync.go getNewReplicaSet).
            sel = {"matchLabels": {
                **(spec.get("selector") or {}).get("matchLabels", {}),
                "pod-template-hash": thash}}
            tmpl = json.loads(json.dumps(template))  # deep copy
            tmpl.setdefault("metadata", {}).setdefault("labels", {})
            tmpl["metadata"]["labels"].update(sel["matchLabels"])
            rs = new_object(
                "ReplicaSet", f"{name}-{thash}", ns,
                labels=dict(tmpl["metadata"]["labels"]),
                spec={"replicas": 0, "selector": sel, "template": tmpl},
                status={"replicas": 0})
            rs["metadata"]["ownerReferences"] = [owner_ref(dep)]
            try:
                new_rs = await self.store.create("replicasets", rs)
            except AlreadyExists:
                await self.queue.add(key)
                return

        strategy = (spec.get("strategy") or {})
        stype = strategy.get("type", "RollingUpdate")
        old_total = sum(int(r["spec"].get("replicas", 0)) for r in old_rses)
        new_want = int(new_rs["spec"].get("replicas", 0))

        if stype == "Recreate":
            if old_total > 0:
                for rs in old_rses:
                    await self._scale(rs, 0)
                return
            if new_want != replicas:
                await self._scale(new_rs, replicas)
        else:  # RollingUpdate
            ru = strategy.get("rollingUpdate") or {}
            max_surge = _resolve_bound(ru.get("maxSurge", 1), replicas)
            max_unavail = _resolve_bound(ru.get("maxUnavailable", 0), replicas)
            if max_surge == 0 and max_unavail == 0:
                max_unavail = 1  # both zero is invalid; reference defaults

            # Scale up new RS within the surge budget.
            total = new_want + old_total
            if new_want < replicas and total < replicas + max_surge:
                up = min(replicas - new_want, replicas + max_surge - total)
                await self._scale(new_rs, new_want + up)
                new_want += up

            # Scale down old RSes. Count only READY replicas as available
            # (rolling.go; spec.replicas would overstate it while old pods
            # are not ready and let scale-down exceed maxUnavailable). First
            # remove UNHEALTHY old replicas outside the availability budget
            # (cleanupUnhealthyReplicas) — they contribute nothing to
            # availability, and without this a permanently-unready old pod
            # deadlocks the rollout at maxSurge=0.
            new_ready = int(new_rs.get("status", {}).get("readyReplicas", 0))
            old_ready = sum(
                int(r.get("status", {}).get("readyReplicas", 0))
                for r in old_rses)
            min_available = replicas - max_unavail
            new_unavail = max(0, new_want - new_ready)
            max_cleanup = max(
                0, new_want + old_total - min_available - new_unavail)
            oldest_first = sorted(
                old_rses,
                key=lambda r: r["metadata"].get("creationTimestamp", ""))
            # Indexer objects are shared/frozen — track effective replica
            # counts locally rather than mutating them.
            eff = {namespaced_name(rs): int(rs["spec"].get("replicas", 0))
                   for rs in oldest_first}
            for rs in oldest_first:
                if max_cleanup <= 0:
                    break
                k = namespaced_name(rs)
                ready = int(rs.get("status", {}).get("readyReplicas", 0))
                drop = min(max(0, eff[k] - ready), max_cleanup)
                if drop > 0:
                    await self._scale(rs, eff[k] - drop)
                    eff[k] -= drop
                    max_cleanup -= drop

            available = new_ready + old_ready
            can_remove = max(0, available - min_available)
            for rs in oldest_first:
                if can_remove <= 0:
                    break
                k = namespaced_name(rs)
                drop = min(eff[k], can_remove)
                if drop > 0:
                    await self._scale(rs, eff[k] - drop)
                    eff[k] -= drop
                    can_remove -= drop
            if old_total > 0 or new_ready < replicas:
                await self.enqueue_after(key, 0.2)  # keep rolling

        def set_status(obj):
            obj.setdefault("status", {})
            obj["status"]["updatedReplicas"] = int(
                new_rs.get("status", {}).get("replicas", 0))
            obj["status"]["readyReplicas"] = sum(
                int(r.get("status", {}).get("readyReplicas", 0))
                for r in owned)
            obj["status"]["replicas"] = sum(
                int(r.get("status", {}).get("replicas", 0)) for r in owned)
            obj["status"]["observedGeneration"] = \
                obj["metadata"].get("generation", 0)
            return obj
        try:
            await self.store.guaranteed_update("deployments", key, set_status)
        except NotFound:
            pass

    async def _scale(self, rs: dict, replicas: int) -> None:
        def mutate(obj):
            if int(obj["spec"].get("replicas", 0)) == replicas:
                return None
            obj["spec"]["replicas"] = replicas
            return obj
        try:
            await self.store.guaranteed_update(
                "replicasets", namespaced_name(rs), mutate)
        except StoreError as e:
            logger.warning("scale %s → %d failed: %s",
                           namespaced_name(rs), replicas, e)
