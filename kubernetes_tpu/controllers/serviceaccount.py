"""ServiceAccount + token controllers: principal lifecycle for authn.

Parity targets (SURVEY §2.4 `serviceaccount/`):
- pkg/controller/serviceaccount/serviceaccounts_controller.go: ensure
  the "default" ServiceAccount exists in every namespace (recreated if
  deleted, stamped on namespace creation).
- pkg/controller/serviceaccount/tokens_controller.go (legacy token
  secrets): issue a token Secret per ServiceAccount, delete it with the
  SA. The issued token authenticates to the apiserver as
  `system:serviceaccount:<ns>:<name>` — the exact username RBAC's
  ServiceAccount subjects bind to (apiserver/rbac.py add_binding).

The apiserver side: `ServiceAccountAuthenticator` plugs into
APIServer/WireServer `token_authenticator` and resolves presented
bearer tokens through the secrets informer, so issued tokens work on
both wires with no static bearer_tokens entry.
"""

from __future__ import annotations

import hashlib
import logging
import secrets as _secrets

from kubernetes_tpu.api.meta import name_of, namespace_of, namespaced_name, new_object
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)

SA_TOKEN_TYPE = "kubernetes.io/service-account-token"
SA_NAME_ANN = "kubernetes.io/service-account.name"


def sa_username(namespace: str, name: str) -> str:
    return f"system:serviceaccount:{namespace}:{name}"


class ServiceAccountController(Controller):
    """Every namespace gets a "default" ServiceAccount."""

    NAME = "serviceaccount"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.ns_informer = factory.informer("namespaces")
        self.sa_informer = factory.informer("serviceaccounts")
        self.watch_resource(factory, "namespaces", key_fn=name_of)
        # SA deletion re-syncs its namespace (recreate default).
        factory.informer("serviceaccounts").add_event_handler(
            ResourceEventHandler(on_delete=self._sa_deleted))

    def _sa_deleted(self, obj) -> None:
        import asyncio
        ns = namespace_of(obj)
        if ns:
            asyncio.ensure_future(self.queue.add(ns))

    async def resync_keys(self):
        return [name_of(n) for n in self.ns_informer.indexer.list()]

    async def sync(self, key: str) -> None:
        ns = self.ns_informer.indexer.get(key)
        if ns is None or (ns.get("status") or {}).get("phase") == \
                "Terminating":
            return
        if self.sa_informer.indexer.get(f"{key}/default") is not None:
            return
        sa = new_object("ServiceAccount", "default", key)
        try:
            await self.store.create("serviceaccounts", sa,
                                    return_copy=False)
        except (AlreadyExists, StoreError) as e:
            logger.debug("default SA for %s: %s", key, e)


class TokenController(Controller):
    """Issue a token Secret per ServiceAccount; GC it with the SA."""

    NAME = "serviceaccount-token"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def _events(self):
        # Lazy: the recorder spins a drain task on first use, and most
        # syncs never emit (ADVICE r5 — only the double-squat dead end
        # needs the Event surface).
        rec = getattr(self, "_recorder", None)
        if rec is None:
            from kubernetes_tpu.client.events import EventRecorder
            rec = self._recorder = EventRecorder(
                self.store, "serviceaccount-token-controller")
        return rec

    def setup(self, factory: InformerFactory) -> None:
        self.sa_informer = factory.informer("serviceaccounts")
        self.secret_informer = factory.informer("secrets")
        self.watch_resource(factory, "serviceaccounts")

        def secret_event(obj):
            # Secret deleted/changed → re-sync its SA.
            import asyncio
            ann = (obj.get("metadata") or {}).get("annotations") or {}
            sa = ann.get(SA_NAME_ANN)
            if sa:
                ns = namespace_of(obj) or "default"
                asyncio.ensure_future(self.queue.add(f"{ns}/{sa}"))

        factory.informer("secrets").add_event_handler(
            ResourceEventHandler(on_delete=secret_event))

    async def resync_keys(self):
        return [namespaced_name(sa)
                for sa in self.sa_informer.indexer.list()]

    def _token_secret_of(self, ns: str, sa_name: str) -> dict | None:
        for s in self.secret_informer.indexer.list():
            if (namespace_of(s) or "default") != ns:
                continue
            if s.get("type") != SA_TOKEN_TYPE:
                continue
            ann = (s.get("metadata") or {}).get("annotations") or {}
            if ann.get(SA_NAME_ANN) == sa_name:
                return s
        return None

    async def sync(self, key: str) -> None:
        ns, _, sa_name = key.partition("/")
        sa = self.sa_informer.indexer.get(key)
        existing = self._token_secret_of(ns, sa_name)
        if sa is None:
            # SA gone → its token secret dies too (tokens_controller
            # secret deletion; ownerRef GC would also cover it).
            if existing is not None:
                try:
                    await self.store.delete(
                        "secrets", namespaced_name(existing))
                except StoreError:
                    pass
            return
        if existing is not None:
            return
        token = f"sa-{_secrets.token_urlsafe(24)}"
        secret_name = None
        # The fallback suffix is DETERMINISTIC (derived from the SA uid):
        # informer-lag resyncs recompute the same name and collide on
        # AlreadyExists instead of minting a new secret per sync.
        uid = (sa.get("metadata") or {}).get("uid") or ""
        suffix = (uid.replace("-", "")[:6]
                  or hashlib.sha256(key.encode()).hexdigest()[:6])
        for candidate in (f"{sa_name}-token",
                          f"{sa_name}-token-{suffix}"):
            secret = new_object(
                "Secret", candidate, ns,
                type=SA_TOKEN_TYPE,
                data={"token": token, "namespace": ns})
            secret["metadata"]["annotations"] = {SA_NAME_ANN: sa_name}
            secret["metadata"]["ownerReferences"] = [{
                "apiVersion": "v1", "kind": "ServiceAccount",
                "name": sa_name,
                "uid": sa.get("metadata", {}).get("uid", ""),
                "controller": True}]
            try:
                await self.store.create("secrets", secret, return_copy=False)
                secret_name = candidate
                break
            except AlreadyExists:
                # The name may be squatted by a FOREIGN secret (wrong
                # type/annotation) that will never authenticate; only
                # accept it as "established" if it really is our token,
                # else retry under a suffixed name rather than mirroring
                # a dead name into sa.secrets.
                try:
                    held = await self.store.get("secrets", f"{ns}/{candidate}")
                except StoreError:
                    continue
                ann = (held.get("metadata") or {}).get("annotations") or {}
                if (held.get("type") == SA_TOKEN_TYPE
                        and ann.get(SA_NAME_ANN) == sa_name):
                    secret_name = candidate
                    break
        if secret_name is None:
            # BOTH candidate names are squatted by foreign secrets
            # (wrong type/annotation): every resync from here recomputes
            # the same names and dead-ends identically, so the SA never
            # gets a working token. Returning silently hid that (ADVICE
            # r5) — log once per sync and emit a Warning Event so the
            # dead-end is observable from `kubectl describe sa` land.
            logger.warning(
                "serviceaccount %s: token secret names %r are both "
                "held by foreign secrets; no token will be issued "
                "until one is freed", key,
                [f"{sa_name}-token", f"{sa_name}-token-{suffix}"])
            self._events().event(
                sa, "Warning", "TokenSecretSquatted",
                f"cannot issue a token secret: both candidate names "
                f"{sa_name}-token and {sa_name}-token-{suffix} exist "
                f"with a foreign type or owner annotation")
            return

        # Mirror the secret name into the SA (kubectl describe parity).
        def note(obj):
            secrets_list = obj.setdefault("secrets", [])
            entry = {"name": secret_name}
            if entry in secrets_list:
                return None
            secrets_list.append(entry)
            return obj
        try:
            await self.store.guaranteed_update(
                "serviceaccounts", key, note, return_copy=False)
        except NotFound:
            pass


class ServiceAccountAuthenticator:
    """Bearer-token authenticator backed by the token secrets.

    Plugs into APIServer/WireServer as `token_authenticator`: returns
    the SA username for a valid token, None otherwise. Uses an
    incremental token index fed by the secrets informer."""

    def __init__(self, factory: InformerFactory):
        self._by_token: dict[str, str] = {}
        self._secret_token: dict[str, str] = {}

        def index(obj):
            # Drop any stale entry FIRST: a token secret updated to a
            # different type must stop authenticating immediately.
            key = namespaced_name(obj)
            old = self._secret_token.pop(key, None)
            if old is not None:
                self._by_token.pop(old, None)
            if obj.get("type") != SA_TOKEN_TYPE:
                return
            data = obj.get("data") or {}
            token = data.get("token")
            ann = (obj.get("metadata") or {}).get("annotations") or {}
            sa = ann.get(SA_NAME_ANN)
            if token and sa:
                ns = namespace_of(obj) or "default"
                self._by_token[token] = sa_username(ns, sa)
                self._secret_token[key] = token

        def drop(obj):
            key = namespaced_name(obj)
            old = self._secret_token.pop(key, None)
            if old is not None:
                self._by_token.pop(old, None)

        factory.informer("secrets").add_event_handler(
            ResourceEventHandler(
                on_add=index, on_update=lambda o, n: index(n),
                on_delete=drop))

    def __call__(self, token: str) -> str | None:
        return self._by_token.get(token)
