"""Rebalance descheduler: evict-and-replace consolidation under a budget.

Parity target: the descheduler's HighNodeUtilization/LowNodeUtilization
strategies (kubernetes-sigs/descheduler) folded into the controller-manager
pattern of SURVEY §2.4 — a resync-driven reconcile loop, not a one-shot
CLI. The scheduler's optimal solve mode (r20, ops/solver.sinkhorn_plan)
packs each BATCH tightly, but a long-lived cluster fragments anyway:
completions and node churn strand capacity on half-empty nodes that
arrival-order placement can never repair. This controller closes that
loop the way production clusters do — propose moves, bound disruption,
let the scheduler re-place:

1. Snapshot nodes + bound pods from the shared informers and score every
   node with `ops/solver.consolidation_scores` — the same free/alloc
   planes the solver consumes, scored on device: occupied nodes whose
   mean free fraction clears the threshold are drain candidates,
   emptiest first (least to move, frees a whole node soonest).
2. A candidate drains only if its displaced pods AGGREGATE-FIT into the
   remaining cluster headroom (candidate excluded) — an admission check,
   not a placement: the scheduler owns placement, so the controller only
   guarantees it isn't evicting into a full cluster.
3. Evict-and-replace: delete the bound pod and create an unbound
   replacement (same spec, nodeName stripped, fresh name/uid) for the
   scheduler to place — there is no kubelet to restart containers, so
   eviction IS delete+recreate here, matching how the perf harness
   models every disruption.
4. The DISRUPTION BUDGET (`KTPU_DESCHEDULER_BUDGET`, ctor-overridable)
   caps evictions PER SYNC CYCLE; the resync period is the rate limiter
   between cycles. `descheduler_evictions_total` counts actual moves.

The ChurnDay rebalance family (perf/config/performance-config.yaml)
drives this controller against fragmenting churn and reports the
fragmentation-over-time curve with the descheduler on vs off.
"""

from __future__ import annotations

import logging
from typing import Iterable

from kubernetes_tpu.api.meta import deep_copy, namespaced_name, new_uid
from kubernetes_tpu.api.types import (
    node_allocatable,
    node_is_unschedulable,
    pod_is_terminal,
    pod_requests,
)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.metrics.registry import DeschedulerMetrics
from kubernetes_tpu.store.mvcc import StoreError
from kubernetes_tpu.utils import flags

logger = logging.getLogger(__name__)

#: resources excluded from the free/alloc quantity planes (pod COUNT is
#: capacity, not a packable quantity — it rides the used_pods vector).
_NON_QUANTITY = frozenset(("pods",))


class DeschedulerController(Controller):
    NAME = "descheduler"
    WORKERS = 1

    def __init__(self, store, *, period: float = 0.5,
                 budget: int | None = None, threshold: float = 0.5,
                 metrics: DeschedulerMetrics | None = None):
        super().__init__(store)
        self.RESYNC_PERIOD = period
        self._budget = budget
        self.threshold = threshold
        self.metrics = metrics or DeschedulerMetrics()
        #: lifetime evict-and-replace moves (the phase-delta the perf
        #: harness reads without touching the registry render).
        self.evictions = 0
        self._seq = 0

    @property
    def budget(self) -> int:
        if self._budget is not None:
            return self._budget
        return flags.get("KTPU_DESCHEDULER_BUDGET")

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")
        self.node_informer = factory.informer("nodes")

    async def resync_keys(self) -> Iterable[str]:
        return ["rebalance"]

    async def sync(self, key: str) -> None:
        if key == "rebalance":
            await self.rebalance_once()

    # -- one consolidation cycle -------------------------------------------

    async def rebalance_once(self) -> int:
        """One bounded consolidation pass; returns evictions issued."""
        import numpy as np

        from kubernetes_tpu.ops import solver

        nodes = [n for n in self.node_informer.indexer.list()
                 if not node_is_unschedulable(n)]
        if not nodes:
            return 0
        names = [n["metadata"]["name"] for n in nodes]
        index = {name: i for i, name in enumerate(names)}
        allocs = [node_allocatable(n) for n in nodes]
        resources = sorted({r for a in allocs for r in a
                            if r not in _NON_QUANTITY})
        if not resources:
            return 0

        n_nodes, n_res = len(nodes), len(resources)
        alloc_q = np.zeros((n_nodes, n_res), np.float32)
        for i, a in enumerate(allocs):
            for j, r in enumerate(resources):
                alloc_q[i, j] = a.get(r, 0)
        free_q = alloc_q.copy()
        used_pods = np.zeros((n_nodes,), np.int32)
        victims_by_node: dict[int, list[dict]] = {}
        for pod in self.pod_informer.indexer.list():
            if pod_is_terminal(pod):
                continue
            i = index.get(pod.get("spec", {}).get("nodeName") or "")
            if i is None:
                continue
            used_pods[i] += 1
            req = pod_requests(pod)
            for j, r in enumerate(resources):
                free_q[i, j] -= req.get(r, 0)
            victims_by_node.setdefault(i, []).append(pod)

        scores = np.asarray(solver.consolidation_scores(
            free_q, alloc_q, used_pods, np.ones((n_nodes,), bool),
            np.float32(self.threshold)))

        # Cluster headroom EXCLUDING each candidate: displaced pods must
        # aggregate-fit into what the rest of the cluster has free.
        total_free = np.maximum(free_q, 0.0).sum(axis=0)
        budget = max(0, int(self.budget))
        evicted = 0
        for i in np.argsort(-scores):
            if evicted >= budget or not np.isfinite(scores[i]):
                break
            victims = victims_by_node.get(int(i), [])
            if not victims or len(victims) > budget - evicted:
                continue
            need = np.zeros((n_res,), np.float32)
            for pod in victims:
                req = pod_requests(pod)
                for j, r in enumerate(resources):
                    need[j] += req.get(r, 0)
            headroom = total_free - np.maximum(free_q[i], 0.0)
            if np.any(need > headroom):
                continue
            moved = 0
            for pod in victims:
                if await self._evict(pod):
                    moved += 1
            evicted += moved
            if moved:
                # Replacements will land somewhere else: debit the
                # headroom so later candidates see the tighter cluster.
                total_free = headroom - need + np.maximum(free_q[i], 0.0)
        return evicted

    async def _evict(self, pod: dict) -> bool:
        """Evict-and-replace: delete the bound pod, create an unbound
        twin (fresh name/uid, nodeName and status stripped) for the
        scheduler to re-place."""
        repl = deep_copy(pod)
        meta = repl.setdefault("metadata", {})
        self._seq += 1
        meta["name"] = f"{meta.get('name', 'pod')}-reb{self._seq}"
        meta["uid"] = new_uid()
        for k in ("resourceVersion", "creationTimestamp",
                  "deletionTimestamp", "finalizers"):
            meta.pop(k, None)
        repl.get("spec", {}).pop("nodeName", None)
        repl["status"] = {"phase": "Pending"}
        try:
            await self.store.delete("pods", namespaced_name(pod))
        except StoreError:
            return False  # raced a completion/GC: not a move
        await self.store.create("pods", repl)
        self.evictions += 1
        self.metrics.evictions.inc()
        return True
