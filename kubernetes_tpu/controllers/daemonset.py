"""DaemonSet controller: one pod per matching node.

Parity target: pkg/controller/daemon/daemon_controller.go
(`DaemonSetsController.syncDaemonSet` → `manage`/`podsShouldBeOnNode`):
for every node that should run the daemon, ensure exactly one owned pod;
surplus/mismatched pods are deleted. Post-1.12 semantics: the controller
does NOT set spec.nodeName — it pins each pod with a required NodeAffinity
`matchFields: metadata.name == <node>` and lets the default scheduler place
it (daemon_controller.go `util.ReplaceDaemonSetPodNodeNameNodeAffinity`),
plus tolerations for the unschedulable/not-ready taints.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import namespaced_name, new_object, uid_of
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_ref, _controller_of
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)


def make_daemonset(name: str, selector: dict, template: dict,
                   namespace: str = "default") -> dict:
    return new_object("DaemonSet", name, namespace,
                      spec={"selector": selector, "template": template},
                      status={})


def node_name_affinity(node_name: str) -> dict:
    """util.ReplaceDaemonSetPodNodeNameNodeAffinity: pin via matchFields."""
    return {"nodeAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [{
                "matchFields": [{"key": "metadata.name", "operator": "In",
                                 "values": [node_name]}]}]}}}


#: daemon_controller.go AddOrUpdateDaemonPodTolerations.
DAEMON_TOLERATIONS = [
    {"key": "node.kubernetes.io/not-ready", "operator": "Exists",
     "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unreachable", "operator": "Exists",
     "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unschedulable", "operator": "Exists",
     "effect": "NoSchedule"},
]


class DaemonSetController(Controller):
    NAME = "daemonset"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.ds_informer = factory.informer("daemonsets")
        self.pod_informer = factory.informer("pods")
        self.node_informer = factory.informer("nodes")
        self.watch_resource(factory, "daemonsets")

        self.watch_owned_pods(factory, "DaemonSet")

        # Node churn re-syncs every DaemonSet (daemon_controller.go
        # addNode/updateNode enqueue all).
        def all_ds(_obj=None, _new=None):
            for ds in self.ds_informer.indexer.list():
                asyncio.ensure_future(self.queue.add(namespaced_name(ds)))

        self.node_informer.add_event_handler(ResourceEventHandler(
            on_add=all_ds, on_update=lambda o, n: all_ds(),
            on_delete=lambda o: all_ds()))

    async def resync_keys(self):
        return [namespaced_name(ds) for ds in self.ds_informer.indexer.list()]

    def _should_run(self, ds: dict, node: dict) -> bool:
        """podsShouldBeOnNode simulation subset: template nodeSelector must
        match; NoSchedule taints must be tolerated by template+daemon
        tolerations (NoExecute handled by the eviction path, as upstream)."""
        tmpl_spec = (ds["spec"].get("template") or {}).get("spec") or {}
        node_labels = node["metadata"].get("labels") or {}
        for k, v in (tmpl_spec.get("nodeSelector") or {}).items():
            if node_labels.get(k) != v:
                return False
        tolerations = list(tmpl_spec.get("tolerations") or []) + \
            DAEMON_TOLERATIONS
        for taint in (node.get("spec") or {}).get("taints") or []:
            if taint.get("effect") != "NoSchedule":
                continue
            if not any(_tolerates(t, taint) for t in tolerations):
                return False
        return True

    def _owned_pods(self, ds: dict) -> dict[str, list[dict]]:
        """node name → owned pods on it (nominal or bound)."""
        ns = ds["metadata"].get("namespace", "default")
        ds_uid = uid_of(ds)
        by_node: dict[str, list[dict]] = {}
        for pod in self.pod_informer.indexer.list():
            if pod["metadata"].get("namespace", "default") != ns:
                continue
            ref = _controller_of(pod)
            if ref is None or ref.get("kind") != "DaemonSet" \
                    or ref.get("name") != ds["metadata"]["name"]:
                continue
            if ref.get("uid") and ds_uid and ref["uid"] != ds_uid:
                continue
            node = pod["spec"].get("nodeName") or _pinned_node(pod) or ""
            by_node.setdefault(node, []).append(pod)
        return by_node

    async def sync(self, key: str) -> None:
        ds = self.ds_informer.indexer.get(key)
        if ds is None:
            return
        ns = ds["metadata"].get("namespace", "default")
        nodes = {n["metadata"]["name"]: n
                 for n in self.node_informer.indexer.list()}
        by_node = self._owned_pods(ds)
        desired = {name for name, n in nodes.items()
                   if self._should_run(ds, n)}

        for node_name in desired:
            pods = by_node.get(node_name, [])
            if not pods:
                await self._create_pod(ds, ns, node_name)
            elif len(pods) > 1:
                # Keep the oldest, delete duplicates (manage() dedupe).
                pods.sort(key=lambda p: p["metadata"]
                          .get("creationTimestamp", ""))
                for p in pods[1:]:
                    try:
                        await self.store.delete("pods", namespaced_name(p))
                    except NotFound:
                        pass
        for node_name, pods in by_node.items():
            if node_name not in desired:
                for p in pods:
                    try:
                        await self.store.delete("pods", namespaced_name(p))
                    except NotFound:
                        pass

        def set_status(obj):
            st = obj.setdefault("status", {})
            st["desiredNumberScheduled"] = len(desired)
            st["currentNumberScheduled"] = sum(
                1 for n, ps in by_node.items() if n in desired and ps)
            st["numberReady"] = sum(
                1 for n, ps in by_node.items() if n in desired
                for p in ps if (p.get("status") or {}).get("phase") == "Running")
            st["numberMisscheduled"] = sum(
                len(ps) for n, ps in by_node.items() if n not in desired)
            st["observedGeneration"] = obj["metadata"].get("generation", 0)
            return obj
        try:
            await self.store.guaranteed_update("daemonsets", key, set_status)
        except NotFound:
            pass

    async def _create_pod(self, ds: dict, ns: str, node_name: str) -> None:
        template = (ds["spec"].get("template") or {})
        labels = dict((template.get("metadata") or {}).get("labels")
                      or (ds["spec"].get("selector") or {})
                      .get("matchLabels") or {})
        spec = dict(template.get("spec") or {})
        spec["affinity"] = node_name_affinity(node_name)
        spec["tolerations"] = list(spec.get("tolerations") or []) + \
            DAEMON_TOLERATIONS
        if not spec.get("containers"):
            spec["containers"] = [{"name": "main", "image": "daemon"}]
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": f"{ds['metadata']['name']}-{node_name}",
                "namespace": ns, "labels": labels,
                "ownerReferences": [owner_ref(ds)],
            },
            "spec": spec,
            "status": {"phase": "Pending"},
        }
        try:
            await self.store.create("pods", pod)
        except StoreError as e:
            logger.warning("ds %s: create pod for %s failed: %s",
                           ds["metadata"]["name"], node_name, e)


def _pinned_node(pod: dict) -> str | None:
    """Inverse of node_name_affinity: which node is this pod pinned to?"""
    na = ((pod["spec"].get("affinity") or {}).get("nodeAffinity") or {})
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in req.get("nodeSelectorTerms") or []:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("operator") == "In":
                vals = f.get("values") or []
                if len(vals) == 1:
                    return vals[0]
    return None


def _tolerates(tol: dict, taint: dict) -> bool:
    if tol.get("effect") and tol["effect"] != taint.get("effect"):
        return False
    if tol.get("operator", "Equal") == "Exists":
        return not tol.get("key") or tol["key"] == taint.get("key")
    return tol.get("key") == taint.get("key") and \
        tol.get("value", "") == taint.get("value", "")
