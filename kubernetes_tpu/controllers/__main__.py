"""kube-controller-manager analog: `python -m kubernetes_tpu.controllers`.

Hosts the full controller set over one informer factory against a remote
apiserver, with leader election.

    python -m kubernetes_tpu.controllers --server http://127.0.0.1:8080 \
        --controllers deployment,replicaset,job,cronjob,gc

Parity target: cmd/kube-controller-manager (SURVEY §2.1).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

#: name -> constructor(store); the default set mirrors the reference's
#: always-on controllers.
REGISTRY = {
    "deployment": "DeploymentController",
    "replicaset": "ReplicaSetController",
    "statefulset": "StatefulSetController",
    "daemonset": "DaemonSetController",
    "job": "JobController",
    "cronjob": "CronJobController",
    "nodelifecycle": "NodeLifecycleController",
    "podgc": "PodGCController",
    "gc": "GarbageCollectorController",
    "namespace": "NamespaceController",
    "endpointslice": "EndpointSliceController",
    "resourcequota": "ResourceQuotaController",
    "disruption": "DisruptionController",
    "ttl": "TTLAfterFinishedController",
    "hpa": "HorizontalPodAutoscalerController",
    "pvbinder": "PVBinderController",
    "attachdetach": "AttachDetachController",
    "resourceclaim": "ResourceClaimController",
    "serviceaccount": "ServiceAccountController",
    "serviceaccount-token": "TokenController",
    "kubeproxy": "KubeProxyController",
}

DEFAULT_SET = [n for n in REGISTRY if n not in ("kubeproxy",)]


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpu-controller-manager",
                                 description=__doc__)
    ap.add_argument("--server", default=None)
    ap.add_argument("--wire", default=None)
    ap.add_argument("--token", default=None)
    ap.add_argument("--controllers", default=",".join(DEFAULT_SET),
                    help="comma list (default: all but kubeproxy)")
    ap.add_argument("--leader-elect", action="store_true")
    return ap


async def serve(args) -> None:
    if args.wire:
        from kubernetes_tpu.apiserver.wire import WireStore
        store = WireStore(args.wire, token=args.token,
                          user_agent="ktpu-controller-manager")
    elif args.server:
        from kubernetes_tpu.apiserver.client import RemoteStore
        store = RemoteStore(args.server, token=args.token,
                            user_agent="ktpu-controller-manager")
    else:
        raise SystemExit("one of --server / --wire is required")

    import kubernetes_tpu.controllers as C
    wanted = [n.strip() for n in args.controllers.split(",") if n.strip()]
    controllers = []
    for name in wanted:
        cls_name = REGISTRY.get(name)
        if cls_name is None:
            raise SystemExit(f"unknown controller {name!r}")
        controllers.append(getattr(C, cls_name)(store))
    mgr = C.ControllerManager(store, controllers)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass

    if args.leader_elect:
        import uuid

        from kubernetes_tpu.client.leaderelection import LeaderElector
        elector = LeaderElector(
            store, "kube-controller-manager",
            identity=f"ktpu-cm-{uuid.uuid4().hex[:8]}")
        # ControllerManager owns the fencing: losing the lease STOPS
        # every controller so the standby replica converges instead of
        # double-reconciling.
        task = asyncio.ensure_future(
            mgr.run_with_leader_election(elector))
        logging.info("controller-manager (leader-elected): %s",
                     ", ".join(wanted))
        stop_task = asyncio.ensure_future(stop.wait())
        await asyncio.wait({task, stop_task},
                           return_when=asyncio.FIRST_COMPLETED)
        task.cancel()
        stop_task.cancel()
        await asyncio.gather(task, return_exceptions=True)
    else:
        await mgr.start()
        logging.info("controller-manager running: %s", ", ".join(wanted))
        await stop.wait()
        await mgr.stop()
    close = getattr(store, "close", None)
    if close is not None:
        await close()


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    asyncio.run(serve(args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
