"""KWOK-style fake node controller: thousands of nodes without kubelets.

Parity target: kubernetes-sigs/kwok (SURVEY §4 "Scale simulation" row) +
cmd/kubemark hollow nodes: register N Node objects, renew their coordination
Leases on the kubelet cadence, and fake the pod lifecycle (bound pods are
marked Running, and terminate when deleted). This is what makes 5k/50k-node
configs runnable on one host with the REAL control plane (store, scheduler,
controllers all unmodified).
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import namespaced_name, new_object
from kubernetes_tpu.api.types import (
    make_node,
    make_resource_slice,
    template_devices,
)
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)


class KwokController(Controller):
    NAME = "kwok"
    WORKERS = 2

    def __init__(self, store, *, node_count: int = 0,
                 node_template: dict | None = None,
                 lease_period: float = 2.0,
                 name_prefix: str = "kwok-node-",
                 device_zones: int = 2,
                 device_driver: str = "dra.ktpu"):
        super().__init__(store)
        self.node_count = node_count
        self.node_template = node_template or {}
        self.lease_period = lease_period
        self.name_prefix = name_prefix
        #: device-plugin seam (SURVEY §2.5 devicemanager): extended
        #: resources in the node template ALSO publish as per-node
        #: ResourceSlices (the DRA driver's ListAndWatch analog), split
        #: into contiguous blocks across this many NUMA zones (devices
        #: 0..n/z-1 in zone 0, etc. — the alignment MatchAttribute needs).
        self.device_zones = max(1, device_zones)
        self.device_driver = device_driver
        self._device_list: list[dict] | None = None  # built once
        self._managed: set[str] = set()
        self._ip_seq = 0  # fake pod IP allocator (see _mark_running)
        self._run_queue: list[str] = []
        self._run_draining = False
        self._stage_tasks: set[asyncio.Task] = set()

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")

        def on_pod(obj):
            # Fake kubelet: a pod bound to a managed node starts "Running".
            # Keys are buffered and drained by ONE task (not one task per
            # pod — at 10k pods/s the per-pod task + write overhead is a
            # top host cost).
            node = obj.get("spec", {}).get("nodeName")
            if node in self._managed and \
                    obj.get("status", {}).get("phase") == "Pending":
                self._run_queue.append(namespaced_name(obj))
                if not self._run_draining:
                    self._run_draining = True
                    asyncio.ensure_future(self._drain_mark_running())

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=on_pod, on_update=lambda o, n: on_pod(n)))

    async def _drain_mark_running(self) -> None:
        try:
            while self._run_queue:
                batch, self._run_queue = self._run_queue, []
                for key in batch:
                    await self._mark_running(key)
        finally:
            self._run_draining = False

    async def register_nodes(self) -> None:
        for i in range(self.node_count):
            name = f"{self.name_prefix}{i}"
            node = make_node(name, **self.node_template)
            node["metadata"].setdefault("annotations", {})[
                "kwok.x-k8s.io/node"] = "fake"
            try:
                await self.store.create("nodes", node)
            except AlreadyExists:
                pass
            self._managed.add(name)
            await self._publish_devices(name)

    def _template_devices(self) -> list[dict]:
        """Device list derived from the template ONCE (50k-node runs
        register 50k slices; re-parsing per node would be 400k throwaway
        dict builds). Naming/zoning convention: api.types.template_devices
        (shared with the hollow-kubelet agent)."""
        if self._device_list is None:
            self._device_list = template_devices(
                self.node_template.get("allocatable"), self.device_zones)
        return self._device_list

    async def _publish_devices(self, node_name: str) -> None:
        """Model HOW `google.com/tpu: 8` arrives: the kubelet device
        manager / DRA driver registers the node's devices. Extended
        resources in the template (names containing '/') publish as a
        ResourceSlice with per-device NUMA attributes, so BOTH device
        paths work against kwok nodes — legacy extended-resource counting
        (already in node.allocatable) and DRA claims."""
        devices = self._template_devices()
        if not devices:
            return
        try:
            # store.create deep-copies on entry; the shared list is safe.
            await self.store.create(
                "resourceslices",
                make_resource_slice(node_name, self.device_driver,
                                    devices))
        except AlreadyExists:
            pass
        except StoreError:
            logger.exception("kwok: device publish failed for %s",
                             node_name)

    def start(self) -> None:
        super().start()
        self._tasks.append(asyncio.ensure_future(self._lease_loop()))

    async def _lease_loop(self) -> None:
        """Renew every managed node's Lease (nodelease cadence)."""
        while not self._stopped:
            # Copy: fail_node() may discard from _managed while this loop is
            # suspended at an await; one tick's failure must not kill the task.
            for name in list(self._managed):
                if name not in self._managed:
                    continue
                try:
                    await self.store.guaranteed_update(
                        "leases", f"kube-node-lease/{name}",
                        self._renew)
                except NotFound:
                    lease = new_object("Lease", name, "kube-node-lease",
                                       spec={"renewTime": 0})
                    try:
                        await self.store.create("leases", lease)
                    except StoreError:
                        pass
                except StoreError:
                    pass
                except Exception:
                    logger.exception("kwok lease renew failed for %s", name)
            await asyncio.sleep(self.lease_period)

    @staticmethod
    def _renew(lease: dict) -> dict:
        lease.setdefault("spec", {})
        lease["spec"]["renewTime"] = lease["spec"].get("renewTime", 0) + 1
        return lease

    async def _mark_running(self, key: str) -> None:
        complete_after = [None]

        def mutate(pod):
            if pod.get("status", {}).get("phase") != "Pending":
                return None
            pod.setdefault("status", {})["phase"] = "Running"
            # Fake pod IP (kwok does the same): EndpointSlice endpoints
            # need addresses. Sequential allocation — unique by
            # construction (builtin hash() is salted per process and
            # collides at 50k scale).
            self._ip_seq += 1
            hi, lo = divmod(self._ip_seq, 254)
            pod["status"].setdefault(
                "podIP", f"10.{(hi >> 8) % 256}.{hi % 256}.{lo + 1}")
            conds = pod["status"].setdefault("conditions", [])
            if not any(c.get("type") == "Ready" for c in conds):
                conds.append({"type": "Ready", "status": "True"})
            complete_after[0] = (pod["metadata"].get("annotations") or {}).get(
                "kwok.x-k8s.io/complete-after")
            return pod
        try:
            await self.store.guaranteed_update(
                "pods", key, mutate, return_copy=False)
        except StoreError:
            return
        # Lifecycle stage (kwok Stage API analog): a pod annotated
        # `kwok.x-k8s.io/complete-after: "<seconds>"` runs to completion —
        # how Jobs finish in this kubelet-less world.
        if complete_after[0] is not None:
            try:
                delay = float(complete_after[0])
            except ValueError:
                return
            # Self-discarding set — one task per completing pod must not
            # accumulate for the controller's lifetime.
            t = asyncio.ensure_future(self._complete_later(key, delay))
            self._stage_tasks.add(t)
            t.add_done_callback(self._stage_tasks.discard)

    async def _complete_later(self, key: str, delay: float) -> None:
        await asyncio.sleep(delay)

        def mutate(pod):
            if pod.get("status", {}).get("phase") != "Running":
                return None
            pod["status"]["phase"] = "Succeeded"
            return pod
        try:
            await self.store.guaranteed_update(
                "pods", key, mutate, return_copy=False)
        except StoreError:
            pass

    def fail_node(self, name: str) -> None:
        """Fault injection: stop heartbeating one node (SURVEY §5.3 —
        node-death injection is first-class in the sim harness)."""
        self._managed.discard(name)

    async def sync(self, key: str) -> None:
        return
