"""ReplicaSet controller: RS → Pods.

Parity target: pkg/controller/replicaset/replica_set.go
(`ReplicaSetController.syncReplicaSet` → `manageReplicas`): list matching
pods via the RS selector, create/delete the difference, adopt via
ownerReferences, write status (replicas / readyReplicas).
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.labels import from_label_selector
from kubernetes_tpu.api.meta import namespaced_name, new_object, uid_of
from kubernetes_tpu.api.types import pod_is_terminal
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)

#: Burst cap per sync (replica_set.go BurstReplicas=500; smaller here —
#: level-triggered resync covers the rest).
BURST_REPLICAS = 500


def make_replicaset(name: str, replicas: int, selector: dict,
                    pod_template: dict, namespace: str = "default",
                    owner: dict | None = None) -> dict:
    rs = new_object("ReplicaSet", name, namespace,
                    spec={"replicas": replicas, "selector": selector,
                          "template": pod_template},
                    status={"replicas": 0})
    if owner:
        rs["metadata"]["ownerReferences"] = [owner]
    return rs


def owner_ref(obj: dict, controller: bool = True) -> dict:
    return {
        "apiVersion": obj.get("apiVersion", "v1"),
        "kind": obj.get("kind", ""),
        "name": obj["metadata"]["name"],
        "uid": obj["metadata"].get("uid", ""),
        "controller": controller,
    }


def _controller_of(obj: dict) -> dict | None:
    for ref in obj.get("metadata", {}).get("ownerReferences") or []:
        if ref.get("controller"):
            return ref
    return None


class ReplicaSetController(Controller):
    NAME = "replicaset"
    WORKERS = 4
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.rs_informer = factory.informer("replicasets")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "replicasets")

        # Pod events map back to the owning RS key (replica_set.go addPod/
        # deletePod resolve the controllerRef).
        def pod_to_rs(obj):
            ref = _controller_of(obj)
            if ref and ref.get("kind") == "ReplicaSet":
                ns = obj["metadata"].get("namespace", "default")
                asyncio.ensure_future(self.queue.add(f"{ns}/{ref['name']}"))

        from kubernetes_tpu.client import ResourceEventHandler
        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=pod_to_rs, on_update=lambda o, n: pod_to_rs(n),
            on_delete=pod_to_rs))

    async def resync_keys(self):
        return [namespaced_name(rs) for rs in self.rs_informer.indexer.list()]

    def _matching_pods(self, rs: dict) -> list[dict]:
        sel = from_label_selector(rs["spec"].get("selector") or {})
        ns = rs["metadata"].get("namespace", "default")
        rs_uid = uid_of(rs)
        out = []
        for pod in self.pod_informer.indexer.list():
            if pod["metadata"].get("namespace", "default") != ns:
                continue
            if pod_is_terminal(pod) or pod["metadata"].get("deletionTimestamp"):
                continue
            ref = _controller_of(pod)
            if ref is not None:
                # Owned pods count iff owned by THIS RS (uid match).
                if ref.get("uid") and rs_uid and ref["uid"] != rs_uid:
                    continue
                if ref.get("kind") != "ReplicaSet" or \
                        ref.get("name") != rs["metadata"]["name"]:
                    continue
                out.append(pod)
            elif sel.matches(pod["metadata"].get("labels")):
                out.append(pod)  # orphan adoption candidate (counted)
        return out

    async def sync(self, key: str) -> None:
        rs = self.rs_informer.indexer.get(key)
        if rs is None:
            return  # deleted; pods are cleaned by GC/podgc
        want = int(rs["spec"].get("replicas", 0))
        pods = self._matching_pods(rs)
        have = len(pods)
        diff = want - have
        ns = rs["metadata"].get("namespace", "default")

        if diff > 0:
            template = rs["spec"].get("template") or {}
            base = rs["metadata"]["name"]
            for i in range(min(diff, BURST_REPLICAS)):
                pod = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "generateName": f"{base}-",
                        "name": f"{base}-{self._suffix()}",
                        "namespace": ns,
                        "labels": dict((template.get("metadata") or {})
                                       .get("labels")
                                       or (rs["spec"].get("selector") or {})
                                       .get("matchLabels") or {}),
                        "ownerReferences": [owner_ref(rs)],
                    },
                    "spec": dict((template.get("spec") or {})),
                    "status": {"phase": "Pending"},
                }
                if not pod["spec"].get("containers"):
                    pod["spec"]["containers"] = [
                        {"name": "main", "image": "app"}]
                try:
                    await self.store.create("pods", pod)
                except StoreError as e:
                    logger.warning("rs %s: create pod failed: %s", key, e)
                    break
        elif diff < 0:
            # Prefer deleting unscheduled, then newest (getPodsToDelete
            # ranks not-ready/pending first, then younger pods): newest-first
            # within each group, unscheduled group first.
            pods.sort(key=lambda p: p["metadata"].get("creationTimestamp", ""),
                      reverse=True)
            pods.sort(key=lambda p: bool(p["spec"].get("nodeName")))
            for pod in pods[: min(-diff, BURST_REPLICAS)]:
                try:
                    await self.store.delete("pods", namespaced_name(pod))
                except NotFound:
                    pass

        def set_status(obj):
            obj.setdefault("status", {})
            obj["status"]["replicas"] = have if diff <= 0 else want
            obj["status"]["readyReplicas"] = sum(
                1 for p in pods if p["spec"].get("nodeName"))
            obj["status"]["observedGeneration"] = \
                obj["metadata"].get("generation", 0)
            return obj
        try:
            await self.store.guaranteed_update("replicasets", key, set_status)
        except NotFound:
            pass

    _seq = 0

    @classmethod
    def _suffix(cls) -> str:
        cls._seq += 1
        return f"{cls._seq:05d}"
