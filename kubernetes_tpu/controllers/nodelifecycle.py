"""Node lifecycle controller: heartbeat monitoring → taints → eviction.

Parity target: pkg/controller/nodelifecycle/node_lifecycle_controller.go
(SURVEY §5.3): kubelets renew a coordination Lease every ~10s; if no renewal
for `node_monitor_grace_period` (default 40s) the controller marks
Ready=Unknown and adds the `node.kubernetes.io/unreachable:NoExecute` taint;
the NoExecute taint manager then evicts pods whose tolerationSeconds expire
(admission injects a default 300s toleration; ours is a knob).
Recovery (lease renewed) removes the taint and restores Ready=True.
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.api.meta import name_of, namespaced_name
from kubernetes_tpu.api.types import (
    TAINT_NO_EXECUTE,
    TAINT_UNREACHABLE,
    toleration_tolerates_taint,
)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)


class NodeLifecycleController(Controller):
    NAME = "nodelifecycle"
    WORKERS = 2

    def __init__(self, store, *,
                 node_monitor_period: float = 1.0,
                 node_monitor_grace_period: float = 4.0,
                 default_toleration_seconds: float = 3.0,
                 toleration_seconds_cap: float | None = None,
                 clock=time.monotonic):
        super().__init__(store)
        self.monitor_period = node_monitor_period
        self.grace_period = node_monitor_grace_period
        self.default_toleration_seconds = default_toleration_seconds
        #: upper bound applied to FINITE per-pod tolerationSeconds (the
        #: admission default injects 300s on every pod); fault-injection
        #: harnesses set this to accelerate the eviction clock the same
        #: way they shorten the grace period. None = honor pod values;
        #: tolerate-forever (no seconds) is never overridden.
        self.toleration_seconds_cap = toleration_seconds_cap
        self.clock = clock
        #: node -> monotonic time of last observed lease renewal
        self._last_heartbeat: dict[str, float] = {}
        #: (pod key) -> eviction task
        self._evictions: dict[str, asyncio.Task] = {}

    def setup(self, factory: InformerFactory) -> None:
        self.node_informer = factory.informer("nodes")
        self.pod_informer = factory.informer("pods")
        lease_informer = factory.informer("leases")

        from kubernetes_tpu.client import ResourceEventHandler

        def on_lease(obj):
            node = name_of(obj)
            self._last_heartbeat[node] = self.clock()

        lease_informer.add_event_handler(ResourceEventHandler(
            on_add=on_lease, on_update=lambda o, n: on_lease(n)))

        def on_node_add(obj):
            # A node with no lease yet gets the benefit of the doubt from
            # its creation time.
            self._last_heartbeat.setdefault(name_of(obj), self.clock())

        self.node_informer.add_event_handler(ResourceEventHandler(
            on_add=on_node_add,
            on_delete=lambda obj: self._last_heartbeat.pop(name_of(obj), None),
        ))

    def start(self) -> None:
        super().start()
        self._tasks.append(asyncio.ensure_future(self._monitor_loop()))

    async def _monitor_loop(self) -> None:
        """monitorNodeHealth tick."""
        while not self._stopped:
            await asyncio.sleep(self.monitor_period)
            now = self.clock()
            for node in self.node_informer.indexer.list():
                name = name_of(node)
                last = self._last_heartbeat.get(name, now)
                stale = (now - last) > self.grace_period
                tainted = any(
                    t.get("key") == TAINT_UNREACHABLE
                    for t in node.get("spec", {}).get("taints") or [])
                if stale and not tainted:
                    await self._mark_unreachable(name)
                elif not stale and tainted:
                    await self._mark_reachable(name)

    async def _mark_unreachable(self, name: str) -> None:
        logger.warning("node %s missed heartbeats; tainting unreachable", name)

        def mutate(node):
            taints = node.setdefault("spec", {}).setdefault("taints", [])
            if any(t.get("key") == TAINT_UNREACHABLE for t in taints):
                return None
            taints.append({"key": TAINT_UNREACHABLE,
                           "effect": TAINT_NO_EXECUTE})
            self._set_ready(node, "Unknown")
            return node
        try:
            await self.store.guaranteed_update("nodes", name, mutate)
        except NotFound:
            return
        # NoExecute taint manager: schedule eviction for every pod on the
        # node after its effective tolerationSeconds.
        for pod in self.pod_informer.indexer.list():
            if pod.get("spec", {}).get("nodeName") != name:
                continue
            key = namespaced_name(pod)
            if key in self._evictions:
                continue
            delay = self._toleration_seconds(pod)
            if delay is None:
                continue  # tolerates forever
            self._evictions[key] = asyncio.ensure_future(
                self._evict_after(key, name, delay))

    async def _mark_reachable(self, name: str) -> None:
        logger.info("node %s heartbeats resumed; removing taint", name)

        def mutate(node):
            taints = node.get("spec", {}).get("taints") or []
            kept = [t for t in taints if t.get("key") != TAINT_UNREACHABLE]
            if len(kept) == len(taints):
                return None
            node["spec"]["taints"] = kept
            self._set_ready(node, "True")
            return node
        try:
            await self.store.guaranteed_update("nodes", name, mutate)
        except NotFound:
            pass
        # Cancel pending evictions for pods on the recovered node.
        for key, task in list(self._evictions.items()):
            pod = self.pod_informer.indexer.get(key)
            if pod is not None and pod.get("spec", {}).get("nodeName") == name:
                task.cancel()
                del self._evictions[key]

    def _toleration_seconds(self, pod: dict) -> float | None:
        """Effective tolerationSeconds for the unreachable taint: the pod's
        matching toleration wins; absent one, the injected default applies
        (defaulttolerationseconds admission plugin)."""
        taint = {"key": TAINT_UNREACHABLE, "effect": TAINT_NO_EXECUTE}
        cap = self.toleration_seconds_cap
        for tol in pod.get("spec", {}).get("tolerations") or []:
            if toleration_tolerates_taint(tol, taint):
                secs = tol.get("tolerationSeconds")
                if secs is None:
                    return None  # tolerates forever; the cap never applies
                return float(secs) if cap is None \
                    else min(float(secs), cap)
        return self.default_toleration_seconds if cap is None \
            else min(self.default_toleration_seconds, cap)

    async def _evict_after(self, key: str, node: str, delay: float) -> None:
        try:
            await asyncio.sleep(delay)
            pod = self.pod_informer.indexer.get(key)
            if pod is None or pod.get("spec", {}).get("nodeName") != node:
                return
            logger.warning("evicting %s from unreachable node %s", key, node)
            try:
                await self.store.delete("pods", key)
            except StoreError:
                pass
        except asyncio.CancelledError:
            pass
        finally:
            self._evictions.pop(key, None)

    @staticmethod
    def _set_ready(node: dict, status: str) -> None:
        conds = node.setdefault("status", {}).setdefault("conditions", [])
        for c in conds:
            if c.get("type") == "Ready":
                c["status"] = status
                return
        conds.append({"type": "Ready", "status": status})

    async def sync(self, key: str) -> None:  # all work happens in the loops
        return

    async def stop(self) -> None:
        for t in self._evictions.values():
            t.cancel()
        await super().stop()
