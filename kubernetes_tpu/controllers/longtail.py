"""Controller long tail (SURVEY §2.4 bottom rows): EndpointSlice,
ResourceQuota, Disruption (PDB + eviction API), TTL-after-finished, HPA.

Parity targets:
- pkg/controller/endpointslice/ — Service selector → EndpointSlice objects
  (ready = pod Running + Ready condition; address = status.podIP).
- pkg/controller/resourcequota/ + plugin/pkg/admission/resourcequota —
  usage accounting in status.used, enforcement at pod admission.
- pkg/controller/disruption/ + pkg/registry/core/pod/storage `EvictionREST`
  — PDB accounting (currentHealthy / disruptionsAllowed) and the
  pods/eviction subresource that refuses voluntary evictions when the
  budget is exhausted (429 in the reference; Conflict here).
- pkg/controller/ttlafterfinished/ — delete finished Jobs after their
  `ttlSecondsAfterFinished`.
- pkg/controller/podautoscaler/horizontal.go — HPA. Divergence: there is
  no metrics-server in this simulator; the metric source is the pods'
  `ktpu.dev/load` annotation (average utilization per pod, percent),
  which tests/KWOK set. The scaling rule is the reference's
  desired = ceil(current × avgLoad / target).
"""

from __future__ import annotations

import asyncio
import logging
import math
import time

from kubernetes_tpu.api.meta import (
    name_of,
    namespaced_name,
    new_object,
    uid_of,
)
from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import pod_is_terminal, pod_requests
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import Conflict, StoreError

logger = logging.getLogger(__name__)


def make_service(name: str, selector: dict, namespace: str = "default",
                 port: int = 80) -> dict:
    return new_object("Service", name, namespace, spec={
        "selector": dict(selector),
        "ports": [{"port": port, "protocol": "TCP"}]})


def make_pdb(name: str, selector: dict, *, min_available: int | None = None,
             max_unavailable: int | None = None,
             namespace: str = "default") -> dict:
    spec: dict = {"selector": dict(selector)}
    if min_available is not None:
        spec["minAvailable"] = min_available
    if max_unavailable is not None:
        spec["maxUnavailable"] = max_unavailable
    return new_object("PodDisruptionBudget", name, namespace, spec=spec)


def make_resource_quota(name: str, hard: dict,
                        namespace: str = "default") -> dict:
    return new_object("ResourceQuota", name, namespace,
                      spec={"hard": dict(hard)})


def make_hpa(name: str, target_ref: str, *, min_replicas: int = 1,
             max_replicas: int = 10, target_utilization: int = 80,
             namespace: str = "default") -> dict:
    """targetRef: "deployments/<name>"."""
    return new_object(
        "HorizontalPodAutoscaler", name, namespace,
        api_version="autoscaling/v2",
        spec={"scaleTargetRef": target_ref,
              "minReplicas": min_replicas, "maxReplicas": max_replicas,
              "targetUtilizationPercent": target_utilization})


def _pod_ready(pod: dict) -> bool:
    if pod.get("status", {}).get("phase") != "Running":
        return False
    return any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in pod.get("status", {}).get("conditions") or [])


def _selector_matches(selector: dict, labels: dict) -> bool:
    from kubernetes_tpu.api.labels import from_label_selector
    sel = selector if ("matchLabels" in selector
                       or "matchExpressions" in selector) \
        else {"matchLabels": selector}
    return from_label_selector(sel).matches(labels or {})


class EndpointSliceController(Controller):
    """Service → one EndpointSlice (named after the service)."""

    NAME = "endpointslice"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services")
        self.pod_informer = factory.informer("pods")
        self.eps_informer = factory.informer("endpointslices")
        self.watch_resource(factory, "services")

        def pod_changed(obj):
            ns = obj.get("metadata", {}).get("namespace", "default")
            for svc in self.svc_informer.indexer.list():
                if svc.get("metadata", {}).get("namespace") != ns:
                    continue
                if _selector_matches(svc.get("spec", {}).get("selector")
                                     or {}, obj.get("metadata", {})
                                     .get("labels")):
                    asyncio.ensure_future(
                        self.queue.add(namespaced_name(svc)))

        def pod_updated(old, new):
            # Both label sets: a relabel can REMOVE the pod from a
            # service's selector (the reference processes old and new).
            pod_changed(old)
            pod_changed(new)

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=pod_changed,
            on_update=pod_updated,
            on_delete=pod_changed))

    async def resync_keys(self):
        return [namespaced_name(s) for s in self.svc_informer.indexer.list()]

    async def sync(self, key: str) -> None:
        svc = self.svc_informer.indexer.get(key)
        if svc is None:
            # Service deleted → its slice goes too.
            try:
                await self.store.delete("endpointslices", key)
            except StoreError:
                pass
            return
        ns = svc["metadata"].get("namespace", "default")
        selector = svc.get("spec", {}).get("selector") or {}
        endpoints = []
        for pod in self.pod_informer.indexer.list():
            if pod.get("metadata", {}).get("namespace") != ns:
                continue
            if pod_is_terminal(pod):
                continue
            if not _selector_matches(selector,
                                     pod.get("metadata", {}).get("labels")):
                continue
            ip = pod.get("status", {}).get("podIP")
            if not ip:
                continue
            endpoints.append({
                "addresses": [ip],
                "conditions": {"ready": _pod_ready(pod)},
                "targetRef": {"kind": "Pod",
                              "name": pod["metadata"]["name"],
                              "uid": uid_of(pod)},
                "nodeName": pod.get("spec", {}).get("nodeName"),
            })
        endpoints.sort(key=lambda e: e["addresses"][0])

        def mutate(eps):
            eps["endpoints"] = endpoints
            eps["ports"] = svc.get("spec", {}).get("ports") or []
            return eps
        try:
            await self.store.guaranteed_update(
                "endpointslices", key, mutate, return_copy=False)
        except StoreError:
            eps = new_object("EndpointSlice", name_of(svc), ns)
            eps["addressType"] = "IPv4"
            eps["endpoints"] = endpoints
            eps["ports"] = svc.get("spec", {}).get("ports") or []
            eps["metadata"]["ownerReferences"] = [{
                "kind": "Service", "name": name_of(svc),
                "uid": uid_of(svc), "controller": True}]
            try:
                await self.store.create("endpointslices", eps)
            except StoreError:
                pass


#: resource names ResourceQuota tracks (requests.* aliases fold onto bare).
_QUOTA_KEYS = ("pods", "cpu", "memory", "requests.cpu", "requests.memory")


def _quota_usage(pods: list[dict], namespace: str) -> dict[str, int]:
    used = {"pods": 0, "cpu": 0, "memory": 0}
    for p in pods:
        if p.get("metadata", {}).get("namespace") != namespace:
            continue
        if pod_is_terminal(p):
            continue
        used["pods"] += 1
        reqs = pod_requests(p)
        used["cpu"] += reqs.get("cpu", 0)
        used["memory"] += reqs.get("memory", 0)
    return used


class ResourceQuotaController(Controller):
    """Recompute status.used for every quota (the admission check reads
    live tables; this controller is the user-facing accounting)."""

    NAME = "resourcequota"
    WORKERS = 1
    RESYNC_PERIOD = 2.0

    def setup(self, factory: InformerFactory) -> None:
        self.rq_informer = factory.informer("resourcequotas")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "resourcequotas")

        def pod_changed(obj):
            ns = obj.get("metadata", {}).get("namespace", "default")
            for rq in self.rq_informer.indexer.list():
                if rq.get("metadata", {}).get("namespace") == ns:
                    asyncio.ensure_future(
                        self.queue.add(namespaced_name(rq)))

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=pod_changed,
            on_update=lambda old, new: pod_changed(new),
            on_delete=pod_changed))

    async def resync_keys(self):
        return [namespaced_name(r) for r in self.rq_informer.indexer.list()]

    async def sync(self, key: str) -> None:
        rq = self.rq_informer.indexer.get(key)
        if rq is None:
            return
        ns = rq["metadata"].get("namespace", "default")
        used = _quota_usage(self.pod_informer.indexer.list(), ns)

        def mutate(obj):
            hard = obj.get("spec", {}).get("hard") or {}
            st = obj.setdefault("status", {})
            st["hard"] = dict(hard)
            from kubernetes_tpu.api.resource import format_quantity
            shown = {}
            for k in hard:
                base = k.split(".")[-1]
                if base == "pods":
                    shown[k] = str(used["pods"])
                elif base in ("cpu", "memory"):
                    shown[k] = format_quantity(used[base])
            st["used"] = shown
            return obj
        try:
            await self.store.guaranteed_update(
                "resourcequotas", key, mutate, return_copy=False)
        except StoreError:
            pass


def install_quota_admission(store) -> None:
    """Admission enforcement (plugin/pkg/admission/resourcequota): a pod
    create that would exceed any quota in its namespace is rejected."""

    def check(pod: dict) -> None:
        ns = pod.get("metadata", {}).get("namespace", "default")
        quotas = [q for q in store._table("resourcequotas").values()
                  if q.get("metadata", {}).get("namespace") == ns]
        if not quotas:
            return
        reqs = pod_requests(pod)
        key = f"{ns}/{pod.get('metadata', {}).get('name', '')}"
        old = store._table("pods").get(key)
        delta_pods = 1
        old_cpu = old_mem = 0
        if old is not None and not pod_is_terminal(old):
            delta_pods = 0
            old_reqs = pod_requests(old)
            old_cpu = old_reqs.get("cpu", 0)
            old_mem = old_reqs.get("memory", 0)
        d_cpu = reqs.get("cpu", 0) - old_cpu
        d_mem = reqs.get("memory", 0) - old_mem
        # Quota only gates usage-INCREASING writes (the reference):
        # bindings, status flips and request-lowering updates pass without
        # even scanning the table — an over-quota namespace must not wedge
        # pod lifecycle, and this is the store's hottest write path.
        if delta_pods <= 0 and d_cpu <= 0 and d_mem <= 0:
            return
        used = _quota_usage(list(store._table("pods").values()), ns)
        want = {"pods": used["pods"] + delta_pods,
                "cpu": used["cpu"] + d_cpu,
                "memory": used["memory"] + d_mem}
        from kubernetes_tpu.store.mvcc import Invalid
        for q in quotas:
            for k, limit in (q.get("spec", {}).get("hard") or {}).items():
                base = k.split(".")[-1]
                if base not in want:
                    continue
                lim = int(limit) if base == "pods" else parse_quantity(limit)
                if want[base] > lim:
                    raise Invalid(
                        f"exceeded quota {name_of(q)!r}: requested "
                        f"{base} would exceed hard limit {limit}")

    # Both operations: this store's update() is a full replace with no
    # spec-immutability validation, so PUT could otherwise raise requests
    # past the quota.
    store.register_mutator("pods", check, on=("create", "update"))


class DisruptionController(Controller):
    """PDB status accounting + the eviction gate."""

    NAME = "disruption"
    WORKERS = 1
    RESYNC_PERIOD = 2.0

    def setup(self, factory: InformerFactory) -> None:
        self.pdb_informer = factory.informer("poddisruptionbudgets")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "poddisruptionbudgets")

        def pod_changed(obj):
            ns = obj.get("metadata", {}).get("namespace", "default")
            for pdb in self.pdb_informer.indexer.list():
                if pdb.get("metadata", {}).get("namespace") != ns:
                    continue
                asyncio.ensure_future(self.queue.add(namespaced_name(pdb)))

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=pod_changed,
            on_update=lambda old, new: pod_changed(new),
            on_delete=pod_changed))

    async def resync_keys(self):
        return [namespaced_name(p)
                for p in self.pdb_informer.indexer.list()]

    async def sync(self, key: str) -> None:
        pdb = self.pdb_informer.indexer.get(key)
        if pdb is None:
            return
        ns = pdb["metadata"].get("namespace", "default")
        selector = pdb.get("spec", {}).get("selector") or {}
        matching = [p for p in self.pod_informer.indexer.list()
                    if p.get("metadata", {}).get("namespace") == ns
                    and not pod_is_terminal(p)
                    and _selector_matches(
                        selector, p.get("metadata", {}).get("labels"))]
        healthy = sum(1 for p in matching if _pod_ready(p))
        allowed = _disruptions_allowed(pdb, len(matching), healthy)

        def mutate(obj):
            obj.setdefault("status", {}).update({
                "expectedPods": len(matching),
                "currentHealthy": healthy,
                "disruptionsAllowed": allowed,
            })
            return obj
        try:
            await self.store.guaranteed_update(
                "poddisruptionbudgets", key, mutate, return_copy=False)
        except StoreError:
            pass


def _disruptions_allowed(pdb: dict, expected: int, healthy: int) -> int:
    spec = pdb.get("spec", {})
    if "minAvailable" in spec:
        return max(0, healthy - int(spec["minAvailable"]))
    if "maxUnavailable" in spec:
        unavailable = expected - healthy
        return max(0, int(spec["maxUnavailable"]) - unavailable)
    return max(0, healthy - expected)  # no constraint → allow none missing


def install_eviction_subresource(store) -> None:
    """POST pods/<key>/eviction (EvictionREST): voluntary eviction that a
    PDB with zero disruptionsAllowed refuses with Conflict (429/
    TooManyRequests in the reference's wire form). Also installs the
    reference's PDB validation (exactly one of minAvailable /
    maxUnavailable) — a field-less PDB would block every eviction."""
    from kubernetes_tpu.store.mvcc import Invalid

    def validate_pdb(pdb: dict) -> None:
        spec = pdb.get("spec") or {}
        if ("minAvailable" in spec) == ("maxUnavailable" in spec):
            raise Invalid(
                "PodDisruptionBudget: exactly one of minAvailable or "
                "maxUnavailable must be set")

    store.register_validator("poddisruptionbudgets", validate_pdb)

    async def evict(store_, key: str, body) -> dict:
        pod = await store_.get("pods", key)
        ns = pod.get("metadata", {}).get("namespace", "default")
        labels = pod.get("metadata", {}).get("labels") or {}
        for pdb in store_._table("poddisruptionbudgets").values():
            if pdb.get("metadata", {}).get("namespace") != ns:
                continue
            sel = pdb.get("spec", {}).get("selector") or {}
            if not _selector_matches(sel, labels):
                continue
            # Recount LIVE (the controller's status lags events; the
            # reference's EvictionREST consumes the budget synchronously).
            matching = [
                q for q in store_._table("pods").values()
                if q.get("metadata", {}).get("namespace") == ns
                and not pod_is_terminal(q)
                and _selector_matches(
                    sel, q.get("metadata", {}).get("labels"))]
            healthy = sum(1 for q in matching if _pod_ready(q))
            if _disruptions_allowed(pdb, len(matching), healthy) <= 0:
                raise Conflict(
                    f"Cannot evict pod as it would violate the pod's "
                    f"disruption budget {name_of(pdb)!r}")
        await store_.delete("pods", key)
        return {"kind": "Status", "apiVersion": "v1", "status": "Success"}

    store.register_subresource("pods", "eviction", evict)


class TTLAfterFinishedController(Controller):
    """Delete finished Jobs `ttlSecondsAfterFinished` after completion."""

    NAME = "ttl-after-finished"
    WORKERS = 1
    RESYNC_PERIOD = 1.0

    def setup(self, factory: InformerFactory) -> None:
        self.job_informer = factory.informer("jobs")
        self.watch_resource(factory, "jobs")

    async def resync_keys(self):
        return [namespaced_name(j) for j in self.job_informer.indexer.list()
                if j.get("spec", {}).get("ttlSecondsAfterFinished")
                is not None]

    async def sync(self, key: str) -> None:
        job = self.job_informer.indexer.get(key)
        if job is None:
            return
        ttl = job.get("spec", {}).get("ttlSecondsAfterFinished")
        if ttl is None:
            return
        conds = (job.get("status") or {}).get("conditions") or []
        done = [c for c in conds
                if c.get("type") in ("Complete", "Failed")
                and c.get("status") == "True"]
        if not done:
            return
        raw = done[0].get("lastTransitionTime")
        finished_at = None
        if isinstance(raw, (int, float)):
            finished_at = float(raw)
        elif isinstance(raw, str):
            import datetime
            try:
                finished_at = datetime.datetime.fromisoformat(
                    raw.replace("Z", "+00:00")).timestamp()
            except ValueError:
                pass
        if finished_at is None:
            return  # nil completion time → never TTL-delete (reference)
        if time.time() - finished_at < float(ttl):
            return  # not due yet; the 1s resync re-enqueues it
        try:
            await self.store.delete("jobs", key, uid=uid_of(job))
            logger.info("ttl-after-finished: deleted job %s", key)
        except StoreError:
            pass


class HorizontalPodAutoscalerController(Controller):
    """HPA over the `ktpu.dev/load` annotation as the metric source (no
    metrics-server in the simulator — see module docstring)."""

    NAME = "horizontal-pod-autoscaler"
    WORKERS = 1
    RESYNC_PERIOD = 1.0

    def setup(self, factory: InformerFactory) -> None:
        self.hpa_informer = factory.informer("horizontalpodautoscalers")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "horizontalpodautoscalers")

    async def resync_keys(self):
        return [namespaced_name(h)
                for h in self.hpa_informer.indexer.list()]

    async def sync(self, key: str) -> None:
        hpa = self.hpa_informer.indexer.get(key)
        if hpa is None:
            return
        spec = hpa.get("spec", {})
        target_res, _, target_name = spec.get(
            "scaleTargetRef", "").partition("/")
        if not target_name:
            return
        ns = hpa["metadata"].get("namespace", "default")
        try:
            target = await self.store.get(target_res, f"{ns}/{target_name}")
        except StoreError:
            return
        sel = (target.get("spec", {}).get("selector") or {})
        pods = [p for p in self.pod_informer.indexer.list()
                if p.get("metadata", {}).get("namespace") == ns
                and not pod_is_terminal(p)
                and _selector_matches(
                    sel, p.get("metadata", {}).get("labels"))]
        if not pods:
            return
        loads = [float((p.get("metadata", {}).get("annotations") or {})
                       .get("ktpu.dev/load", 0)) for p in pods]
        avg = sum(loads) / len(loads)
        current = int(target.get("spec", {}).get("replicas", len(pods)))
        tgt = float(spec.get("targetUtilizationPercent", 80))
        desired = max(int(spec.get("minReplicas", 1)),
                      min(int(spec.get("maxReplicas", 10)),
                          math.ceil(current * avg / tgt) if avg else
                          int(spec.get("minReplicas", 1))))
        if desired == current:
            return

        def scale(obj):
            obj.setdefault("spec", {})["replicas"] = desired
            return obj
        try:
            await self.store.guaranteed_update(
                target_res, f"{ns}/{target_name}", scale, return_copy=False)
            logger.info("hpa %s: scaled %s/%s %d → %d (avg load %.0f%%)",
                        key, target_res, target_name, current, desired, avg)
        except StoreError:
            pass
