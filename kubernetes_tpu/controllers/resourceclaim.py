"""ResourceClaim controller (DRA lifecycle).

Parity target: `pkg/controller/resourceclaim/controller.go` (SURVEY §2.4
long tail). Responsibilities:

- For pods referencing a ResourceClaimTemplate, stamp out a per-pod
  ResourceClaim named `<pod>-<ref name>` with an ownerReference to the pod
  (the generated claim dies with the pod via the GC cascade, and is also
  deleted directly here for promptness).
- When a consumer pod terminates or disappears, remove it from every
  referenced claim's status.reservedFor.
- When reservedFor drains empty on a GENERATED claim, delete it; on a
  user-created claim, clear status.allocation (deallocate) so the devices
  return to the pool.
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api.meta import (
    name_of,
    namespace_of,
    namespaced_name,
    new_object,
)
from kubernetes_tpu.api.types import pod_is_terminal
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)

#: annotation marking a claim generated from a template for one pod.
GENERATED_FOR_ANN = "resource.kubernetes.io/pod-claim-name"


class ResourceClaimController(Controller):
    NAME = "resourceclaim"
    WORKERS = 2

    def __init__(self, store):
        super().__init__(store)
        #: recently deleted pod key -> uid, so release can match
        #: reservedFor entries by uid (a recreated same-name pod's
        #: reservation must survive the OLD pod's cleanup).
        self._deleted_uids: dict[str, str] = {}
        #: consumer index (pod key -> claim keys naming it in reservedFor)
        #: so release is O(pod's claims), not O(all claims).
        self._claims_by_consumer: dict[str, set[str]] = {}
        self._claim_consumers: dict[str, set[str]] = {}

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")
        self.claim_informer = factory.informer("resourceclaims")
        self.template_informer = factory.informer("resourceclaimtemplates")
        self.watch_resource(factory, "pods")

        def remember_uid(obj):
            uid = (obj.get("metadata") or {}).get("uid")
            if uid:
                self._deleted_uids[namespaced_name(obj)] = uid
                if len(self._deleted_uids) > 4096:
                    for k in list(self._deleted_uids)[:2048]:
                        self._deleted_uids.pop(k, None)

        factory.informer("pods").add_event_handler(ResourceEventHandler(
            on_delete=remember_uid))
        # Claim events re-sync their consumers (reservedFor names pods)
        # and maintain the consumer index.

        def claim_event(obj, gone=False):
            import asyncio
            key = namespaced_name(obj)
            ns = namespace_of(obj) or "default"
            new = set() if gone else {
                f"{ns}/{r['name']}"
                for r in (obj.get("status") or {}).get("reservedFor") or []
                if r.get("name")}
            old = self._claim_consumers.get(key, set())
            for pod_key in old - new:
                bucket = self._claims_by_consumer.get(pod_key)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        self._claims_by_consumer.pop(pod_key, None)
            for pod_key in new - old:
                self._claims_by_consumer.setdefault(
                    pod_key, set()).add(key)
            if new:
                self._claim_consumers[key] = new
            else:
                self._claim_consumers.pop(key, None)
            for pod_key in new:
                asyncio.ensure_future(self.queue.add(pod_key))

        def tmpl_arrived(obj):
            # Pods that referenced this template before it existed parked
            # with a warning; stamp their claims now (template creation is
            # rare, so the pod scan here is off the hot path).
            import asyncio
            ns = namespace_of(obj) or "default"
            tmpl_name = name_of(obj)
            for pod in self.pod_informer.indexer.list():
                if (namespace_of(pod) or "default") != ns:
                    continue
                for ref in (pod.get("spec") or {}) \
                        .get("resourceClaims") or []:
                    if ref.get("resourceClaimTemplateName") == tmpl_name:
                        asyncio.ensure_future(
                            self.queue.add(namespaced_name(pod)))
                        break

        factory.informer("resourceclaimtemplates").add_event_handler(
            ResourceEventHandler(on_add=tmpl_arrived))
        factory.informer("resourceclaims").add_event_handler(
            ResourceEventHandler(
                on_add=claim_event,
                on_update=lambda old, new: claim_event(new),
                on_delete=lambda obj: claim_event(obj, gone=True)))

    async def sync(self, key: str) -> None:
        pod = self.pod_informer.indexer.get(key)
        if pod is None or pod_is_terminal(pod):
            await self._release_consumer(key, pod)
            return
        await self._ensure_generated_claims(pod)

    # -- template → claim stamping ----------------------------------------

    async def _ensure_generated_claims(self, pod: dict) -> None:
        ns = namespace_of(pod) or "default"
        for ref in (pod.get("spec") or {}).get("resourceClaims") or []:
            tmpl_name = ref.get("resourceClaimTemplateName")
            if not tmpl_name:
                continue
            claim_name = f"{name_of(pod)}-{ref.get('name', '')}"
            if self.claim_informer.indexer.get(f"{ns}/{claim_name}"):
                continue
            tmpl = self.template_informer.indexer.get(f"{ns}/{tmpl_name}")
            if tmpl is None:
                try:
                    tmpl = await self.store.get(
                        "resourceclaimtemplates", f"{ns}/{tmpl_name}")
                except NotFound:
                    logger.warning(
                        "pod %s references missing template %s/%s",
                        name_of(pod), ns, tmpl_name)
                    continue
            claim = new_object("ResourceClaim", claim_name, ns,
                               api_version="resource.k8s.io/v1")
            claim["spec"] = dict(tmpl.get("spec") or {})
            claim["metadata"]["annotations"] = {
                GENERATED_FOR_ANN: ref.get("name", "")}
            claim["metadata"]["ownerReferences"] = [{
                "apiVersion": "v1", "kind": "Pod", "name": name_of(pod),
                "uid": pod.get("metadata", {}).get("uid", ""),
                "controller": True}]
            try:
                await self.store.create("resourceclaims", claim,
                                        return_copy=False)
            except StoreError as e:
                logger.debug("claim %s create raced: %s", claim_name, e)

    # -- consumer release --------------------------------------------------

    async def _release_consumer(self, pod_key: str, pod: dict | None) -> None:
        """Drop `pod` from reservedFor on every claim naming it; then
        delete drained generated claims / deallocate drained user claims."""
        ns, _, pod_name = pod_key.partition("/")
        # Match by uid when we know it: a recreated same-name pod's fresh
        # reservation must NOT be dropped by the old pod's cleanup.
        pod_uid = (pod or {}).get("metadata", {}).get("uid") \
            or self._deleted_uids.get(pod_key)

        def names_pod(r) -> bool:
            if r.get("name") != pod_name:
                return False
            entry_uid = r.get("uid")
            if pod_uid and entry_uid and entry_uid != pod_uid:
                return False  # some OTHER incarnation's reservation
            return True

        claim_keys = sorted(self._claims_by_consumer.get(pod_key, ()))
        for ck in claim_keys:
            claim = self.claim_informer.indexer.get(ck)
            if claim is None:
                continue
            reserved = (claim.get("status") or {}).get("reservedFor") or []
            if not any(names_pod(r) for r in reserved):
                continue
            key = namespaced_name(claim)
            generated = GENERATED_FOR_ANN in (
                claim.get("metadata", {}).get("annotations") or {})
            owner_uids = {r.get("uid")
                          for r in claim.get("metadata", {})
                          .get("ownerReferences") or []}

            def drop(obj):
                status = obj.setdefault("status", {})
                before = status.get("reservedFor") or []
                after = [r for r in before if not names_pod(r)]
                if len(after) == len(before):
                    return None
                status["reservedFor"] = after
                if not after and not generated:
                    # Deallocate: devices return to the pool (the
                    # reference's deallocation for delayed-release claims).
                    status.pop("allocation", None)
                return obj

            try:
                await self.store.guaranteed_update(
                    "resourceclaims", key, drop, return_copy=False)
                if generated and (pod is None or pod_is_terminal(pod)) \
                        and (not pod_uid or not owner_uids
                             or pod_uid in owner_uids):
                    # Generated claims die with their pod (ownerRef GC
                    # would too; direct delete keeps the pool prompt).
                    # A claim owned by a NEWER same-name incarnation is
                    # left alone — its owner is alive.
                    await self.store.delete("resourceclaims", key)
            except NotFound:
                pass
            except StoreError:
                logger.exception("releasing claim %s failed", key)
                await self.enqueue_after(pod_key, 0.5)
