"""Pod garbage collector.

Parity target: pkg/controller/podgc/gc_controller.go: periodically deletes
(a) pods bound to nodes that no longer exist ("orphaned"), (b) terminated
pods beyond a threshold, (c) unscheduled terminating pods.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import pod_is_terminal
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)


class PodGCController(Controller):
    NAME = "podgc"
    WORKERS = 1

    def __init__(self, store, *, gc_period: float = 2.0,
                 terminated_pod_threshold: int = 0):
        super().__init__(store)
        self.gc_period = gc_period
        self.terminated_pod_threshold = terminated_pod_threshold

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")
        self.node_informer = factory.informer("nodes")

    def start(self) -> None:
        super().start()
        self._tasks.append(asyncio.ensure_future(self._gc_loop()))

    async def _gc_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.gc_period)
            try:
                await self.gc_once()
            except Exception:
                logger.exception("podgc pass failed")

    async def gc_once(self) -> int:
        nodes = {n["metadata"]["name"]
                 for n in self.node_informer.indexer.list()}
        deleted = 0
        terminated: list[dict] = []
        for pod in self.pod_informer.indexer.list():
            node = pod.get("spec", {}).get("nodeName")
            if node and node not in nodes:
                # gcOrphaned: bound to a vanished node.
                deleted += await self._delete(pod)
            elif pod_is_terminal(pod):
                terminated.append(pod)
        if self.terminated_pod_threshold > 0 and \
                len(terminated) > self.terminated_pod_threshold:
            terminated.sort(
                key=lambda p: p["metadata"].get("creationTimestamp", ""))
            excess = len(terminated) - self.terminated_pod_threshold
            for pod in terminated[:excess]:
                deleted += await self._delete(pod)
        return deleted

    async def _delete(self, pod: dict) -> int:
        try:
            await self.store.delete("pods", namespaced_name(pod))
            return 1
        except StoreError:
            return 0

    async def sync(self, key: str) -> None:
        return
