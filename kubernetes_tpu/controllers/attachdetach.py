"""Attach/detach controller: VolumeAttachment lifecycle.

Parity target: pkg/controller/volume/attachdetach (SURVEY §2.4 "PV binder
/ attach-detach"): reconcile the desired state (pods scheduled to nodes
referencing PV-backed PVCs) against the actual state (VolumeAttachment
objects), attaching volumes to the pods' nodes and detaching them when no
pod on the node uses the volume any more.

VolumeAttachment (storage.k8s.io, cluster-scoped) shape:
    spec: {attacher, nodeName, source: {persistentVolumeName}}
    status: {attached: bool}

There is no real CSI driver here — "attach" completes immediately (the
external-attacher analog is the controller itself), but the object
lifecycle, naming (`va-<pv>-<node>`), and multi-pod refcount semantics
match the reference so schedulers/kubelets-analogs can observe it.
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api.meta import namespaced_name, new_object
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)

DEFAULT_ATTACHER = "attach.ktpu.dev"


def attachment_name(pv: str, node: str) -> str:
    return f"va-{pv}-{node}"


class AttachDetachController(Controller):
    NAME = "attachdetach"
    WORKERS = 2
    RESYNC_PERIOD = 2.0

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")
        self.pvc_informer = factory.informer("persistentvolumeclaims")
        self.pv_informer = factory.informer("persistentvolumes")
        self.va_informer = factory.informer("volumeattachments")
        # Any pod/PVC/attachment movement re-reconciles the world; the
        # desired state is small enough to diff whole (the reference
        # keeps a DesiredStateOfWorld cache for the same diff).
        self.watch_resource(factory, "pods", key_fn=lambda o: "~all")
        self.watch_resource(factory, "persistentvolumeclaims",
                            key_fn=lambda o: "~all")
        self.watch_resource(factory, "volumeattachments",
                            key_fn=lambda o: "~all")

    async def resync_keys(self):
        return ["~all"]

    def _desired(self) -> dict[str, tuple[str, str]]:
        """attachment name -> (pv, node) for every (PV, node) pair some
        scheduled pod references through a bound PVC."""
        from kubernetes_tpu.api.types import pod_is_terminal
        want: dict[str, tuple[str, str]] = {}
        for pod in self.pod_informer.indexer.list():
            node = (pod.get("spec") or {}).get("nodeName")
            if not node or pod_is_terminal(pod):
                # Terminated pods release their volumes (the reference's
                # DesiredStateOfWorld excludes them).
                continue
            ns = pod["metadata"].get("namespace", "default")
            for vol in (pod.get("spec") or {}).get("volumes") or []:
                claim = (vol.get("persistentVolumeClaim") or {}) \
                    .get("claimName")
                if not claim:
                    continue
                pvc = self.pvc_informer.indexer.get(f"{ns}/{claim}")
                if pvc is None:
                    continue
                pv = (pvc.get("spec") or {}).get("volumeName")
                if not pv:
                    continue
                want[attachment_name(pv, node)] = (pv, node)
        return want

    async def sync(self, key: str) -> None:
        want = self._desired()
        have = {va["metadata"]["name"]: va
                for va in self.va_informer.indexer.list()}
        # Attach: desired but absent.
        for name, (pv, node) in want.items():
            if name in have:
                continue
            va = new_object(
                "VolumeAttachment", name, None,
                api_version="storage.k8s.io/v1",
                spec={"attacher": DEFAULT_ATTACHER, "nodeName": node,
                      "source": {"persistentVolumeName": pv}},
                status={"attached": False})
            try:
                await self.store.create("volumeattachments", va,
                                        return_copy=False)
            except AlreadyExists:
                pass
            except StoreError:
                logger.exception("attach %s failed", name)
                continue
            await self._mark_attached(name)
        # Mark attached any pending ones (controller restart).
        for name, va in have.items():
            if name in want and not (va.get("status") or {}) \
                    .get("attached"):
                await self._mark_attached(name)
        # Detach: attached but no longer desired.
        for name in set(have) - set(want):
            try:
                await self.store.delete("volumeattachments", name)
            except StoreError:
                pass

    async def _mark_attached(self, name: str) -> None:
        def mark(obj):
            status = obj.setdefault("status", {})
            if status.get("attached"):
                return None
            status["attached"] = True
            return obj
        try:
            await self.store.guaranteed_update(
                "volumeattachments", name, mark, return_copy=False)
        except NotFound:
            pass
