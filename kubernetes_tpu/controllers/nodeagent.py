"""Node-agent behaviors beyond KWOK's lifecycle: probes + node-pressure
eviction (SURVEY §2.5 `prober/`, `eviction/eviction_manager.go`).

The kubelet-less world (KWOK) fakes containers, so probes are staged:
a pod annotated `kwok.x-k8s.io/fail-readiness-after: "<seconds>"` flips
its Ready condition False after that long — consumed by EndpointSlices
(endpoint drops out of rotation) exactly as a real readiness failure
would be. `kwok.x-k8s.io/fail-liveness-after` additionally bumps
`restartCount` and flips Ready back True (the kubelet restarts the
container), the prober → container-restart loop.

Node-pressure eviction mirrors `eviction_manager.go`: when a node's
requested memory exceeds `threshold` × allocatable, the manager taints it
`node.kubernetes.io/memory-pressure:NoSchedule` and evicts pods —
lowest priority first, biggest memory request first within a priority —
until below threshold; the taint lifts when pressure clears.
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.api.meta import namespaced_name, uid_of
from kubernetes_tpu.api.types import pod_is_terminal, pod_requests
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)

READINESS_ANN = "kwok.x-k8s.io/fail-readiness-after"
LIVENESS_ANN = "kwok.x-k8s.io/fail-liveness-after"
PRESSURE_TAINT = "node.kubernetes.io/memory-pressure"


class ProberController(Controller):
    """Staged probe outcomes for KWOK pods."""

    NAME = "prober"
    WORKERS = 2
    RESYNC_PERIOD = 0.5

    def setup(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods")
        #: pod key -> monotonic time it went Running (probe clocks).
        self._running_since: dict[str, float] = {}

        def on_pod(obj):
            key = namespaced_name(obj)
            if obj.get("status", {}).get("phase") == "Running":
                self._running_since.setdefault(key, time.monotonic())
                anns = obj.get("metadata", {}).get("annotations") or {}
                if READINESS_ANN in anns or LIVENESS_ANN in anns:
                    asyncio.ensure_future(self.queue.add(key))
            else:
                self._running_since.pop(key, None)

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=on_pod, on_update=lambda o, n: on_pod(n),
            on_delete=lambda o: self._running_since.pop(
                namespaced_name(o), None)))

    async def resync_keys(self):
        out = []
        for key in self._running_since:
            pod = self.pod_informer.indexer.get(key)
            if pod is None:
                continue
            anns = pod.get("metadata", {}).get("annotations") or {}
            if READINESS_ANN in anns or LIVENESS_ANN in anns:
                out.append(key)
        return out

    async def sync(self, key: str) -> None:
        pod = self.pod_informer.indexer.get(key)
        since = self._running_since.get(key)
        if pod is None or since is None:
            return
        anns = pod.get("metadata", {}).get("annotations") or {}
        elapsed = time.monotonic() - since

        def _after(name: str) -> bool:
            if name not in anns:
                return False
            try:
                return elapsed >= float(anns[name])
            except (TypeError, ValueError):
                return False  # malformed annotation → probe disabled

        fail_ready = _after(READINESS_ANN)
        fail_live = _after(LIVENESS_ANN)
        if not fail_ready and not fail_live:
            return

        def mutate(p):
            st = p.setdefault("status", {})
            conds = st.setdefault("conditions", [])
            ready = next((c for c in conds if c.get("type") == "Ready"),
                         None)
            if ready is None:
                ready = {"type": "Ready", "status": "True"}
                conds.append(ready)
            if fail_live:
                # Liveness failure → kubelet restarts the container:
                # restartCount++ and the pod comes back Ready.
                st["restartCount"] = int(st.get("restartCount", 0)) + 1
                ready["status"] = "True"
                anns2 = p["metadata"].setdefault("annotations", {})
                anns2.pop(LIVENESS_ANN, None)  # one staged failure
            elif fail_ready:
                if ready["status"] == "False":
                    return None
                ready["status"] = "False"
            return p
        try:
            await self.store.guaranteed_update(
                "pods", key, mutate, return_copy=False)
        except StoreError:
            pass
        if fail_live:
            self._running_since[key] = time.monotonic()


class NodePressureEvictionController(Controller):
    """eviction_manager.go analog over requested (not measured) memory."""

    NAME = "node-pressure-eviction"
    WORKERS = 1
    RESYNC_PERIOD = 1.0

    def __init__(self, store, threshold: float = 0.9):
        super().__init__(store)
        self.threshold = threshold

    def setup(self, factory: InformerFactory) -> None:
        self.node_informer = factory.informer("nodes")
        self.pod_informer = factory.informer("pods")
        # nodeName index: _memory_state must not scan every pod per node
        # per second (O(nodes × pods) at 5k/10k scale).
        self.pod_informer.indexer.add_indexer(
            "nodeName", lambda o: [o.get("spec", {}).get("nodeName")]
            if o.get("spec", {}).get("nodeName") else [])
        self.watch_resource(factory, "nodes", key_fn=lambda o: o[
            "metadata"]["name"])

        def pod_changed(obj):
            node = obj.get("spec", {}).get("nodeName")
            if node:
                asyncio.ensure_future(self.queue.add(node))

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=pod_changed, on_update=lambda o, n: pod_changed(n),
            on_delete=pod_changed))

    async def resync_keys(self):
        return [n["metadata"]["name"]
                for n in self.node_informer.indexer.list()]

    def _memory_state(self, node: dict) -> tuple[int, int, list[dict]]:
        from kubernetes_tpu.api.resource import parse_quantity
        alloc = parse_quantity(
            (node.get("status", {}).get("allocatable") or {})
            .get("memory", 0))
        name = node["metadata"]["name"]
        residents = [p for p in self.pod_informer.indexer.by_index(
                         "nodeName", name)
                     if not pod_is_terminal(p)]
        used = sum(pod_requests(p).get("memory", 0) for p in residents)
        return used, alloc, residents

    async def sync(self, key: str) -> None:
        node = self.node_informer.indexer.get(key)
        if node is None:
            return
        used, alloc, residents = self._memory_state(node)
        over = alloc > 0 and used > self.threshold * alloc
        tainted = any(t.get("key") == PRESSURE_TAINT
                      for t in node.get("spec", {}).get("taints") or [])

        if over:
            if not tainted:
                await self._set_taint(key, True)
            # Evict until under threshold: lowest priority first, largest
            # memory request first within a priority (rankMemoryPressure).
            victims = sorted(
                residents,
                key=lambda p: (p.get("spec", {}).get("priority", 0) or 0,
                               -pod_requests(p).get("memory", 0)))
            for victim in victims:
                if used <= self.threshold * alloc:
                    break
                vkey = namespaced_name(victim)
                try:
                    await self.store.delete("pods", vkey,
                                            uid=uid_of(victim))
                    logger.info(
                        "node-pressure eviction: evicted %s from %s",
                        vkey, key)
                except StoreError:
                    pass  # already gone (stale cache) — still freed
                # Count the memory freed either way: a NotFound means the
                # pod is gone regardless, and NOT decrementing would march
                # down the victim list evicting live pods.
                used -= pod_requests(victim).get("memory", 0)
        elif tainted:
            await self._set_taint(key, False)

    async def _set_taint(self, node_name: str, on: bool) -> None:
        def mutate(n):
            taints = n.setdefault("spec", {}).setdefault("taints", [])
            has = any(t.get("key") == PRESSURE_TAINT for t in taints)
            if on and not has:
                taints.append({"key": PRESSURE_TAINT,
                               "effect": "NoSchedule"})
            elif not on and has:
                n["spec"]["taints"] = [
                    t for t in taints if t.get("key") != PRESSURE_TAINT]
            else:
                return None
            return n
        try:
            await self.store.guaranteed_update(
                "nodes", node_name, mutate, return_copy=False)
        except StoreError:
            pass
