"""kube-proxy analog: the Service VIP → endpoints dataplane programmer.

Parity target: pkg/proxy (SURVEY §2.6) — `servicechangetracker.go` /
`endpointschangetracker.go` accumulate deltas from the Service and
EndpointSlice watches, and `iptables/proxier.go syncProxyRules` compiles
the WHOLE dataplane atomically on a min-sync-period cadence. There is no
kernel here, so the "dataplane" is an in-memory rules table with the same
compile-everything-atomically semantics, plus a `lookup()` that does what
the kernel's DNAT would: pick a ready endpoint for a (clusterIP, port)
round-robin. ClusterIPs are allocated at Service admission
(`install_service_ip_allocator` — the apiserver's RangeRegistry analog).
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.api.meta import name_of, namespaced_name
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller

logger = logging.getLogger(__name__)

SERVICE_CIDR_PREFIX = "10.96"


def install_service_ip_allocator(store) -> None:
    """Allocate spec.clusterIP at Service create (the apiserver's service
    IP RangeRegistry). Sequential over 10.96.0.0/16; explicit clusterIP
    (including "None" for headless Services) is respected."""
    seq = [0]

    def allocate(svc: dict) -> None:
        spec = svc.setdefault("spec", {})
        if spec.get("clusterIP"):
            return
        in_use = {(s.get("spec") or {}).get("clusterIP")
                  for s in store._table("services").values()}
        for _ in range(254 * 256):
            seq[0] += 1
            hi, lo = divmod(seq[0], 254)
            ip = f"{SERVICE_CIDR_PREFIX}.{hi % 256}.{lo + 1}"
            # Skip explicitly-claimed VIPs (the RangeRegistry behavior —
            # two Services must never share a clusterIP).
            if ip not in in_use:
                spec["clusterIP"] = ip
                return
        from kubernetes_tpu.store.mvcc import Invalid
        raise Invalid("service IP range exhausted")

    store.register_mutator("services", allocate, on=("create",))


class KubeProxyController(Controller):
    """One simulated proxier (a node's dataplane view).

    Watches Services + EndpointSlices; every change marks the table dirty
    and ONE sync compiles the full rules snapshot — `syncProxyRules` is
    a full-table rewrite, never an incremental patch — throttled by
    `min_sync_period` exactly like the reference's async runner.
    """

    NAME = "kube-proxy"
    WORKERS = 1
    RESYNC_PERIOD = 5.0

    #: the single queue key: the dataplane syncs as a whole.
    _KEY = "__sync__"

    def __init__(self, store, min_sync_period: float = 0.05):
        super().__init__(store)
        self.min_sync_period = min_sync_period
        #: compiled dataplane: (clusterIP, port) → list of "ip:port" ready
        #: backends. Replaced atomically by _compile.
        self.rules: dict[tuple[str, int], list[str]] = {}
        self.sync_count = 0
        self._last_sync = 0.0
        self._rr: dict[tuple[str, int], int] = {}

    def setup(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services")
        self.eps_informer = factory.informer("endpointslices")

        def dirty(*_a):
            asyncio.ensure_future(self.queue.add(self._KEY))

        for inf in (self.svc_informer, self.eps_informer):
            inf.add_event_handler(ResourceEventHandler(
                on_add=dirty, on_update=lambda o, n: dirty(),
                on_delete=dirty))

    async def resync_keys(self):
        return [self._KEY]

    async def sync(self, key: str) -> None:
        # min-sync-period batching: coalesce bursts into one rewrite.
        now = time.monotonic()
        wait = self.min_sync_period - (now - self._last_sync)
        if wait > 0:
            await asyncio.sleep(wait)
        self._last_sync = time.monotonic()
        self._compile()

    def _compile(self) -> None:
        """The syncProxyRules analog: full atomic rewrite from the caches."""
        slices = {namespaced_name(e): e
                  for e in self.eps_informer.indexer.list()}
        rules: dict[tuple[str, int], list[str]] = {}
        for svc in self.svc_informer.indexer.list():
            try:
                self._compile_service(svc, slices, rules)
            except Exception:
                # One malformed Service must not brick the whole table —
                # syncProxyRules is a full rewrite, so a raised error here
                # would freeze dataplane programming for EVERY service.
                logger.exception("kube-proxy: skipping service %s",
                                 namespaced_name(svc))
        self.rules = rules
        # Prune round-robin state for rules that no longer exist, or
        # service churn grows it without bound.
        self._rr = {k: v for k, v in self._rr.items() if k in rules}
        self.sync_count += 1

    @staticmethod
    def _compile_service(svc: dict, slices: dict,
                         rules: dict[tuple[str, int], list[str]]) -> None:
        vip = (svc.get("spec") or {}).get("clusterIP")
        if not vip or vip == "None":
            return  # headless
        eps = slices.get(namespaced_name(svc))
        for port_spec in (svc.get("spec") or {}).get("ports") or []:
            port = int(port_spec.get("port", 0))
            raw_target = port_spec.get("targetPort", port)
            try:
                target = int(raw_target)
            except (TypeError, ValueError):
                # Named targetPort: the reference resolves it via the
                # endpoint's port list; our slices carry the service
                # ports verbatim, so fall back to the service port.
                target = port
            backends: list[str] = []
            for ep in (eps or {}).get("endpoints") or []:
                if not (ep.get("conditions") or {}).get("ready"):
                    continue
                for addr in ep.get("addresses") or []:
                    backends.append(f"{addr}:{target}")
            rules[(vip, port)] = sorted(backends)

    def lookup(self, cluster_ip: str, port: int) -> str | None:
        """What the kernel DNAT would do: round-robin over ready backends
        (iptables statistic mode / IPVS rr)."""
        backends = self.rules.get((cluster_ip, port))
        if not backends:
            return None
        k = (cluster_ip, port)
        i = self._rr.get(k, 0)
        self._rr[k] = (i + 1) % len(backends)
        return backends[i % len(backends)]
