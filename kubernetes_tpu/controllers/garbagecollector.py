"""Garbage collector + namespace lifecycle controllers.

Parity targets:
- pkg/controller/garbagecollector/ (`GarbageCollector`, `GraphBuilder`):
  an ownerReference dependency graph over watched resources; deleting an
  owner cascades (background policy) to its dependents, and dependents
  whose owner never existed / already vanished are collected on sight.
- pkg/controller/namespace/ (`NamespaceController`): deleting a Namespace
  fans out to every namespaced object inside it.

Divergences, by design: the reference resolves the watchable set from
API-server discovery and honors foreground-deletion finalizers; this
store has a fixed resource list (`GC_RESOURCES`, extendable) and hard
deletes, so cascade is always the background policy. `orphan` semantics
(ownerReference removal instead of deletion) are honored when a
dependent carries the `kubernetes.io/orphan` finalizer-equivalent
annotation.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import (
    name_of,
    namespaced_name,
    owner_references_of,
    uid_of,
)
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import StoreError

logger = logging.getLogger(__name__)

#: Resources participating in the ownerReference graph (both as owners and
#: dependents). Order matters only for readability.
GC_RESOURCES = [
    "pods",
    "replicasets",
    "deployments",
    "jobs",
    "statefulsets",
    "daemonsets",
    "podgroups",
    "persistentvolumeclaims",
    "resourceclaims",
]

#: Namespaced resources purged on namespace deletion.
NAMESPACED_RESOURCES = GC_RESOURCES + ["events", "leases"]

#: ownerReference kind → resource (shared mapping; see api/meta.py).
#: Owners of kinds OUTSIDE the GC's WATCHED resources are never treated
#: as collectable (a Node-owned mirror pod or a custom resource's
#: dependent must not be GC'd just because we don't watch the owner).
from kubernetes_tpu.api.meta import (  # noqa: E402
    CLUSTER_SCOPED_RESOURCES,
    KIND_TO_RESOURCE,
)


class GarbageCollectorController(Controller):
    """ownerReference graph → cascade deletion (background policy)."""

    NAME = "garbage-collector"
    WORKERS = 2
    RESYNC_PERIOD = 5.0

    def __init__(self, store, resources: list[str] | None = None):
        super().__init__(store)
        self.resources = list(resources or GC_RESOURCES)
        #: live owner uids (from watched resources).
        self._alive: set[str] = set()
        #: owner uid -> {(resource, dependent key)}.
        self._dependents: dict[str, set[tuple[str, str]]] = {}
        #: dependent (resource, key) -> set of owner uids it waits on.
        self._owners_of: dict[tuple[str, str], set[str]] = {}

    def _resource_for(self, kind: str | None) -> str | None:
        """Owner-kind resolution includes the store's CRD-registered kinds
        (store-local since ADVICE r3), falling back to the built-ins for
        remote stores without the accessor."""
        f = getattr(self.store, "resource_for_kind", None)
        return f(kind) if f else KIND_TO_RESOURCE.get(kind)

    def _cluster_scoped(self, resource: str) -> bool:
        f = getattr(self.store, "is_cluster_scoped", None)
        return f(resource) if f else resource in CLUSTER_SCOPED_RESOURCES

    def setup(self, factory: InformerFactory) -> None:
        self._informers = {}
        for resource in self.resources:
            inf = factory.informer(resource)
            self._informers[resource] = inf

            def on_add(obj, resource=resource):
                self._track(resource, obj)

            def on_update(old, new, resource=resource):
                self._track(resource, new)

            def on_delete(obj, resource=resource):
                self._on_delete(resource, obj)

            inf.add_event_handler(ResourceEventHandler(
                on_add=on_add, on_update=on_update, on_delete=on_delete))

    def _track(self, resource: str, obj: dict) -> None:
        uid = uid_of(obj)
        if uid:
            self._alive.add(uid)
        dep = (resource, namespaced_name(obj))
        refs = owner_references_of(obj)
        old_owners = self._owners_of.pop(dep, set())
        for ouid in old_owners:
            self._dependents.get(ouid, set()).discard(dep)
        if not refs:
            return
        # Only owners of WATCHED resources enter the graph: a Node-owned
        # mirror pod (or any unwatched kind) must never be tracked, or the
        # resync sweep would re-enqueue + re-verify it forever.
        # Accumulate first, write the graph only once every ref is watched:
        # writing _dependents per-ref and bailing on a later unwatched ref
        # would leave orphaned entries (map leak) that enqueue spurious
        # sync work for objects the GC will always keep.
        owners = set()
        for ref in refs:
            owner_res = self._resource_for(ref.get("kind"))
            if owner_res is None or owner_res not in self.resources:
                return  # any unwatched owner kind ⇒ never collectable
            ouid = ref.get("uid")
            if ouid:
                owners.add(ouid)
        if not owners:
            return
        for ouid in owners:
            self._dependents.setdefault(ouid, set()).add(dep)
        self._owners_of[dep] = owners
        # Owner already gone (or never seen after sync) → collect now.
        if not any(o in self._alive for o in owners):
            asyncio.ensure_future(self.queue.add(f"{resource}|{dep[1]}"))

    def _on_delete(self, resource: str, obj: dict) -> None:
        uid = uid_of(obj)
        self._alive.discard(uid)
        # The deleted object's OWN dependent bookkeeping must go too, or
        # resync_keys re-enqueues its dead key forever and the maps leak.
        dep = (resource, namespaced_name(obj))
        for ouid in self._owners_of.pop(dep, set()):
            self._dependents.get(ouid, set()).discard(dep)
        for d in self._dependents.pop(uid, set()):
            asyncio.ensure_future(self.queue.add(f"{d[0]}|{d[1]}"))

    async def resync_keys(self):
        # Orphan sweep: dependents whose every owner uid is dead.
        out = []
        for (resource, key), owners in list(self._owners_of.items()):
            if owners and not any(o in self._alive for o in owners):
                out.append(f"{resource}|{key}")
        return out

    async def sync(self, key: str) -> None:
        resource, _, obj_key = key.partition("|")
        inf = self._informers.get(resource)
        obj = inf.indexer.get(obj_key) if inf is not None else None
        if obj is None:
            return
        refs = owner_references_of(obj)
        if not refs:
            return
        if any(ref.get("uid") in self._alive for ref in refs):
            return  # an owner still exists (fast path)
        # Authoritative verify against the store (the reference GC checks
        # the API before cascading): the in-memory graph can lag its own
        # informers, and unwatched owner kinds are NEVER collectable.
        ns = obj.get("metadata", {}).get("namespace", "default")
        for ref in refs:
            owner_res = self._resource_for(ref.get("kind"))
            if owner_res is None or owner_res not in self.resources:
                # An owner of an UNWATCHED kind (Node, custom resource,
                # ...) is never collectable — keep the dependent.
                return
            owner_key = ref.get("name") \
                if self._cluster_scoped(owner_res) \
                else f"{ns}/{ref.get('name')}"
            try:
                owner = await self.store.get(owner_res, owner_key)
            except StoreError:
                continue  # this owner really is gone
            if not ref.get("uid") or uid_of(owner) == ref.get("uid"):
                return  # owner alive (uid matches) → keep dependent
        anns = obj.get("metadata", {}).get("annotations") or {}
        if anns.get("kubernetes.io/orphan") == "true":
            # Orphan policy: strip ownerReferences, keep the object.
            def strip(o):
                o["metadata"].pop("ownerReferences", None)
                return o
            try:
                await self.store.guaranteed_update(
                    resource, obj_key, strip, return_copy=False)
            except StoreError:
                pass
            return
        logger.info("GC: cascading delete %s/%s (owners gone)",
                    resource, obj_key)
        try:
            await self.store.delete(resource, obj_key, uid=uid_of(obj))
        except StoreError:
            pass


class NamespaceController(Controller):
    """Namespace deletion fan-out: purge every namespaced object in a
    deleted namespace (namespace/namespace_controller.go `syncNamespace`
    deletion path, minus finalizer staging — deletes here are hard)."""

    NAME = "namespace"
    WORKERS = 1

    def __init__(self, store, resources: list[str] | None = None):
        super().__init__(store)
        self.resources = list(resources or NAMESPACED_RESOURCES)

    def setup(self, factory: InformerFactory) -> None:
        self._ns_informer = factory.informer("namespaces")

        def on_delete(obj):
            asyncio.ensure_future(self.queue.add(name_of(obj)))

        self._ns_informer.add_event_handler(ResourceEventHandler(
            on_delete=on_delete))

    async def sync(self, key: str) -> None:
        # Namespace gone → delete everything inside it.
        for resource in self.resources:
            try:
                items = (await self.store.list(resource)).items
            except StoreError:
                continue
            for obj in items:
                if obj.get("metadata", {}).get("namespace") != key:
                    continue
                try:
                    await self.store.delete(
                        resource, namespaced_name(obj), uid=uid_of(obj))
                except StoreError:
                    pass
