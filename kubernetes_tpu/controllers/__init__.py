"""Controllers tier: reconcile loops over the store (SURVEY §2.4/§3.4)."""

from kubernetes_tpu.controllers.attachdetach import AttachDetachController
from kubernetes_tpu.controllers.base import Controller, ControllerManager
from kubernetes_tpu.controllers.cronjob import (
    CronJobController,
    CronSchedule,
    make_cronjob,
)
from kubernetes_tpu.controllers.daemonset import (
    DaemonSetController,
    make_daemonset,
)
from kubernetes_tpu.controllers.deployment import (
    DeploymentController,
    make_deployment,
)
from kubernetes_tpu.controllers.descheduler import DeschedulerController
from kubernetes_tpu.controllers.garbagecollector import (
    GarbageCollectorController,
    NamespaceController,
)
from kubernetes_tpu.controllers.job import JobController, make_job
from kubernetes_tpu.controllers.longtail import (
    DisruptionController,
    EndpointSliceController,
    HorizontalPodAutoscalerController,
    ResourceQuotaController,
    TTLAfterFinishedController,
    install_eviction_subresource,
    install_quota_admission,
    make_hpa,
    make_pdb,
    make_resource_quota,
    make_service,
)
from kubernetes_tpu.controllers.kubeproxy import (
    KubeProxyController,
    install_service_ip_allocator,
)
from kubernetes_tpu.controllers.nodeagent import (
    NodePressureEvictionController,
    ProberController,
)
from kubernetes_tpu.controllers.kwok import KwokController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.pvbinder import PVBinderController
from kubernetes_tpu.controllers.replicaset import (
    ReplicaSetController,
    make_replicaset,
)
from kubernetes_tpu.controllers.resourceclaim import ResourceClaimController
from kubernetes_tpu.controllers.serviceaccount import (
    ServiceAccountAuthenticator,
    ServiceAccountController,
    TokenController,
)
from kubernetes_tpu.controllers.statefulset import (
    StatefulSetController,
    make_statefulset,
)

__all__ = [
    "NodePressureEvictionController",
    "ProberController",
    "KubeProxyController",
    "install_service_ip_allocator",
    "DisruptionController",
    "EndpointSliceController",
    "HorizontalPodAutoscalerController",
    "ResourceQuotaController",
    "TTLAfterFinishedController",
    "install_eviction_subresource",
    "install_quota_admission",
    "make_hpa", "make_pdb", "make_resource_quota", "make_service",
    "GarbageCollectorController",
    "NamespaceController",
    "Controller", "ControllerManager",
    "DaemonSetController", "make_daemonset",
    "DeschedulerController",
    "DeploymentController", "make_deployment",
    "JobController", "make_job",
    "KwokController", "NodeLifecycleController", "PodGCController",
    "PVBinderController",
    "ReplicaSetController", "make_replicaset",
    "ResourceClaimController",
    "AttachDetachController",
    "CronJobController", "CronSchedule", "make_cronjob",
    "ServiceAccountAuthenticator", "ServiceAccountController",
    "TokenController",
    "StatefulSetController", "make_statefulset",
]
