"""Job controller: run-to-completion workloads.

Parity target: pkg/controller/job/job_controller.go (`Controller.syncJob`,
`manageJob`): parallelism/completions accounting, NonIndexed + Indexed
completion modes, backoffLimit → Failed condition, activeDeadlineSeconds,
Complete condition + completionTime. SURVEY §2.4 calls Job "the
gang-adjacent batch workload" — on TPU clusters it is the shape most
training launches take, so Indexed mode (stable per-replica identity) is
first-class here.
"""

from __future__ import annotations

import logging
import time

from kubernetes_tpu.api.meta import namespaced_name, new_object, now_iso, uid_of
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_ref, _controller_of
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)

#: job_controller.go DefaultJobApiBackoffLimit.
DEFAULT_BACKOFF_LIMIT = 6


def make_job(name: str, *, parallelism: int = 1, completions: int | None = None,
             template: dict | None = None, namespace: str = "default",
             completion_mode: str = "NonIndexed",
             backoff_limit: int = DEFAULT_BACKOFF_LIMIT,
             active_deadline_seconds: float | None = None) -> dict:
    spec = {
        "parallelism": parallelism,
        "template": template or {"spec": {"containers": [
            {"name": "main", "image": "app"}]}},
        "completionMode": completion_mode,
        "backoffLimit": backoff_limit,
    }
    if completions is not None:
        spec["completions"] = completions
    if active_deadline_seconds is not None:
        spec["activeDeadlineSeconds"] = active_deadline_seconds
    return new_object("Job", name, namespace, spec=spec, status={})


def _phase(pod: dict) -> str:
    return (pod.get("status") or {}).get("phase", "Pending")


class JobController(Controller):
    NAME = "job"
    WORKERS = 4
    RESYNC_PERIOD = 5.0

    def setup(self, factory: InformerFactory) -> None:
        self.job_informer = factory.informer("jobs")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "jobs")
        self.watch_owned_pods(factory, "Job")

    async def resync_keys(self):
        return [namespaced_name(j) for j in self.job_informer.indexer.list()]

    def _owned_pods(self, job: dict) -> list[dict]:
        ns = job["metadata"].get("namespace", "default")
        juid = uid_of(job)
        out = []
        for pod in self.pod_informer.indexer.list():
            if pod["metadata"].get("namespace", "default") != ns:
                continue
            ref = _controller_of(pod)
            if ref is None or ref.get("kind") != "Job" \
                    or ref.get("name") != job["metadata"]["name"]:
                continue
            if ref.get("uid") and juid and ref["uid"] != juid:
                continue
            out.append(pod)
        return out

    @staticmethod
    def _finished(job: dict) -> bool:
        return any(c.get("type") in ("Complete", "Failed")
                   and c.get("status") == "True"
                   for c in (job.get("status") or {}).get("conditions") or [])

    async def sync(self, key: str) -> None:
        job = self.job_informer.indexer.get(key)
        if job is None or self._finished(job):
            return
        spec = job.get("spec") or {}
        parallelism = int(spec.get("parallelism", 1))
        completions = spec.get("completions")
        indexed = spec.get("completionMode") == "Indexed"
        if indexed and completions is None:
            completions = parallelism  # validation requires it; be lenient
        backoff_limit = int(spec.get("backoffLimit", DEFAULT_BACKOFF_LIMIT))
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]

        pods = self._owned_pods(job)
        active = [p for p in pods if _phase(p) not in ("Succeeded", "Failed")
                  and not p["metadata"].get("deletionTimestamp")]

        # CUMULATIVE terminal accounting (job_controller.go with the
        # JobTrackingWithFinalizers semantics): live terminal pods are
        # counted into status ONCE, keyed by uid — so eviction/GC deleting a
        # finished pod cannot regress succeeded/failed or re-run completed
        # indexes. The counted-uid sets are bounded by total pod churn of
        # one job (status-internal analog of uncountedTerminatedPods).
        status = job.get("status") or {}
        counted = set(status.get("countedTerminatedUIDs") or [])
        n_succeeded = int(status.get("succeeded", 0))
        n_failed = int(status.get("failed", 0))
        completed_idx = set(status.get("completedIndexes") or [])
        new_uids: list[str] = []
        for p in pods:
            phase = _phase(p)
            if phase not in ("Succeeded", "Failed"):
                continue
            uid = uid_of(p) or namespaced_name(p)
            if uid in counted:
                continue
            new_uids.append(uid)
            if phase == "Succeeded":
                idx = (p["metadata"].get("annotations") or {}).get(
                    "batch.kubernetes.io/job-completion-index")
                if indexed:
                    if idx is not None and idx not in completed_idx:
                        completed_idx.add(idx)
                        n_succeeded += 1
                else:
                    n_succeeded += 1
            else:
                n_failed += 1

        # Terminal transitions first (syncJob ordering).
        deadline = spec.get("activeDeadlineSeconds")
        start = status.get("startTime")
        past_deadline = False
        if deadline is not None and start is not None:
            past_deadline = time.time() - _parse_ts(start) > float(deadline)
        if n_failed > backoff_limit or past_deadline:
            for p in active:
                try:
                    await self.store.delete("pods", namespaced_name(p))
                except NotFound:
                    pass
            reason = "DeadlineExceeded" if past_deadline else \
                "BackoffLimitExceeded"
            await self._update_status(
                key, active=0, succeeded=n_succeeded, failed=n_failed,
                new_uids=new_uids, completed_idx=completed_idx,
                condition=("Failed", reason))
            return
        complete = (completions is not None and n_succeeded >= completions) \
            or (completions is None and n_succeeded > 0 and not active)
        if complete:
            await self._update_status(
                key, active=0, succeeded=n_succeeded, failed=n_failed,
                new_uids=new_uids, completed_idx=completed_idx,
                condition=("Complete", "Completed"))
            return

        # manageJob: create up to parallelism active pods, bounded by
        # remaining completions.
        want_active = parallelism
        if completions is not None:
            want_active = min(parallelism, completions - n_succeeded)
        diff = want_active - len(active)
        n_active = len(active)
        if diff > 0:
            if indexed:
                have_idx = {(p["metadata"].get("annotations") or {})
                            .get("batch.kubernetes.io/job-completion-index")
                            for p in active} | completed_idx
                missing = [i for i in range(int(completions))
                           if str(i) not in have_idx][:diff]
                for i in missing:
                    await self._create_pod(job, ns, name, index=i)
                n_active += len(missing)
            else:
                for _ in range(diff):
                    await self._create_pod(job, ns, name)
                n_active += diff
        elif diff < 0:
            for p in active[:(-diff)]:
                try:
                    await self.store.delete("pods", namespaced_name(p))
                except NotFound:
                    pass
            n_active += diff
        await self._update_status(
            key, active=n_active, succeeded=n_succeeded, failed=n_failed,
            new_uids=new_uids, completed_idx=completed_idx, condition=None,
            set_start=start is None)

    async def _create_pod(self, job: dict, ns: str, name: str,
                          index: int | None = None) -> None:
        template = (job.get("spec") or {}).get("template") or {}
        meta = dict(template.get("metadata") or {})
        labels = dict(meta.get("labels") or {})
        labels.setdefault("job-name", name)
        pod_name = f"{name}-{index}" if index is not None \
            else f"{name}-{self._suffix()}"
        annotations = dict(meta.get("annotations") or {})
        if index is not None:
            annotations["batch.kubernetes.io/job-completion-index"] = str(index)
            labels["batch.kubernetes.io/job-completion-index"] = str(index)
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": pod_name, "namespace": ns, "labels": labels,
                "annotations": annotations,
                "ownerReferences": [owner_ref(job)],
            },
            "spec": dict(template.get("spec") or {}),
            "status": {"phase": "Pending"},
        }
        if not pod["spec"].get("containers"):
            pod["spec"]["containers"] = [{"name": "main", "image": "app"}]
        pod["spec"].setdefault("restartPolicy", "Never")
        try:
            await self.store.create("pods", pod)
        except StoreError as e:
            logger.warning("job %s/%s: create pod failed: %s", ns, name, e)

    async def _update_status(self, key: str, *, active: int, succeeded: int,
                             failed: int, new_uids: list[str],
                             completed_idx: set[str],
                             condition: tuple[str, str] | None,
                             set_start: bool = False) -> None:
        def mutate(obj):
            st = obj.setdefault("status", {})
            st["active"] = active
            # Counters only move forward (cumulative semantics survive a
            # racing stale sync).
            st["succeeded"] = max(succeeded, int(st.get("succeeded", 0)))
            st["failed"] = max(failed, int(st.get("failed", 0)))
            if new_uids:
                st["countedTerminatedUIDs"] = sorted(
                    set(st.get("countedTerminatedUIDs") or []) | set(new_uids))
            if completed_idx:
                st["completedIndexes"] = sorted(
                    set(st.get("completedIndexes") or []) | completed_idx)
            if set_start and not st.get("startTime"):
                st["startTime"] = now_iso()
            if condition is not None:
                ctype, reason = condition
                conds = st.setdefault("conditions", [])
                if not any(c.get("type") == ctype for c in conds):
                    conds.append({"type": ctype, "status": "True",
                                  "reason": reason,
                                  "lastTransitionTime": now_iso()})
                if ctype == "Complete":
                    st["completionTime"] = now_iso()
                st["active"] = 0
            return obj
        try:
            await self.store.guaranteed_update("jobs", key, mutate)
        except NotFound:
            pass

    _seq = 0

    @classmethod
    def _suffix(cls) -> str:
        cls._seq += 1
        return f"{cls._seq:05d}"


def _parse_ts(ts: str) -> float:
    from datetime import datetime
    try:
        return datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return time.time()
