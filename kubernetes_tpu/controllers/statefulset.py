"""StatefulSet controller: ordered, stable-identity pods (+ per-pod PVCs).

Parity target: pkg/controller/statefulset/ (stateful_set.go,
stateful_set_control.go `UpdateStatefulSet`): pods named <set>-<ordinal>,
created strictly in ordinal order (OrderedReady waits for the previous
ordinal to be Running before creating the next; podManagementPolicy:
Parallel creates all at once), scaled down highest-ordinal-first, stable
`statefulset.kubernetes.io/pod-name` label, volumeClaimTemplates → one PVC
per (template × pod) that survives pod deletion.
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api.meta import namespaced_name, new_object, uid_of
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_ref, _controller_of
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)


def make_statefulset(name: str, replicas: int, selector: dict, template: dict,
                     namespace: str = "default",
                     pod_management_policy: str = "OrderedReady",
                     volume_claim_templates: list | None = None) -> dict:
    spec = {"replicas": replicas, "selector": selector, "template": template,
            "podManagementPolicy": pod_management_policy,
            "serviceName": name}
    if volume_claim_templates:
        spec["volumeClaimTemplates"] = volume_claim_templates
    return new_object("StatefulSet", name, namespace, spec=spec, status={})


class StatefulSetController(Controller):
    NAME = "statefulset"
    WORKERS = 2
    RESYNC_PERIOD = 2.0

    def setup(self, factory: InformerFactory) -> None:
        self.sts_informer = factory.informer("statefulsets")
        self.pod_informer = factory.informer("pods")
        self.watch_resource(factory, "statefulsets")

        self.watch_owned_pods(factory, "StatefulSet")

    async def resync_keys(self):
        return [namespaced_name(s) for s in self.sts_informer.indexer.list()]

    def _owned_pods(self, sts: dict) -> dict[int, dict]:
        """ordinal → pod."""
        ns = sts["metadata"].get("namespace", "default")
        base = sts["metadata"]["name"] + "-"
        suid = uid_of(sts)
        out: dict[int, dict] = {}
        for pod in self.pod_informer.indexer.list():
            if pod["metadata"].get("namespace", "default") != ns:
                continue
            ref = _controller_of(pod)
            if ref is None or ref.get("kind") != "StatefulSet" \
                    or ref.get("name") != sts["metadata"]["name"]:
                continue
            if ref.get("uid") and suid and ref["uid"] != suid:
                continue
            name = pod["metadata"]["name"]
            if not name.startswith(base):
                continue
            try:
                out[int(name[len(base):])] = pod
            except ValueError:
                continue
        return out

    @staticmethod
    def _running(pod: dict) -> bool:
        return (pod.get("status") or {}).get("phase") == "Running"

    async def sync(self, key: str) -> None:
        sts = self.sts_informer.indexer.get(key)
        if sts is None:
            return
        spec = sts.get("spec") or {}
        want = int(spec.get("replicas", 1))
        ordered = spec.get("podManagementPolicy", "OrderedReady") != "Parallel"
        ns = sts["metadata"].get("namespace", "default")
        pods = self._owned_pods(sts)

        # Scale up: create missing ordinals in order; OrderedReady stops at
        # the first ordinal whose predecessor isn't Running yet. Terminal
        # pods are deleted for recreation (stateful_set_control.go replaces
        # failed replicas) so an OrderedReady walk can't deadlock on one.
        for i in range(want):
            pod = pods.get(i)
            if pod is None:
                await self._create_pod(sts, ns, i)
                if ordered:
                    break  # wait for it to come up before the next ordinal
            elif (pod.get("status") or {}).get("phase") in ("Failed",
                                                            "Succeeded"):
                try:
                    await self.store.delete("pods", namespaced_name(pod))
                except NotFound:
                    pass
                if ordered:
                    break  # recreate on the next poke
            elif ordered and not self._running(pod):
                break  # predecessor must be Running before creating i+1

        # Scale down: delete highest ordinals first, one at a time when
        # ordered (stateful_set_control.go scale-down walk).
        excess = sorted((i for i in pods if i >= want), reverse=True)
        for i in excess if not ordered else excess[:1]:
            try:
                await self.store.delete("pods", namespaced_name(pods[i]))
            except NotFound:
                pass

        def set_status(obj):
            st = obj.setdefault("status", {})
            st["replicas"] = sum(1 for i in pods if i < want)
            st["readyReplicas"] = sum(
                1 for i, p in pods.items() if i < want and self._running(p))
            st["currentReplicas"] = st["replicas"]
            st["observedGeneration"] = obj["metadata"].get("generation", 0)
            return obj
        try:
            await self.store.guaranteed_update("statefulsets", key, set_status)
        except NotFound:
            pass

    async def _create_pod(self, sts: dict, ns: str, ordinal: int) -> None:
        name = f"{sts['metadata']['name']}-{ordinal}"
        template = (sts["spec"].get("template") or {})
        labels = dict((template.get("metadata") or {}).get("labels")
                      or (sts["spec"].get("selector") or {})
                      .get("matchLabels") or {})
        labels["statefulset.kubernetes.io/pod-name"] = name
        spec = dict(template.get("spec") or {})
        if not spec.get("containers"):
            spec["containers"] = [{"name": "main", "image": "app"}]
        # volumeClaimTemplates → stable per-pod PVCs (<claim>-<pod>); they
        # are NOT owned by the pod — identity survives pod deletion.
        vcts = sts["spec"].get("volumeClaimTemplates") or []
        for vct in vcts:
            claim_name = f"{vct['metadata']['name']}-{name}"
            pvc = new_object(
                "PersistentVolumeClaim", claim_name, ns,
                spec=dict(vct.get("spec") or {}), status={"phase": "Pending"})
            pvc["metadata"]["labels"] = dict(labels)
            try:
                await self.store.create("persistentvolumeclaims", pvc)
            except AlreadyExists:
                pass  # stable identity: reuse the surviving claim
            except StoreError as e:
                logger.warning("sts %s: create PVC %s failed: %s",
                               key_str(sts), claim_name, e)
        if vcts:
            spec = dict(spec)
            spec["volumes"] = list(spec.get("volumes") or []) + [
                {"name": vct["metadata"]["name"],
                 "persistentVolumeClaim": {
                     "claimName": f"{vct['metadata']['name']}-{name}"}}
                for vct in vcts]
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns, "labels": labels,
                         "ownerReferences": [owner_ref(sts)]},
            "spec": spec,
            "status": {"phase": "Pending"},
        }
        try:
            await self.store.create("pods", pod)
        except AlreadyExists:
            pass
        except StoreError as e:
            logger.warning("sts %s: create pod %s failed: %s",
                           key_str(sts), name, e)


def key_str(obj: dict) -> str:
    return namespaced_name(obj)
