"""CronJob controller: scheduled Job spawning.

Parity target: pkg/controller/cronjob/cronjob_controllerv2.go
(`syncCronJob`): compute the most recent schedule time since
status.lastScheduleTime, honor startingDeadlineSeconds, apply
concurrencyPolicy (Allow | Forbid | Replace), stamp Jobs named
`<cronjob>-<scheduled-unix-minute>` with ownerReferences, and trim
finished Jobs to the success/failure history limits.

The cron expression parser supports the standard five fields
(minute hour day-of-month month day-of-week) with `*`, lists, ranges
and `*/step` — the subset the reference's robfig/cron usage relies on.
"""

from __future__ import annotations

import logging
import time as _time
from datetime import datetime, timedelta, timezone

from kubernetes_tpu.api.meta import namespaced_name, new_object, uid_of
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import _controller_of
from kubernetes_tpu.store.mvcc import AlreadyExists, NotFound, StoreError

logger = logging.getLogger(__name__)


def make_cronjob(name: str, schedule: str, *, namespace: str = "default",
                 job_template: dict | None = None,
                 concurrency_policy: str = "Allow",
                 starting_deadline_seconds: float | None = None,
                 suspend: bool = False,
                 successful_jobs_history_limit: int = 3,
                 failed_jobs_history_limit: int = 1) -> dict:
    spec = {
        "schedule": schedule,
        "concurrencyPolicy": concurrency_policy,
        "suspend": suspend,
        "jobTemplate": job_template or {"spec": {
            "template": {"spec": {"containers": [
                {"name": "main", "image": "app"}]}}}},
        "successfulJobsHistoryLimit": successful_jobs_history_limit,
        "failedJobsHistoryLimit": failed_jobs_history_limit,
    }
    if starting_deadline_seconds is not None:
        spec["startingDeadlineSeconds"] = starting_deadline_seconds
    return new_object("CronJob", name, namespace, spec=spec, status={})


# -- cron expression math ---------------------------------------------------


def _parse_field(field: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
        if not (lo <= start <= hi and lo <= end <= hi and step >= 1):
            raise ValueError(f"cron field out of range: {field!r}")
        out.update(range(start, end + 1, step))
    return out


class CronSchedule:
    """Compiled five-field cron expression."""

    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields: {spec!r}")
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31)
        self.months = _parse_field(fields[3], 1, 12)
        # cron dow: 0 and 7 are both Sunday; python weekday(): Mon=0.
        dow = _parse_field(fields[4], 0, 7)
        self.dow = {(d % 7) for d in dow}
        # robfig/cron star semantics: any field BEGINNING with '*'
        # (including "*/n") carries the star bit — dom AND dow then,
        # vixie OR only when both are restricted lists.
        self.dom_star = fields[2].startswith("*")
        self.dow_star = fields[4].startswith("*")

    def _day_matches(self, t: datetime) -> bool:
        dom_ok = t.day in self.dom
        dow_ok = ((t.weekday() + 1) % 7) in self.dow  # cron Sun=0
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # vixie-cron OR semantics

    def next_after(self, after: datetime) -> datetime:
        """First matching minute strictly after `after` (UTC); raises
        ValueError for valid-but-never-firing specs (e.g. Feb 30)."""
        t = after.replace(second=0, microsecond=0) + timedelta(minutes=1)
        # Horizon-bounded walk (not iteration-bounded): the hierarchical
        # jumps advance at least a month/day/hour per miss, so scanning
        # five years of a never-firing spec is a few thousand steps, not
        # a multi-second minute-by-minute grind.
        horizon = t + timedelta(days=366 * 5)
        while t <= horizon:
            if t.month not in self.months:
                # jump to the 1st of the next month
                if t.month == 12:
                    t = t.replace(year=t.year + 1, month=1, day=1,
                                  hour=0, minute=0)
                else:
                    t = t.replace(month=t.month + 1, day=1,
                                  hour=0, minute=0)
                continue
            if not self._day_matches(t):
                t = t.replace(hour=0, minute=0) + timedelta(days=1)
                continue
            if t.hour not in self.hours:
                t = t.replace(minute=0) + timedelta(hours=1)
                continue
            if t.minute not in self.minutes:
                t += timedelta(minutes=1)
                continue
            return t
        raise ValueError("cron schedule never fires")


def _parse_time(s: str | None) -> datetime | None:
    if not s:
        return None
    return datetime.fromisoformat(s.replace("Z", "+00:00"))


def _fmt_time(t: datetime) -> str:
    return t.astimezone(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


class CronJobController(Controller):
    NAME = "cronjob"
    WORKERS = 2
    RESYNC_PERIOD = 1.0

    def __init__(self, store, *, now=None):
        super().__init__(store)
        #: injectable clock (tests drive schedules without waiting).
        self.now = now or (lambda: datetime.fromtimestamp(
            _time.time(), tz=timezone.utc))

    def setup(self, factory: InformerFactory) -> None:
        self.cron_informer = factory.informer("cronjobs")
        self.job_informer = factory.informer("jobs")
        self.watch_resource(factory, "cronjobs")
        self.watch_owned(factory, "jobs", "CronJob")

    async def resync_keys(self):
        return [namespaced_name(c)
                for c in self.cron_informer.indexer.list()]

    def _owned_jobs(self, cron: dict) -> list[dict]:
        ns = cron["metadata"].get("namespace", "default")
        cuid = uid_of(cron)
        out = []
        for job in self.job_informer.indexer.list():
            if job["metadata"].get("namespace", "default") != ns:
                continue
            ref = _controller_of(job)
            if ref is None or ref.get("kind") != "CronJob" \
                    or ref.get("name") != cron["metadata"]["name"]:
                continue
            if ref.get("uid") and cuid and ref["uid"] != cuid:
                continue
            out.append(job)
        return out

    @staticmethod
    def _job_finished(job: dict) -> str | None:
        for c in (job.get("status") or {}).get("conditions") or []:
            if c.get("status") == "True" and \
                    c.get("type") in ("Complete", "Failed"):
                return c["type"]
        return None

    async def sync(self, key: str) -> None:
        cron = self.cron_informer.indexer.get(key)
        if cron is None:
            return
        spec = cron.get("spec") or {}
        if spec.get("suspend"):
            return
        try:
            sched = CronSchedule(spec.get("schedule", ""))
        except ValueError as e:
            logger.warning("cronjob %s: bad schedule: %s", key, e)
            return
        return await self._sync_scheduled(key, cron, spec, sched)

    async def _sync_scheduled(self, key, cron, spec, sched) -> None:
        now = self.now()
        created = cron["metadata"].get("creationTimestamp")
        last = _parse_time((cron.get("status") or {})
                           .get("lastScheduleTime")) \
            or _parse_time(created) or now
        if last > now:
            # Clock skew (or an injected test clock behind the apiserver's
            # stamp): a future baseline would postpone the first run
            # indefinitely.
            last = now
        try:
            due = sched.next_after(last)
            if due > now:
                await self._trim_history(cron)
                return
            # Most recent missed time wins (the reference warns past 100
            # misses; we just take the latest).
            while True:
                nxt = sched.next_after(due)
                if nxt > now:
                    break
                due = nxt
        except ValueError as e:
            # Valid-looking spec that never fires (e.g. "0 0 30 2 *"):
            # park it, don't hot-requeue.
            logger.warning("cronjob %s: schedule never fires: %s", key, e)
            return
        deadline = spec.get("startingDeadlineSeconds")
        if deadline is not None and \
                (now - due).total_seconds() > float(deadline):
            await self._record_schedule(key, due)  # too late: skip run
            return
        active = [j for j in self._owned_jobs(cron)
                  if self._job_finished(j) is None]
        policy = spec.get("concurrencyPolicy", "Allow")
        if active and policy == "Forbid":
            # Do NOT record the skipped time: the run stays due and
            # catches up when the active job finishes (the reference
            # leaves LastScheduleTime unset in this branch).
            return
        if active and policy == "Replace":
            for j in active:
                try:
                    await self.store.delete("jobs", namespaced_name(j))
                except StoreError:
                    pass
        await self._spawn_job(cron, due)
        await self._record_schedule(key, due)
        await self._trim_history(cron)

    async def _spawn_job(self, cron: dict, due: datetime) -> None:
        ns = cron["metadata"].get("namespace", "default")
        name = f"{cron['metadata']['name']}-{int(due.timestamp()) // 60}"
        tmpl = (cron.get("spec") or {}).get("jobTemplate") or {}
        job = new_object("Job", name, ns,
                         spec=dict(tmpl.get("spec") or {}), status={})
        job["metadata"]["ownerReferences"] = [{
            "apiVersion": "batch/v1", "kind": "CronJob",
            "name": cron["metadata"]["name"], "uid": uid_of(cron),
            "controller": True}]
        job["metadata"]["annotations"] = {
            "batch.kubernetes.io/cronjob-scheduled-timestamp":
                _fmt_time(due)}
        try:
            await self.store.create("jobs", job, return_copy=False)
        except AlreadyExists:
            pass  # deterministic name: this tick already ran

    async def _record_schedule(self, key: str, due: datetime) -> None:
        def set_last(obj):
            status = obj.setdefault("status", {})
            if status.get("lastScheduleTime") == _fmt_time(due):
                return None
            status["lastScheduleTime"] = _fmt_time(due)
            return obj
        try:
            await self.store.guaranteed_update(
                "cronjobs", key, set_last, return_copy=False)
        except NotFound:
            pass

    async def _trim_history(self, cron: dict) -> None:
        spec = cron.get("spec") or {}
        limits = {"Complete": int(spec.get(
            "successfulJobsHistoryLimit", 3)),
            "Failed": int(spec.get("failedJobsHistoryLimit", 1))}
        by_outcome: dict[str, list[dict]] = {"Complete": [], "Failed": []}
        for j in self._owned_jobs(cron):
            outcome = self._job_finished(j)
            if outcome:
                by_outcome[outcome].append(j)
        for outcome, jobs in by_outcome.items():
            jobs.sort(key=lambda j: j["metadata"]
                      .get("creationTimestamp", ""))
            excess = len(jobs) - limits[outcome]
            for j in jobs[:max(0, excess)]:
                try:
                    await self.store.delete("jobs", namespaced_name(j))
                except StoreError:
                    pass
