"""PersistentVolume binder controller: the PVC ↔ PV state machine.

Parity target: pkg/controller/volume/persistentvolume/pv_controller.go
(`syncClaim` / `syncVolume`): Pending PVCs are matched to Available PVs
(capacity, accessModes, storageClassName, selector) and bound both ways
(pv.spec.claimRef ↔ pvc.spec.volumeName); WaitForFirstConsumer claims wait
for the scheduler's `volume.kubernetes.io/selected-node` annotation
(VolumeBinding plugin sets it at Reserve); claims with no matching PV are
dynamically provisioned (simulated provisioner honoring the selected node's
topology); deleting a PVC releases its PV per reclaim policy.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.meta import namespaced_name, uid_of
from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import make_pv
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.mvcc import NotFound, StoreError

logger = logging.getLogger(__name__)

SELECTED_NODE_ANN = "volume.kubernetes.io/selected-node"
#: provisioner value that disables dynamic provisioning (the reference's
#: kubernetes.io/no-provisioner convention for local volumes).
NO_PROVISIONER = "kubernetes.io/no-provisioner"


def pv_matches_claim(pv: dict, pvc: dict) -> bool:
    """findMatchingVolume subset: class, phase, capacity, accessModes."""
    if (pv.get("status") or {}).get("phase") != "Available":
        return False
    if pv.get("spec", {}).get("claimRef"):
        return False
    want_class = pvc.get("spec", {}).get("storageClassName") or ""
    if (pv.get("spec", {}).get("storageClassName") or "") != want_class:
        return False
    want = parse_quantity((pvc["spec"].get("resources") or {})
                          .get("requests", {}).get("storage", 0))
    have = parse_quantity((pv["spec"].get("capacity") or {})
                          .get("storage", 0))
    if have < want:
        return False
    pv_modes = set(pv["spec"].get("accessModes") or [])
    return set(pvc["spec"].get("accessModes") or []).issubset(pv_modes)


def pv_node_ok(pv: dict, node: dict) -> bool:
    """CheckVolumeNodeAffinity: PV nodeAffinity.required terms vs node."""
    from kubernetes_tpu.api.labels import match_node_selector_terms
    req = ((pv.get("spec") or {}).get("nodeAffinity") or {}).get("required")
    if not req:
        return True
    return match_node_selector_terms(
        req.get("nodeSelectorTerms") or [],
        node.get("metadata", {}).get("labels") or {},
        node["metadata"]["name"])


class PVBinderController(Controller):
    NAME = "pv-binder"
    WORKERS = 2
    RESYNC_PERIOD = 2.0

    def __init__(self, store, *, provision_delay: float = 0.05):
        super().__init__(store)
        self.provision_delay = provision_delay
        self._seq = 0

    def setup(self, factory: InformerFactory) -> None:
        self.pvc_informer = factory.informer("persistentvolumeclaims")
        self.pv_informer = factory.informer("persistentvolumes")
        self.sc_informer = factory.informer("storageclasses")
        self.node_informer = factory.informer("nodes")
        self.watch_resource(factory, "persistentvolumeclaims")

        # PVC deletion → release its PV (syncVolume's released path).
        def on_pvc_delete(obj):
            vol = obj.get("spec", {}).get("volumeName")
            if vol:
                asyncio.ensure_future(self._release_pv(vol, uid_of(obj)))

        self.pvc_informer.add_event_handler(ResourceEventHandler(
            on_delete=on_pvc_delete))
        # New PVs can satisfy pending claims.
        self.pv_informer.add_event_handler(ResourceEventHandler(
            on_add=lambda obj: asyncio.ensure_future(self._poke_pending())))

    async def _poke_pending(self) -> None:
        for pvc in self.pvc_informer.indexer.list():
            if (pvc.get("status") or {}).get("phase") == "Pending":
                await self.queue.add(namespaced_name(pvc))

    async def resync_keys(self):
        return [namespaced_name(c)
                for c in self.pvc_informer.indexer.list()
                if (c.get("status") or {}).get("phase") != "Bound"]

    def _storage_class(self, pvc: dict) -> dict | None:
        name = pvc.get("spec", {}).get("storageClassName")
        if not name:
            return None
        return self.sc_informer.indexer.get(name)

    async def sync(self, key: str) -> None:
        pvc = self.pvc_informer.indexer.get(key)
        if pvc is None or (pvc.get("status") or {}).get("phase") == "Bound":
            return
        sc = self._storage_class(pvc)
        selected = (pvc["metadata"].get("annotations") or {}) \
            .get(SELECTED_NODE_ANN)
        wffc = bool(sc) and sc.get("volumeBindingMode") == "WaitForFirstConsumer"
        if wffc and not selected:
            return  # syncUnboundClaim: wait for the scheduler to pick a node

        node = self.node_informer.indexer.get(selected) if selected else None
        # Static match first (findMatchingVolume), topology-checked when a
        # node was selected.
        for pv in self.pv_informer.indexer.list():
            if pv_matches_claim(pv, pvc) and \
                    (node is None or pv_node_ok(pv, node)):
                await self._bind(pvc, pv)
                return
        # Dynamic provisioning (simulated provisioner).
        if sc is not None and sc.get("provisioner") != NO_PROVISIONER:
            await self._provision(pvc, sc, selected)

    async def _bind(self, pvc: dict, pv: dict) -> None:
        key = namespaced_name(pvc)
        pv_name = pv["metadata"]["name"]

        def claim_pv(obj):
            if obj.get("spec", {}).get("claimRef"):
                return None  # raced with another claim; sync retries
            obj["spec"]["claimRef"] = {
                "kind": "PersistentVolumeClaim",
                "namespace": pvc["metadata"].get("namespace", "default"),
                "name": pvc["metadata"]["name"],
                "uid": uid_of(pvc),
            }
            obj.setdefault("status", {})["phase"] = "Bound"
            return obj
        try:
            bound = await self.store.guaranteed_update(
                "persistentvolumes", pv_name, claim_pv)
        except NotFound:
            return
        if not (bound.get("spec", {}).get("claimRef") or {}).get("uid") \
                == uid_of(pvc):
            return  # lost the race

        def bind_claim(obj):
            obj["spec"]["volumeName"] = pv_name
            obj.setdefault("status", {})["phase"] = "Bound"
            return obj
        try:
            await self.store.guaranteed_update(
                "persistentvolumeclaims", key, bind_claim)
        except NotFound:
            await self._release_pv(pv_name, uid_of(pvc))

    async def _provision(self, pvc: dict, sc: dict, selected: str | None) -> None:
        """Simulated external provisioner: a real one takes time — the
        VolumeBinding plugin's PreBind genuinely blocks on this."""
        await asyncio.sleep(self.provision_delay)
        self._seq += 1
        request = (pvc["spec"].get("resources") or {}) \
            .get("requests", {}).get("storage", "1Gi")
        node_affinity = None
        if selected:
            node_affinity = {"nodeSelectorTerms": [{"matchFields": [
                {"key": "metadata.name", "operator": "In",
                 "values": [selected]}]}]}
        pv = make_pv(f"pvc-{uid_of(pvc) or self._seq}",
                     capacity=str(request),
                     storage_class=sc["metadata"]["name"],
                     access_modes=list(pvc["spec"].get("accessModes") or []),
                     node_affinity=node_affinity,
                     reclaim_policy="Delete")
        try:
            await self.store.create("persistentvolumes", pv)
        except StoreError as e:
            logger.warning("provision for %s failed: %s",
                           namespaced_name(pvc), e)
            return
        await self._bind(pvc, pv)

    async def _release_pv(self, pv_name: str, claim_uid: str | None) -> None:
        def release(obj):
            ref = obj.get("spec", {}).get("claimRef")
            if not ref or (claim_uid and ref.get("uid") != claim_uid):
                return None
            if obj["spec"].get("persistentVolumeReclaimPolicy") == "Delete":
                obj.setdefault("status", {})["phase"] = "Released"  # then deleted below
            else:
                obj["spec"].pop("claimRef", None)
                obj.setdefault("status", {})["phase"] = "Available"
            return obj
        try:
            out = await self.store.guaranteed_update(
                "persistentvolumes", pv_name, release)
            if (out.get("status") or {}).get("phase") == "Released":
                await self.store.delete("persistentvolumes", pv_name)
        except StoreError:
            pass
