"""Controller base: the informer → ratelimited workqueue → sync(key) triangle.

Parity target: the pattern every controller in pkg/controller/ follows
(SURVEY §3.4): shared informer handlers enqueue namespace/name keys into a
rate-limited workqueue, N worker tasks pop keys and run `sync(key)`
level-triggered; failures re-enqueue with exponential backoff; periodic
resync forces full reconciliation.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Iterable

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.client import (
    InformerFactory,
    RateLimitingQueue,
    ResourceEventHandler,
)

logger = logging.getLogger(__name__)


class Controller:
    """Subclass contract: set NAME, implement `sync(key)`, and wire
    informers in `setup(factory)` using `enqueue`/`enqueue_obj`."""

    NAME = "controller"
    WORKERS = 2
    RESYNC_PERIOD = 0.0  # seconds; 0 disables periodic resync

    def __init__(self, store):
        self.store = store
        self.queue = RateLimitingQueue()
        self._tasks: list[asyncio.Task] = []
        self._stopped = False

    # -- wiring ------------------------------------------------------------

    def setup(self, factory: InformerFactory) -> None:
        raise NotImplementedError

    def watch_resource(self, factory: InformerFactory, resource: str,
                       key_fn=None) -> None:
        """Standard handler set: enqueue the object's key on add/update/delete."""
        key_fn = key_fn or namespaced_name

        def enq(obj):
            asyncio.ensure_future(self.queue.add(key_fn(obj)))

        factory.informer(resource).add_event_handler(ResourceEventHandler(
            on_add=enq, on_update=lambda old, new: enq(new), on_delete=enq))

    def watch_owned(self, factory: InformerFactory, resource: str,
                    kind: str) -> None:
        """Events of `resource` map back to the owning controller's key
        via the controllerRef (the addPod/deletePod pattern every
        workload controller shares — generalized for Job→CronJob etc.)."""
        def to_owner(obj):
            for ref in obj.get("metadata", {}).get("ownerReferences") or []:
                if ref.get("controller") and ref.get("kind") == kind:
                    ns = obj["metadata"].get("namespace", "default")
                    asyncio.ensure_future(
                        self.queue.add(f"{ns}/{ref['name']}"))
                    return

        factory.informer(resource).add_event_handler(ResourceEventHandler(
            on_add=to_owner, on_update=lambda o, n: to_owner(n),
            on_delete=to_owner))

    def watch_owned_pods(self, factory: InformerFactory, kind: str) -> None:
        self.watch_owned(factory, "pods", kind)

    async def enqueue(self, key: str) -> None:
        await self.queue.add(key)

    async def enqueue_after(self, key: str, delay: float) -> None:
        await self.queue.add_after(key, delay)

    # -- run loop ----------------------------------------------------------

    def start(self) -> None:
        for _ in range(self.WORKERS):
            self._tasks.append(asyncio.ensure_future(self._worker()))
        if self.RESYNC_PERIOD > 0:
            self._tasks.append(asyncio.ensure_future(self._resync_loop()))

    async def _worker(self) -> None:
        while not self._stopped:
            key, shutdown = await self.queue.get()
            if shutdown:
                return
            try:
                await self.sync(key)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("%s: sync(%s) failed; requeueing",
                                 self.NAME, key)
                await self.queue.add_rate_limited(key)
            else:
                self.queue.forget(key)
            finally:
                await self.queue.done(key)

    async def _resync_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.RESYNC_PERIOD)
            for key in await self.resync_keys():
                await self.queue.add(key)

    async def resync_keys(self) -> Iterable[str]:
        return []

    async def sync(self, key: str) -> None:
        raise NotImplementedError

    async def stop(self) -> None:
        self._stopped = True
        await self.queue.shut_down()
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)


class ControllerManager:
    """kube-controller-manager analog: hosts controllers over one shared
    informer factory (cmd/kube-controller-manager app/controllermanager.go)."""

    def __init__(self, store, controllers: list[Controller]):
        self.store = store
        self.controllers = controllers
        self.factory = InformerFactory(store)

    async def start(self) -> None:
        for c in self.controllers:
            c.setup(self.factory)
        self.factory.start()
        await self.factory.wait_for_sync()
        for c in self.controllers:
            c.start()

    async def stop(self) -> None:
        for c in self.controllers:
            await c.stop()
        self.factory.stop()

    async def run_with_leader_election(self, elector) -> None:
        """Leader-elected controller-manager lifetime: controllers run only
        while holding the lease (kube-controller-manager's
        leaderElectAndRun); losing it stops every controller so the
        standby replica converges instead of fighting."""
        async def lead():
            await self.start()
            await asyncio.Event().wait()  # run until cancelled

        try:
            await elector.run(on_started_leading=lead)
        finally:
            await self.stop()
