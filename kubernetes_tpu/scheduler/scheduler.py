"""The scheduler: queue → cycle → assume → (async) bind.

Parity target: pkg/scheduler/scheduler.go + schedule_one.go
(`Scheduler.Run` → `ScheduleOne`; `schedulingCycle` (synchronous hot path:
snapshot → PreFilter → findNodesThatFitPod → prioritizeNodes → selectHost →
assume → Reserve → Permit) and `bindingCycle` (async task: WaitOnPermit →
PreBind → Bind → PostBind)); eventhandlers.go (`addAllEventHandlers`).

Two execution modes share every seam:

- `run_one()` — the reference-shaped one-pod-per-cycle loop (the oracle).
- `run_batched(max_batch=P)` — drains up to P pods per cycle and hands the
  whole batch to a backend (host greedy or the TPU solver); intra-batch
  resource contention is resolved by the backend before any assume happens.

`percentageOfNodesToScore` is honored on the host path for parity
(numFeasibleNodesToFind: adaptive 50 - N/125, floor 5%); the TPU path
defaults it to 100% because full-N is one tensor op (SURVEY §2.8).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Mapping

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import pod_is_terminal
from kubernetes_tpu.client import EventRecorder, InformerFactory, ResourceEventHandler
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.utils import flags
from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Framework,
    Status,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.scheduler.plugins.defaultpreemption import DefaultPreemption
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.scheduler.queue import ClusterEvent, SchedulingQueue
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.utils.trace import Trace
from kubernetes_tpu.utils.tracing import traceparent_of

logger = logging.getLogger(__name__)


class FitError(Exception):
    def __init__(self, pod: PodInfo, num_nodes: int, statuses: Mapping[str, Status]):
        self.pod = pod
        self.num_nodes = num_nodes
        self.statuses = statuses
        # The batched backend's DiagMap precomputes the counts (re-counting
        # N-entry maps per failed pod dominated dense failure waves).
        reasons = getattr(statuses, "reason_counts", None)
        if reasons is None:
            reasons = {}
            for st in statuses.values():
                for r in st.reasons:
                    reasons[r] = reasons.get(r, 0) + 1
        msg = ", ".join(f"{n} {r}" for r, n in sorted(reasons.items()))
        super().__init__(
            f"0/{num_nodes} nodes are available: {msg}" if msg
            else f"0/{num_nodes} nodes are available")


class ScheduleResult:
    __slots__ = ("node", "evaluated", "feasible")

    def __init__(self, node: str, evaluated: int, feasible: int):
        self.node = node
        self.evaluated = evaluated
        self.feasible = feasible


class Scheduler:
    def __init__(
        self,
        store,
        profiles: Mapping[str, Framework] | None = None,
        percentage_of_nodes_to_score: int = 0,
        seed: int = 0,
        metrics: SchedulerMetrics | None = None,
        backend=None,
        pod_initial_backoff: float = 1.0,
        pod_max_backoff: float = 10.0,
        trace_threshold_ms: float | None = None,
        tracer=None,
    ):
        self.store = store
        self.metrics = metrics or SchedulerMetrics()
        #: OTel-style spans (§5.1); same default process tracer as the
        #: apiserver so one tracer assembles the whole pod journey.
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        self.tracer = tracer if tracer is not None else DEFAULT_TRACER
        if profiles is None:
            plugins = build_plugins(store=store)
            fwk = Framework(plugins, DEFAULT_SCORE_WEIGHTS, metrics=self.metrics)
            profiles = {"default-scheduler": fwk}
        self.profiles = dict(profiles)
        for fwk in self.profiles.values():
            if fwk.metrics is None:
                fwk.metrics = self.metrics
            if getattr(fwk, "tracer", None) is None:
                fwk.tracer = self.tracer
            for p in fwk.post_filter_plugins:
                if isinstance(p, DefaultPreemption):
                    p.framework = fwk
                    if p.evict is None:
                        p.evict = self._preemption_evict
            for p in fwk.plugins:
                # Plugins needing the frameworkHandle analog (Permit
                # allow/reject — e.g. Coscheduling) get the scheduler.
                if hasattr(p, "set_scheduler"):
                    p.set_scheduler(self)
        self.cache = SchedulerCache()
        default_fwk = next(iter(self.profiles.values()))
        self.queue = SchedulingQueue(
            default_fwk, initial_backoff=pod_initial_backoff,
            max_backoff=pod_max_backoff)
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        #: utiltrace threshold: scheduling attempts slower than this log a
        #: step-by-step latency trace (SURVEY §5.1). None defaults from
        #: KTPU_TRACE_THRESHOLD_MS (the tracer's tree-dump threshold
        #: reads the same variable), else the reference's 100ms.
        if trace_threshold_ms is None:
            env = flags.get("KTPU_TRACE_THRESHOLD_MS")
            trace_threshold_ms = env if env is not None else 100.0
        self.trace_threshold_ms = trace_threshold_ms
        self.rng = random.Random(seed)
        self.backend = None  # TPU batch backend; None = host path
        if backend is not None:
            self.attach_backend(backend)
        #: Profiles the batched backend serves (TPUScorer gate, per-profile);
        #: None = all profiles (constructor-injected backend, old behavior).
        self.backend_profiles: set[str] | None = None
        self.extenders: list = []
        #: serving.ServingTier (admission window + resident planes +
        #: single-pod fast path), attached lazily at run()-loop entry by
        #: serving.maybe_attach_serving — flagless when a batched
        #: backend is present; KTPU_SERVING=0 keeps it None and the
        #: loop structurally identical to the pre-serving shape.
        self.serving = None
        self.recorder = EventRecorder(store, "default-scheduler")
        self._informer_factory: InformerFactory | None = None
        self._binding_tasks: set[asyncio.Task] = set()
        self._permit_waiters: dict[str, asyncio.Future] = {}
        self._stop = False
        #: consecutive nominee-check failures per preemptor retry: the
        #: first few failures requeue cheaply (victim deletes are still
        #: landing); persistent failure falls to the full batch path,
        #: which can re-preempt (the nominee may have been stolen).
        self._nominee_fails: dict[str, int] = {}
        #: tick-coalesced cluster events (label-deduped) for ONE
        #: move_all_batch scan per loop tick — see _move_all_soon.
        self._pending_moves: dict[str, ClusterEvent] = {}
        self._move_scheduled = False
        self._register_default_hints(default_fwk)

    def _move_all_soon(self, event: ClusterEvent) -> None:
        """Coalesce same-tick cluster events into one queue scan: an
        informer burst (e.g. a preemption wave's victim deletes) fires
        one move_all_batch instead of one full-parked-set scan per event."""
        self._pending_moves[event.label] = event
        if not self._move_scheduled:
            self._move_scheduled = True
            asyncio.get_event_loop().call_soon(self._drain_moves)

    def _drain_moves(self) -> None:
        self._move_scheduled = False
        events = list(self._pending_moves.values())
        self._pending_moves.clear()
        if events:
            asyncio.ensure_future(self.queue.move_all_batch(events))

    # ------------------------------------------------------------------
    # wiring (eventhandlers.go addAllEventHandlers)
    # ------------------------------------------------------------------

    def _register_default_hints(self, fwk: Framework) -> None:
        for plugin in fwk.plugins:
            for label in getattr(plugin, "EVENTS", []):
                self.queue.register_hint(
                    label, plugin.NAME, lambda pi, ev: "Queue")

    async def setup_informers(self, factory: InformerFactory) -> None:
        self._informer_factory = factory
        if self.backend is not None \
                and getattr(self.backend, "control_shards", 0) is None:
            # Remote store: ask the server for the control-plane shape
            # so the host prep's shard accounting matches the backing
            # store instead of re-deriving it from node count.
            probe = getattr(self.store, "control_topology", None)
            if probe is not None:
                try:
                    topo = await probe()
                    self.backend.control_shards = int(
                        topo.get("nodeShards", 1) or 1)
                except Exception:
                    logger.warning("control-plane topology probe failed; "
                                   "shard accounting falls back to the "
                                   "flagless policy", exc_info=True)
        pods = factory.informer("pods")
        nodes = factory.informer("nodes")
        for fwk in self.profiles.values():
            for p in fwk.plugins:
                if hasattr(p, "set_informers"):
                    p.set_informers(factory)

        def on_pod_add(obj):
            pi = PodInfo(obj)
            if pod_is_terminal(obj):
                return
            if pi.node_name:
                self.cache.add_pod(pi)
                self._move_all_soon(ClusterEvent("Pod", "Add"))
            elif self._responsible(pi):
                asyncio.ensure_future(self.queue.add(pi))
                # A new PENDING pod can lift gates of other pods (e.g.
                # Coscheduling's minMember gate counts siblings). Only poke
                # the queue when something is actually parked — at perf
                # scale this fires once per created pod.
                if self.queue.has_parked():
                    self._move_all_soon(ClusterEvent("Pod", "Add"))

        def on_pod_update(old, new):
            pi = PodInfo(new)
            if pod_is_terminal(new):
                on_pod_delete(new)
                return
            if pi.node_name:
                self.cache.update_pod(pi)
            elif self._responsible(pi):
                # Covers the SchedulingGates-removal path too: queue.update
                # re-runs PreEnqueue on the fresh object.
                asyncio.ensure_future(self.queue.update(pi))

        def on_pod_delete(obj):
            key = namespaced_name(obj)
            if obj.get("spec", {}).get("nodeName") or self.cache.is_assumed(key):
                self.cache.remove_pod(key)
            self._nominee_fails.pop(key, None)
            asyncio.ensure_future(self.queue.delete(key))
            self._move_all_soon(ClusterEvent("Pod", "Delete"))

        def on_node_add(obj):
            self.cache.add_node(obj)
            self._move_all_soon(ClusterEvent("Node", "Add"))

        def on_node_update(old, new):
            self.cache.update_node(new)
            self._move_all_soon(ClusterEvent("Node", "Update"))

        def on_node_delete(obj):
            self.cache.remove_node(obj["metadata"]["name"])

        pods.add_event_handler(ResourceEventHandler(
            on_add=on_pod_add, on_update=on_pod_update, on_delete=on_pod_delete))
        nodes.add_event_handler(ResourceEventHandler(
            on_add=on_node_add, on_update=on_node_update, on_delete=on_node_delete))

        # Secondary resources plugins declared EVENTS for (addAllEventHandlers
        # registers an informer per EventResource): PVC/PV/StorageClass churn
        # must re-activate pods parked for volume reasons. Only the declared
        # (kind, action) labels get handlers, and move_all runs even with
        # nothing parked so in-flight cycles are marked for backoff
        # (_moved_while_in_flight) when the event races their failure.
        labels = {label
                  for fwk in self.profiles.values()
                  for p in fwk.plugins
                  for label in getattr(p, "EVENTS", [])}
        from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
        resource_of = {k: KIND_TO_RESOURCE[k] for k in (
            "PersistentVolumeClaim", "PersistentVolume", "StorageClass",
            "NodeResourceTopology", "ResourceClaim", "ResourceSlice",
            "DeviceClass")}
        for kind, resource in resource_of.items():

            def poke(action, kind=kind):
                def handler(*_args):
                    self._move_all_soon(ClusterEvent(kind, action))
                return handler

            handlers = {}
            if f"{kind}/Add" in labels:
                handlers["on_add"] = poke("Add")
            if f"{kind}/Update" in labels:
                handlers["on_update"] = poke("Update")
            if f"{kind}/Delete" in labels:
                handlers["on_delete"] = poke("Delete")
            if handlers:
                factory.informer(resource).add_event_handler(
                    ResourceEventHandler(**handlers))

    def attach_backend(self, backend) -> None:
        """Attach the batched backend — the ONE place its cross-wiring
        (degradation metrics + tracer, §5.5/§5.1) happens, for both
        constructor injection and config-built schedulers."""
        self.backend = backend
        if backend is not None and hasattr(backend, "metrics"):
            backend.metrics = self.metrics
        if backend is not None and hasattr(backend, "tracer"):
            backend.tracer = self.tracer
        if backend is not None and hasattr(backend, "control_shards"):
            # Thread the backing store's ACTUAL shard count into the
            # host prep's per-shard accounting: a ShardedNodeStore
            # advertises node_shards, a plain in-process MVCCStore is
            # known unsharded (1). Remote stores resolve via the async
            # topology probe in setup_informers; until something
            # answers, the flagless policy is the fallback.
            from kubernetes_tpu.store.mvcc import MVCCStore
            shards = getattr(self.store, "node_shards", None)
            if shards is not None:
                backend.control_shards = int(shards)
            elif isinstance(self.store, MVCCStore):
                backend.control_shards = 1

    def _responsible(self, pi: PodInfo) -> bool:
        return pi.scheduler_name in self.profiles

    # ------------------------------------------------------------------
    # scheduling cycle (host path)
    # ------------------------------------------------------------------

    def _num_feasible_nodes_to_find(self, num_nodes: int,
                                    pct_override: int | None = None) -> int:
        """numFeasibleNodesToFind: adaptive percentage sampling; a profile
        may override the global percentage (reference scopes the field)."""
        pct = self.percentage_of_nodes_to_score if pct_override is None \
            else pct_override
        if num_nodes < 100 or pct >= 100:
            return num_nodes
        if pct <= 0:
            pct = max(50 - num_nodes // 125, 5)
        return max(num_nodes * pct // 100, 100)

    async def find_nodes_that_fit(
        self, fwk: Framework, state: CycleState, pod: PodInfo, snapshot: Snapshot,
    ) -> tuple[list[NodeInfo], dict[str, Status]]:
        """findNodesThatFitPod: PreFilter → Filter each node (+ extenders)."""
        statuses: dict[str, Status] = {}
        st = fwk.run_pre_filter(state, pod, snapshot)
        if not st.is_success():
            if st.is_unschedulable():
                for n in snapshot:
                    statuses[n.name] = st
                return [], statuses
            raise RuntimeError(f"PreFilter error: {st.message()}")

        # Nominated-node fast path (preemptor pods retry their nominee first).
        if pod.nominated_node:
            ni = snapshot.get(pod.nominated_node)
            if ni is not None and fwk.run_filters(state, pod, ni).is_success():
                return [ni], statuses

        want = self._num_feasible_nodes_to_find(
            len(snapshot),
            getattr(fwk, "percentage_of_nodes_to_score", None))
        feasible: list[NodeInfo] = []
        # Round-robin start offset mirrors nextStartNodeIndex fairness.
        start = self.rng.randrange(len(snapshot)) if len(snapshot) else 0
        nodes = snapshot.nodes
        # One Filter span over the whole node scan (per-node spans would
        # be N per attempt); run_filters keeps its per-plugin metrics.
        with fwk.ep_span("Filter"):
            for i in range(len(nodes)):
                node = nodes[(start + i) % len(nodes)]
                st = fwk.run_filters(state, pod, node)
                if st.is_success():
                    feasible.append(node)
                    if len(feasible) >= want:
                        break
                else:
                    statuses[node.name] = st
        # findNodesThatPassExtenders: HTTP webhooks narrow the feasible set.
        for ext in self.extenders:
            if not feasible:
                break
            feasible, failed, failed_unresolvable = \
                await ext.filter(pod, feasible)
            for name, reason in failed.items():
                statuses[name] = Status.unschedulable(
                    reason).with_plugin(ext.name)
            for name, reason in failed_unresolvable.items():
                statuses[name] = Status.unschedulable(
                    reason, resolvable=False).with_plugin(ext.name)
        return feasible, statuses

    async def prioritize_nodes(
        self, fwk: Framework, state: CycleState, pod: PodInfo,
        nodes: list[NodeInfo],
    ) -> dict[str, float]:
        st = fwk.run_pre_score(state, pod, nodes)
        if not st.is_success():
            raise RuntimeError(f"PreScore error: {st.message()}")
        scores = fwk.run_scores(state, pod, nodes)
        if self.extenders:
            # Parallel fan-out like extender.go's Prioritize goroutines;
            # scores are summed so order doesn't matter.
            results = await asyncio.gather(
                *(ext.prioritize(pod, nodes) for ext in self.extenders))
            for ext_scores in results:
                for name, s in ext_scores.items():
                    scores[name] = scores.get(name, 0.0) + s
        return scores

    def select_host(self, scores: Mapping[str, float]) -> str:
        """selectHost: max score with reservoir-sampled random tiebreak
        (seeded rng — SURVEY §4 carry-in #5)."""
        best = None
        best_score = float("-inf")
        count = 0
        for name, s in scores.items():
            if s > best_score:
                best, best_score, count = name, s, 1
            elif s == best_score:
                count += 1
                if self.rng.randrange(count) == 0:
                    best = name
        return best or ""

    async def schedule_pod(self, fwk: Framework, state: CycleState,
                           pod: PodInfo, snapshot: Snapshot) -> ScheduleResult:
        if len(snapshot) == 0:
            raise FitError(pod, 0, {})
        feasible, statuses = await self.find_nodes_that_fit(
            fwk, state, pod, snapshot)
        if not feasible:
            raise FitError(pod, len(snapshot), statuses)
        if len(feasible) == 1:
            return ScheduleResult(feasible[0].name,
                                  len(statuses) + 1, 1)
        scores = await self.prioritize_nodes(fwk, state, pod, feasible)
        host = self.select_host(scores)
        return ScheduleResult(host, len(statuses) + len(feasible), len(feasible))

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------

    async def schedule_one(self) -> bool:
        """One pod, full cycle. Returns False when queue closed."""
        pods = await self.queue.pop_batch(1)
        if not pods:
            return False
        await self._schedule_pods(pods)
        return True

    async def schedule_batch(self, max_batch: int) -> bool:
        pods = await self.queue.pop_batch(max_batch)
        if not pods:
            return False
        await self._schedule_pods(pods)
        return True

    async def _schedule_pods(self, pods: list[PodInfo]) -> None:
        with Trace("Scheduling", threshold_ms=self.trace_threshold_ms,
                   pods=len(pods)) as tr:
            await self._schedule_pods_traced(pods, tr)

    async def _schedule_pods_traced(self, pods: list[PodInfo],
                                    tr) -> None:
        snapshot = self.cache.update_snapshot()
        tr.step("snapshot")
        # Extenders are per-pod HTTP webhooks whose round-trips dominate any
        # batch win, and their filter verdicts must precede assignment — so
        # configured extenders route pods through the (extender-aware) host
        # path, exactly the reference's control flow.
        if self.backend is not None and len(pods) > 1 and not self.extenders:
            # Pods are batched per profile: each batch runs under its own
            # plugin set/weights (profiles are keyed by schedulerName), and
            # the TPUScorer gate selects the backend PER PROFILE
            # (backend_profiles; None = all).
            # Preemptor retries ride a nominated-node fast check FIRST,
            # across every profile (schedule_one.go evaluates the nominee
            # before anything else): the batch solve has no nominee bias,
            # so any batch processed earlier could steal the freed node
            # and force a re-preemption — eviction churn. The check is
            # nominee-ONLY: a preemptor whose nominee is not yet feasible
            # (victims still terminating) REJOINS the batch instead of
            # burning a full per-pod host scan — per-retry O(N·plugins)
            # scans were the dominant cost of 1k-preemptor waves
            # (BASELINE.md r6), and the failure wave's preemption guard
            # re-nominates without re-evicting.
            nominated = [pi for pi in pods if pi.nominated_node]
            rejoin: set[str] = set()
            if nominated:
                placed = 0
                for pi in nominated:
                    if await self._try_nominated(pi, snapshot):
                        snapshot = self.cache.update_snapshot()
                        self._nominee_fails.pop(pi.key, None)
                        placed += 1
                        continue
                    fails = self._nominee_fails.get(pi.key, 0) + 1
                    # Waiting is only right while victim deletes are
                    # still in flight — i.e. the nominee still hosts
                    # lower-priority pods whose Delete events will
                    # re-activate us. A nominee with none left was
                    # STOLEN by equal/higher-priority pods: no event is
                    # coming, so go re-preempt now instead of idling
                    # until the unschedulable flush.
                    ni = snapshot.get(pi.nominated_node)
                    victims_pending = ni is not None and any(
                        p.priority < pi.priority for p in ni.pods)
                    if fails >= 3 or not victims_pending:
                        # Full batch path, which can re-preempt.
                        self._nominee_fails.pop(pi.key, None)
                        rejoin.add(pi.key)
                    else:
                        # Victim deletes are still landing: requeue and
                        # let their Delete events re-activate the pod —
                        # a full solve for a not-yet-free nominee is the
                        # wave's dominant retry cost.
                        self._nominee_fails[pi.key] = fails
                        await self.queue.add_unschedulable(pi)
                tr.step(
                    f"nominated fast path ({placed}/{len(nominated)} pods)")
            by_profile: dict[str, list[PodInfo]] = {}
            for pi in pods:
                if pi.nominated_node and pi.key not in rejoin:
                    continue
                by_profile.setdefault(pi.scheduler_name, []).append(pi)
            # The backend chunks to its own batch capacity internally and
            # PIPELINES the chunks (device state chains on device; chunk
            # k+1's solve overlaps chunk k's host verify) — SURVEY §2.8.
            for sname, group in by_profile.items():
                if self.backend_profiles is None or \
                        sname in self.backend_profiles:
                    await self._schedule_via_backend(group, snapshot)
                    tr.step(f"backend assign [{sname}] ({len(group)} pods)")
                    snapshot = self.cache.update_snapshot()
                else:
                    for pi in group:
                        await self._schedule_host_path(pi, snapshot)
                        snapshot = self.cache.update_snapshot()
                    tr.step(f"host path [{sname}] ({len(group)} pods)")
            return
        for pi in pods:
            await self._schedule_host_path(pi, snapshot)
            # Re-snapshot so pods later in the batch see earlier assumes.
            snapshot = self.cache.update_snapshot()
        tr.step(f"host path ({len(pods)} pods)")

    async def _try_nominated(self, pi: PodInfo, snapshot) -> bool:
        """Nominee-only evaluation of a preemptor retry: PreFilter + Filter
        on the nominated node alone. True = assumed and binding. False =
        nominee not (yet) feasible; the caller batches the pod instead of
        scanning the rest of the cluster pod-by-pod."""
        fwk = self.profiles.get(pi.scheduler_name)
        if fwk is None:
            logger.error("no profile for schedulerName=%s", pi.scheduler_name)
            await self.queue.done(pi.key)
            return True  # consumed; nothing else can schedule it
        ni = snapshot.get(pi.nominated_node)
        if ni is None:
            return False
        state = CycleState()
        t0 = time.perf_counter()
        if not fwk.run_pre_filter(state, pi, snapshot).is_success():
            return False
        if not fwk.run_filters(state, pi, ni).is_success():
            return False
        self.metrics.observe_attempt("scheduled", fwk.profile_name,
                                     time.perf_counter() - t0)
        await self._assume_and_bind(fwk, state, pi, ni.name)
        return True

    def _prime_preemption(self, fwk: Framework, failed: list[PodInfo],
                          snapshot, diagnostics: Mapping) -> None:
        """Hand the whole failure wave to preemption's batched device
        proposal (DefaultPreemption.prime_wave) before the per-pod
        PostFilter loop; a prime failure only loses the batching."""
        if snapshot is None:
            return
        for p in fwk.post_filter_plugins:
            prime = getattr(p, "prime_wave", None)
            if prime is not None:
                try:
                    prime(failed, snapshot, diagnostics)
                except Exception:
                    logger.exception(
                        "prime_wave failed; per-pod candidate search only")

    async def _schedule_via_backend(self, pods: list[PodInfo], snapshot) -> None:
        """Batched path: the backend returns {pod_key: node_name | None}.

        Device failure is a first-class fault domain (SURVEY §5.3 "TPU
        device loss → fall back to CPU path"): a backend crash falls this
        batch back to the host path, and repeated crashes open a circuit
        that disables the backend for the rest of the run."""
        if self.backend is None:
            # Circuit opened mid-batch by an earlier profile group.
            for pi in pods:
                await self._schedule_host_path(pi, snapshot)
                snapshot = self.cache.update_snapshot()
            return
        fwk = self.profiles.get(pods[0].scheduler_name) or next(iter(self.profiles.values()))
        t0 = time.perf_counter()
        if self.tracer.enabled:
            # One attempt span per backend batch: the device solve is a
            # joint decision over the whole batch, so per-pod spans would
            # invent a serialization that never happened. A single-pod
            # batch parents to its create request (stamped traceparent)
            # and carries the pod key for trace_for joins.
            attrs = {"pods": len(pods), "profile": fwk.profile_name}
            tp = None
            if len(pods) == 1:
                attrs["pod"] = pods[0].key
                tp = traceparent_of(pods[0].pod)
            with self.tracer.span("scheduler.attempt", traceparent=tp,
                                  **attrs):
                for pi in pods:
                    self._record_queue_wait(pi)
                return await self._backend_cycle(pods, snapshot, fwk, t0)
        await self._backend_cycle(pods, snapshot, fwk, t0)

    async def _backend_cycle(self, pods: list[PodInfo], snapshot, fwk,
                             t0: float) -> None:
        try:
            if hasattr(self.backend, "assign_stream"):
                # Chunk-streaming path: bindings for chunk k start while
                # chunk k+1 still solves on device — the device and the
                # API-boundary wire stay busy simultaneously.
                return await self._schedule_via_backend_stream(
                    pods, snapshot, fwk, t0)
            if hasattr(self.backend, "assign_async"):
                # Pipelined path: device fetches run in a worker thread, so
                # binding tasks keep draining during device/relay waits.
                assignments, diagnostics = await self.backend.assign_async(
                    pods, snapshot, fwk)
            else:
                assignments, diagnostics = self.backend.assign(
                    pods, snapshot, fwk)
            self._backend_failures = 0
        except Exception:
            self._backend_failures = getattr(
                self, "_backend_failures", 0) + 1
            logger.exception(
                "TPU backend failed (%d consecutive); falling back to the "
                "host path for this batch", self._backend_failures)
            self.metrics.schedule_attempts.inc(
                result="backend_fallback", profile=fwk.profile_name)
            if self._backend_failures >= 3:
                logger.error(
                    "TPU backend circuit OPEN after %d consecutive "
                    "failures — host path only from here",
                    self._backend_failures)
                self.backend = None
            for pi in pods:
                await self._schedule_host_path(pi, snapshot)
                snapshot = self.cache.update_snapshot()
            return
        elapsed = time.perf_counter() - t0
        # Assigned pods bind FIRST so the failure wave below sees every
        # in-batch assume in ONE snapshot; per-failure re-snapshots were
        # an O(N) walk per preemptor (the wave tensors already account
        # for in-wave claims — preemption.go's nominated-pod charge).
        failed: list[PodInfo] = []
        for pi in pods:
            node = assignments.get(pi.key)
            if node:
                self.metrics.observe_attempt("scheduled", fwk.profile_name, elapsed / len(pods))
                await self._assume_and_bind(fwk, CycleState(), pi, node)
            else:
                failed.append(pi)
        live = self.cache.update_snapshot() if failed else None
        if failed:
            self._prime_preemption(fwk, failed, live, diagnostics)
        for pi in failed:
            self.metrics.observe_attempt("unschedulable", fwk.profile_name,
                                         elapsed / len(pods))
            statuses = diagnostics.get(pi.key, {})
            # state+snapshot enable the PostFilter (preemption) branch
            # — without them the batched path could never preempt.
            # PreFilter runs first so the dry-run's filters see the
            # pod's affinity/spread/volume prefilter state (an empty
            # CycleState would make those filters vacuously pass and
            # evict victims on nodes the pod can never land on).
            state = CycleState()
            fwk.run_pre_filter(state, pi, live)
            await self._handle_failure(
                fwk, pi, FitError(pi, len(snapshot), statuses),
                statuses, state=state, snapshot=live)

    async def _schedule_via_backend_stream(self, pods: list[PodInfo],
                                           snapshot, fwk, t0: float) -> None:
        """Consume the backend's per-chunk assignment stream: each chunk's
        assume/Reserve/bindingCycle work is spawned as soon as its host
        verify lands, overlapping the next chunk's device solve."""
        done: set[str] = set()
        last_t = t0
        stream = self.backend.assign_stream(pods, snapshot, fwk)
        while True:
            # Only the DEVICE step is inside the failure domain: a
            # host-side error in binding/failure handling must neither
            # trip the backend circuit breaker nor strand the pod (the
            # pre-stream path kept the same separation).
            try:
                chunk_pods, ctx = await stream.__anext__()
                self._backend_failures = 0
            except StopAsyncIteration:
                break
            except Exception:
                self._backend_failures = getattr(
                    self, "_backend_failures", 0) + 1
                logger.exception(
                    "TPU backend failed mid-stream (%d consecutive); host "
                    "path for the rest of this batch",
                    self._backend_failures)
                self.metrics.schedule_attempts.inc(
                    result="backend_fallback", profile=fwk.profile_name)
                if self._backend_failures >= 3:
                    logger.error(
                        "TPU backend circuit OPEN after %d consecutive "
                        "failures — host path only from here",
                        self._backend_failures)
                    self.backend = None
                live = self.cache.update_snapshot()
                for pi in pods:
                    if pi.key in done:
                        continue
                    await self._schedule_host_path(pi, live)
                    live = self.cache.update_snapshot()
                return
            # Per-chunk delta (not since-batch-start): summed per-pod
            # observations must track wall time, as on the pre-stream path.
            now = time.perf_counter()
            elapsed, last_t = now - last_t, now
            n = max(1, len(chunk_pods))
            # Binds first, then the chunk's failure wave against ONE live
            # snapshot (see _schedule_via_backend) — per-preemptor
            # re-snapshots dominated dense preemption waves.
            failed = []
            for pi in chunk_pods:
                done.add(pi.key)
                node = ctx.assignments.get(pi.key)
                if node:
                    self.metrics.observe_attempt(
                        "scheduled", fwk.profile_name, elapsed / n)
                    await self._assume_and_bind(
                        fwk, CycleState(), pi, node)
                else:
                    failed.append(pi)
            live = self.cache.update_snapshot() if failed else None
            if failed:
                self._prime_preemption(fwk, failed, live, ctx.diagnostics)
            for pi in failed:
                self.metrics.observe_attempt(
                    "unschedulable", fwk.profile_name, elapsed / n)
                statuses = ctx.diagnostics.get(pi.key, {})
                state = CycleState()
                fwk.run_pre_filter(state, pi, live)
                try:
                    await self._handle_failure(
                        fwk, pi,
                        FitError(pi, len(snapshot), statuses),
                        statuses, state=state, snapshot=live)
                except Exception:
                    # Infrastructure error (e.g. an eviction write
                    # failed): the pod must not silently vanish.
                    logger.exception(
                        "failure handling errored for %s", pi.key)
                    await self.queue.move_to_backoff(pi)

    def _record_queue_wait(self, pi: PodInfo) -> None:
        """Retroactive queue-wait child span: the informer→queue→cycle
        hop crosses tasks no context can follow, so the span is rebuilt
        from the queue's own timestamps (same monotonic clock).
        enqueued_at is re-stamped per activeQ entry, so a retried pod's
        span covers only THIS attempt's wait — not earlier cycles or
        backoff windows."""
        start = pi.enqueued_at or pi.queued_at
        if start and pi.dequeued_at >= start > 0.0:
            self.tracer.record("scheduler.queue.wait", start,
                               pi.dequeued_at, pod=pi.key,
                               attempts=pi.attempts)

    async def _schedule_host_path(self, pi: PodInfo, snapshot) -> None:
        fwk = self.profiles.get(pi.scheduler_name)
        if fwk is None:
            logger.error("no profile for schedulerName=%s", pi.scheduler_name)
            await self.queue.done(pi.key)
            return
        if self.tracer.enabled:
            # traceparent stamped by the creating request (any wire)
            # parents this attempt into the pod's create trace.
            with self.tracer.span("scheduler.attempt", pod=pi.key,
                                  profile=fwk.profile_name,
                                  traceparent=traceparent_of(pi.pod)):
                self._record_queue_wait(pi)
                return await self._schedule_host_path_traced(
                    pi, snapshot, fwk)
        await self._schedule_host_path_traced(pi, snapshot, fwk)

    async def _schedule_host_path_traced(self, pi: PodInfo, snapshot,
                                         fwk) -> None:
        state = CycleState()
        t0 = time.perf_counter()
        try:
            result = await self.schedule_pod(fwk, state, pi, snapshot)
        except FitError as fe:
            self.metrics.observe_attempt("unschedulable", fwk.profile_name,
                                         time.perf_counter() - t0)
            await self._handle_failure(fwk, pi, fe, fe.statuses, state=state,
                                       snapshot=snapshot)
            return
        except Exception as e:  # infrastructure error
            logger.exception("scheduling cycle error for %s", pi.key)
            self.metrics.observe_attempt("error", fwk.profile_name,
                                         time.perf_counter() - t0)
            await self.queue.move_to_backoff(pi)
            return
        self.metrics.observe_attempt("scheduled", fwk.profile_name,
                                     time.perf_counter() - t0)
        await self._assume_and_bind(fwk, state, pi, result.node)

    async def _assume_and_bind(self, fwk: Framework, state: CycleState,
                               pi: PodInfo, node_name: str) -> None:
        """assume → Reserve → Permit → async bindingCycle."""
        try:
            self.cache.assume_pod(pi, node_name)
        except (KeyError, ValueError) as e:
            logger.error("assume failed for %s: %s", pi.key, e)
            await self.queue.move_to_backoff(pi)
            return
        st = fwk.run_reserve(state, pi, node_name)
        if not st.is_success():
            self.cache.forget_pod(pi.key)
            await self._requeue_unschedulable(pi, st)
            return
        permit_status, timeout = fwk.run_permit(state, pi, node_name)
        if not permit_status.is_success() and not permit_status.is_wait():
            fwk.run_unreserve(state, pi, node_name)
            self.cache.forget_pod(pi.key)
            await self._requeue_unschedulable(pi, permit_status)
            return
        if permit_status.is_wait():
            # Register the waiter SYNCHRONOUSLY (frameworkImpl stores
            # waitingPods inside RunPermitPlugins): a sibling's permit may
            # allow/reject this pod before the async binding cycle starts.
            self._permit_waiters[pi.key] = \
                asyncio.get_event_loop().create_future()
        task = asyncio.ensure_future(
            self._binding_cycle(fwk, state, pi, node_name, permit_status, timeout))
        self._binding_tasks.add(task)
        task.add_done_callback(self._binding_tasks.discard)
        self.metrics.goroutines.set(len(self._binding_tasks), operation="binding")

    async def _binding_cycle(self, fwk: Framework, state: CycleState, pi: PodInfo,
                             node_name: str, permit_status: Status,
                             timeout: float) -> None:
        if self.tracer.enabled:
            with self.tracer.span("scheduler.bind", pod=pi.key,
                                  node=node_name):
                return await self._binding_cycle_traced(
                    fwk, state, pi, node_name, permit_status, timeout)
        await self._binding_cycle_traced(
            fwk, state, pi, node_name, permit_status, timeout)

    async def _binding_cycle_traced(self, fwk: Framework, state: CycleState,
                                    pi: PodInfo, node_name: str,
                                    permit_status: Status,
                                    timeout: float) -> None:
        bound = False
        try:
            if permit_status.is_wait():
                ok = await self._wait_on_permit(fwk, pi, timeout)
                if not ok:
                    fwk.run_unreserve(state, pi, node_name)
                    self.cache.forget_pod(pi.key)
                    await self._requeue_unschedulable(
                        pi, Status.unschedulable("rejected at Permit"))
                    return
            st = await fwk.run_pre_bind(state, pi, node_name)
            if not st.is_success():
                fwk.run_unreserve(state, pi, node_name)
                self.cache.forget_pod(pi.key)
                await self._requeue_unschedulable(pi, st)
                return
            st = await self._bind(fwk, state, pi, node_name)
            if not st.is_success():
                fwk.run_unreserve(state, pi, node_name)
                self.cache.forget_pod(pi.key)
                await self._requeue_unschedulable(pi, st)
                return
            # The pod is durably bound in the API from here on: failures
            # below must NOT forget/requeue it (it is genuinely scheduled).
            bound = True
            self.cache.finish_binding(pi.key)
            fwk.run_post_bind(state, pi, node_name)
            self.recorder.event(pi.pod, "Normal", "Scheduled",
                                f"Successfully assigned {pi.key} to {node_name}")
            await self.queue.done(pi.key)
        except Exception:
            logger.exception("binding cycle crashed for %s", pi.key)
            if bound:
                await self.queue.done(pi.key)
                return
            self.cache.forget_pod(pi.key)
            await self.queue.move_to_backoff(pi)

    async def _bind(self, fwk: Framework, state: CycleState, pi: PodInfo,
                    node_name: str) -> Status:
        """schedule_one.go bind: a bind-capable extender interested in the
        pod binds INSTEAD of the framework's Bind plugins."""
        for ext in self.extenders:
            if getattr(ext, "is_binder", lambda: False)() \
                    and ext.is_interested(pi):
                try:
                    await ext.bind(pi, node_name)
                    return Status.success()
                except Exception as e:
                    return Status.error(f"extender bind failed: {e}")
        return await fwk.run_bind(state, pi, node_name)

    # Permit wait support (gang scheduling parks here) ------------------

    def allow_waiting_pod(self, pod_key: str) -> None:
        fut = self._permit_waiters.get(pod_key)
        if fut and not fut.done():
            fut.set_result(True)

    def reject_waiting_pod(self, pod_key: str) -> None:
        fut = self._permit_waiters.get(pod_key)
        if fut and not fut.done():
            fut.set_result(False)

    async def _wait_on_permit(self, fwk: Framework, pi: PodInfo,
                              timeout: float) -> bool:
        fut = self._permit_waiters.get(pi.key)
        if fut is None:
            fut = asyncio.get_event_loop().create_future()
            self._permit_waiters[pi.key] = fut
        try:
            return await asyncio.wait_for(fut, timeout if timeout > 0 else None)
        except asyncio.TimeoutError:
            return False
        finally:
            self._permit_waiters.pop(pi.key, None)

    # Failure handling --------------------------------------------------

    async def _handle_failure(self, fwk: Framework, pi: PodInfo, err: FitError,
                              statuses: Mapping[str, Status],
                              state: CycleState | None = None,
                              snapshot=None) -> None:
        """handleSchedulingFailure: record reasons, try preemption, requeue."""
        pi.last_failure = str(err)
        plugins = getattr(statuses, "plugins", None)
        pi.unschedulable_plugins = plugins if plugins is not None else {
            st.plugin for st in statuses.values() if st.plugin}
        self.recorder.event(pi.pod, "Warning", "FailedScheduling", str(err))
        resolvable = getattr(statuses, "resolvable", None)
        if resolvable is None:
            resolvable = any(
                st.code != UNSCHEDULABLE_AND_UNRESOLVABLE
                for st in statuses.values()) or not statuses
        if resolvable and state is not None and snapshot is not None \
                and fwk.post_filter_plugins:
            nominated, st = fwk.run_post_filters(state, pi, snapshot, statuses)
            if st.is_success() and nominated:
                pi.nominated_node = nominated
                self.metrics.schedule_attempts.inc(
                    result="preemption", profile=fwk.profile_name)
        await self.queue.add_unschedulable(pi)

    async def _requeue_unschedulable(self, pi: PodInfo, st: Status) -> None:
        pi.last_failure = st.message()
        self.recorder.event(pi.pod, "Warning", "FailedScheduling", st.message())
        await self.queue.add_unschedulable(pi)

    def _preemption_evict(self, pod: PodInfo, victim_keys: list[str],
                          node_name: str) -> None:
        """DefaultPreemption side-effects: API-delete victims + record."""
        self.metrics.preemption_victims.observe(len(victim_keys))

        async def do():
            from kubernetes_tpu.store.mvcc import StoreError
            for vk in victim_keys:
                try:
                    await self.store.delete("pods", vk)
                except StoreError:
                    pass

            def set_nominated(p):
                p.setdefault("status", {})["nominatedNodeName"] = node_name
                return p
            try:
                await self.store.guaranteed_update("pods", pod.key, set_nominated)
            except StoreError:
                pass
        asyncio.ensure_future(do())

    # ------------------------------------------------------------------

    async def _cache_janitor(self) -> None:
        """Periodic expiry of assumed-but-never-confirmed pods
        (cache.run → cleanupAssumedPods every 1s in the reference)."""
        try:
            while not self._stop:
                await asyncio.sleep(5.0)
                self.cache.cleanup_expired()
        except asyncio.CancelledError:
            return

    async def run(self, batch_size: int = 1) -> None:
        """wait.UntilWithContext(sched.ScheduleOne) — plus flushers.

        With a batched backend attached the loop runs through the
        serving tier (admission window + single-pod fast path —
        kubernetes_tpu/serving); KTPU_SERVING=0 degrades structurally
        to the plain schedule_batch loop below."""
        flusher = asyncio.ensure_future(self.queue.run_flushers())
        janitor = asyncio.ensure_future(self._cache_janitor())
        from kubernetes_tpu.serving import maybe_attach_serving
        serving = maybe_attach_serving(self)
        try:
            while not self._stop:
                if serving is not None:
                    more = await serving.schedule_next(batch_size)
                else:
                    more = await self.schedule_batch(batch_size)
                if not more:
                    break
                self.metrics.set_pending(self.queue.stats())
        finally:
            flusher.cancel()
            janitor.cancel()

    async def run_with_leader_election(self, elector,
                                       batch_size: int = 1) -> None:
        """Leader-elected run (cmd/kube-scheduler app/server.go `Run`):
        schedule only while holding the lease. Losing it stops the loop
        AND awaits stop() — which cancels in-flight binding tasks — before
        returning (fencing: a deposed leader must not write stale binds
        while the standby schedules the same pods)."""
        async def lead():
            await self.run(batch_size=batch_size)

        def lost():
            self._stop = True

        try:
            await elector.run(on_started_leading=lead,
                              on_stopped_leading=lost)
        finally:
            await self.stop()

    async def stop(self) -> None:
        self._stop = True
        await self.queue.close()
        for t in list(self._binding_tasks):
            t.cancel()
        await asyncio.gather(*self._binding_tasks, return_exceptions=True)
        for ext in self.extenders:
            close = getattr(ext, "close", None)
            if close is not None:
                try:
                    await close()
                except Exception:
                    pass
