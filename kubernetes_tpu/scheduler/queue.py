"""The 3-tier scheduling queue: activeQ / backoffQ / unschedulablePods.

Parity target: pkg/scheduler/internal/queue/scheduling_queue.go
(`PriorityQueue`: `Pop` blocks on the activeQ heap in QueueSort order;
`AddUnschedulableIfNotPresent` parks failed pods with per-pod exponential
backoff (podInitialBackoffSeconds 1s → podMaxBackoffSeconds 10s);
`MoveAllToActiveOrBackoffQueue` reacts to cluster events via QueueingHint
functions; `flushBackoffQCompleted` + `flushUnschedulablePodsLeftover` (60s)
timers; nominator tracks nominated nodes of preemptor pods).

TPU-first deviation: `pop_batch(max_pods)` drains up to P pods in one call —
the batched solver schedules them together, resolving intra-batch resource
contention inside the assignment solve instead of serially (SURVEY §3.1).
Single-pod `pop()` remains for the reference-shaped loop and tests.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time
from typing import Callable, Iterable, Mapping

from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.types import PodInfo


class ClusterEvent:
    """"Resource/Action" event that may make unschedulable pods schedulable
    (framework.ClusterEvent)."""

    __slots__ = ("resource", "action", "label")

    def __init__(self, resource: str, action: str):
        self.resource = resource
        self.action = action
        self.label = f"{resource}/{action}"


# QueueingHint verdicts (framework.QueueingHint)
QUEUE = "Queue"
QUEUE_SKIP = "QueueSkip"

#: hint fn: (pod, event) -> QUEUE | QUEUE_SKIP
HintFn = Callable[[PodInfo, ClusterEvent], str]


class SchedulingQueue:
    def __init__(
        self,
        framework: Framework,
        initial_backoff: float = 1.0,
        max_backoff: float = 10.0,
        unschedulable_flush_interval: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.framework = framework
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.unschedulable_flush_interval = unschedulable_flush_interval
        self.clock = clock

        self._seq = itertools.count()
        # activeQ: heap of (sort_key, seq, PodInfo)
        self._active: list[tuple[tuple, int, PodInfo]] = []
        self._active_keys: set[str] = set()
        # backoffQ: heap of (ready_time, seq, PodInfo)
        self._backoff: list[tuple[float, int, PodInfo]] = []
        self._backoff_keys: set[str] = set()
        # unschedulable: key -> (PodInfo, parked_at)
        self._unschedulable: dict[str, tuple[PodInfo, float]] = {}
        # gated (PreEnqueue rejected): key -> PodInfo
        self._gated: dict[str, PodInfo] = {}
        self._cond = asyncio.Condition()
        self._closed = False
        # moveRequestCycle bookkeeping: event hints per plugin.
        self._hints: dict[str, list[tuple[str, HintFn]]] = {}
        self._in_flight: set[str] = set()
        # Pods whose cycle was in flight when a cluster event fired: they
        # failed *concurrently* with the event, so they go to backoff (prompt
        # retry) instead of unschedulable (the reference's moveRequestCycle
        # comparison in AddUnschedulableIfNotPresent).
        self._moved_while_in_flight: set[str] = set()

    # -- configuration -----------------------------------------------------

    def register_hint(self, event_label: str, plugin: str, fn: HintFn) -> None:
        self._hints.setdefault(event_label, []).append((plugin, fn))

    # -- internals ---------------------------------------------------------

    def _sort_key(self, pi: PodInfo) -> tuple:
        # QueueSort order via framework.less is a comparator; encode the
        # default PrioritySort (priority desc, then FIFO) directly as a key
        # and let custom sorts override via plugin-provided key().
        for p in self.framework.queue_sort_plugins:
            key_fn = getattr(p, "key", None)
            if key_fn is not None:
                return key_fn(pi)
        return (-pi.priority, pi.queued_at)

    def _push_active(self, pi: PodInfo) -> None:
        if pi.key in self._active_keys:
            return
        # Every activeQ entry (first add, backoff flush, move_all) stamps
        # the queue-wait start for this attempt's retroactive span.
        pi.enqueued_at = self.clock()
        heapq.heappush(self._active, (self._sort_key(pi), next(self._seq), pi))
        self._active_keys.add(pi.key)

    def _backoff_duration(self, pi: PodInfo) -> float:
        # per-pod exponential: initial * 2^(attempts-1), capped.
        n = max(pi.attempts, 1)
        return min(self.initial_backoff * (2 ** (n - 1)), self.max_backoff)

    # -- public API --------------------------------------------------------

    async def add(self, pi: PodInfo) -> None:
        """New pending pod enters activeQ (unless gated by PreEnqueue)."""
        async with self._cond:
            if pi.queued_at == 0.0:
                pi.queued_at = self.clock()
            st = self.framework.run_pre_enqueue(pi)
            if not st.is_success():
                pi.unschedulable_plugins = {st.plugin} if st.plugin else set()
                self._gated[pi.key] = pi
                return
            self._remove_everywhere(pi.key)
            self._push_active(pi)
            self._cond.notify_all()

    async def update(self, pi: PodInfo) -> None:
        """Pod object changed while queued: refresh it wherever it sits; a
        gated pod gets re-evaluated (SchedulingGates removal path). add()
        handles removal from every tier via _remove_everywhere."""
        await self.add(pi)

    def _remove_everywhere(self, key: str) -> None:
        if key in self._active_keys:
            self._active = [(k, s, p) for (k, s, p) in self._active if p.key != key]
            heapq.heapify(self._active)
            self._active_keys.discard(key)
        if key in self._backoff_keys:
            self._backoff = [(t, s, p) for (t, s, p) in self._backoff if p.key != key]
            heapq.heapify(self._backoff)
            self._backoff_keys.discard(key)
        self._unschedulable.pop(key, None)
        self._gated.pop(key, None)

    async def delete(self, key: str) -> None:
        async with self._cond:
            self._remove_everywhere(key)

    async def pop(self) -> PodInfo | None:
        """Blocking pop of the highest-priority pod (queue.Pop)."""
        batch = await self.pop_batch(1)
        return batch[0] if batch else None

    async def pop_batch(self, max_pods: int) -> list[PodInfo]:
        """Drain up to max_pods from activeQ; blocks until ≥1 available.
        Flushes due backoff pods first so a ready backoff pod can't be
        starved by an empty activeQ."""
        async with self._cond:
            while True:
                self._flush_backoff_locked()
                if self._active or self._closed:
                    break
                # Wake when the earliest backoff pod becomes ready.
                timeout = None
                if self._backoff:
                    timeout = max(self._backoff[0][0] - self.clock(), 0.01)
                try:
                    await asyncio.wait_for(self._cond.wait(), timeout)
                except asyncio.TimeoutError:
                    continue
            if self._closed and not self._active:
                return []
            return self._drain_locked(max_pods)

    def _drain_locked(self, max_pods: int) -> list[PodInfo]:
        out: list[PodInfo] = []
        now = self.clock()
        while self._active and len(out) < max_pods:
            _, _, pi = heapq.heappop(self._active)
            self._active_keys.discard(pi.key)
            pi.attempts += 1
            # Queue-wait endpoint for the attempt's retroactive
            # scheduler.queue.wait span (queued_at → dequeued_at).
            pi.dequeued_at = now
            self._in_flight.add(pi.key)
            out.append(pi)
        return out

    async def pop_now(self, max_pods: int) -> list[PodInfo]:
        """NON-blocking drain: whatever is ready right now (due backoff
        flushed first), possibly empty — the serving tier's admission
        window merges this into a held dispatch after its coalesce
        sleep, where a blocking pop would stall the batch it already
        holds."""
        async with self._cond:
            self._flush_backoff_locked()
            if self._closed:
                return []
            return self._drain_locked(max_pods)

    def _flush_backoff_locked(self) -> None:
        now = self.clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, pi = heapq.heappop(self._backoff)
            self._backoff_keys.discard(pi.key)
            self._push_active(pi)

    async def add_unschedulable(self, pi: PodInfo) -> None:
        """Failed cycle: park the pod (AddUnschedulableIfNotPresent). If a
        cluster event fired while this pod's cycle was in flight, the event
        may have already fixed the failure — send the pod to backoff for a
        prompt retry instead of parking it (moveRequestCycle semantics)."""
        async with self._cond:
            self._in_flight.discard(pi.key)
            if pi.key in self._moved_while_in_flight:
                self._moved_while_in_flight.discard(pi.key)
                if pi.key not in self._active_keys and pi.key not in self._backoff_keys:
                    ready = self.clock() + self._backoff_duration(pi)
                    heapq.heappush(self._backoff, (ready, next(self._seq), pi))
                    self._backoff_keys.add(pi.key)
                    self._cond.notify_all()
                return
            if pi.key in self._active_keys or pi.key in self._backoff_keys:
                return
            self._unschedulable[pi.key] = (pi, self.clock())

    async def done(self, pod_key: str) -> None:
        """Cycle finished without requeue (scheduled or error-dropped)."""
        async with self._cond:
            self._in_flight.discard(pod_key)
            self._moved_while_in_flight.discard(pod_key)

    async def move_to_backoff(self, pi: PodInfo) -> None:
        async with self._cond:
            self._in_flight.discard(pi.key)
            self._moved_while_in_flight.discard(pi.key)
            if pi.key in self._active_keys or pi.key in self._backoff_keys:
                return
            ready = self.clock() + self._backoff_duration(pi)
            heapq.heappush(self._backoff, (ready, next(self._seq), pi))
            self._backoff_keys.add(pi.key)
            self._cond.notify_all()

    async def move_all(self, event: ClusterEvent) -> int:
        """Cluster event: re-activate unschedulable pods whose QueueingHints
        say the event may help (MoveAllToActiveOrBackoffQueue)."""
        return await self.move_all_batch([event])

    async def move_all_batch(self, events: list[ClusterEvent]) -> int:
        """One pass over the parked pods for a TICK's worth of coalesced
        events: a preemption wave deletes thousands of victims in bursts,
        and scanning every unschedulable pod once per delete event made
        event handling O(events × parked) — the batch scan moves a pod if
        ANY of the tick's events hints QUEUE, the same outcome as the
        sequential per-event scans over an unchanged queue state."""
        moved = 0
        async with self._cond:
            # Cycles currently in flight may be failing for a reason this
            # event just fixed; mark them so their failure lands in backoff.
            self._moved_while_in_flight.update(self._in_flight)
            # Gated pods re-run PreEnqueue: a gate can lift on events that
            # don't touch the pod object itself (e.g. Coscheduling's
            # minMember gate lifts when a SIBLING pod is created).
            for key in list(self._gated):
                pi = self._gated[key]
                if self.framework.run_pre_enqueue(pi).is_success():
                    del self._gated[key]
                    self._push_active(pi)
                    moved += 1
            for key in list(self._unschedulable):
                pi, _ = self._unschedulable[key]
                if not any(self._hint_says_queue(pi, event)
                           for event in events):
                    continue
                del self._unschedulable[key]
                if pi.attempts > 0 and self._backoff_duration(pi) > 0:
                    ready = self.clock() + self._backoff_duration(pi)
                    heapq.heappush(self._backoff, (ready, next(self._seq), pi))
                    self._backoff_keys.add(pi.key)
                else:
                    self._push_active(pi)
                moved += 1
            if moved:
                self._cond.notify_all()
        return moved

    def _hint_says_queue(self, pi: PodInfo, event: ClusterEvent) -> bool:
        hints = self._hints.get(event.label, [])
        if not hints:
            return True  # no hints registered for event → conservative requeue
        # Only hints from plugins that rejected this pod matter
        # (UnschedulablePlugins recorded at failure time).
        relevant = [fn for plugin, fn in hints
                    if not pi.unschedulable_plugins or plugin in pi.unschedulable_plugins]
        if not relevant:
            return False
        return any(fn(pi, event) == QUEUE for fn in relevant)

    async def flush_unschedulable_leftover(self) -> int:
        """Safety valve: pods parked longer than the flush interval re-enter
        backoff (flushUnschedulablePodsLeftover, 60s default)."""
        moved = 0
        async with self._cond:
            now = self.clock()
            for key in list(self._unschedulable):
                pi, parked_at = self._unschedulable[key]
                if now - parked_at < self.unschedulable_flush_interval:
                    continue
                del self._unschedulable[key]
                ready = now + self._backoff_duration(pi)
                heapq.heappush(self._backoff, (ready, next(self._seq), pi))
                self._backoff_keys.add(pi.key)
                moved += 1
            if moved:
                self._cond.notify_all()
        return moved

    async def run_flushers(self) -> None:
        """Background timers (SchedulingQueue.Run)."""
        try:
            while not self._closed:
                await asyncio.sleep(1.0)
                async with self._cond:
                    self._flush_backoff_locked()
                    self._cond.notify_all()
                await self.flush_unschedulable_leftover()
        except asyncio.CancelledError:
            return

    async def close(self) -> None:
        async with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- introspection (metrics: scheduler_pending_pods{queue=...}) --------

    def stats(self) -> dict[str, int]:
        return {
            "active": len(self._active),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
            "gated": len(self._gated),
            "in_flight": len(self._in_flight),
        }

    def backlog_depth(self) -> int:
        """Total pods the scheduler still owes work for (every tier plus
        in-flight cycles) — the open-loop churn battery's saturation
        signal: under sustained arrivals this growing without bound IS
        the knee, where a drain bench would only show a slower clock."""
        return (len(self._active) + len(self._backoff)
                + len(self._unschedulable) + len(self._gated)
                + len(self._in_flight))

    def has_parked(self) -> bool:
        """Anything a cluster event could wake (gated or unschedulable)."""
        return bool(self._gated or self._unschedulable)
