"""Scheduler cache: in-memory mirror of nodes+pods with assume/confirm/expire
and incremental generation-based snapshots.

Parity target: pkg/scheduler/internal/cache/cache.go (`cacheImpl`:
`AssumePod`, `FinishBinding`, `ForgetPod`, `AddPod`, `RemovePod`,
`AddNode`/`UpdateNode`/`RemoveNode`, `UpdateSnapshot` — generation-numbered
incremental copy; assumed pods expire after a TTL (`durationToExpireAssumedPod`,
default 15 min, 0 = never) unless confirmed by observing the bound pod).

The assume protocol is what lets binding be asynchronous: the cycle writes the
assumed pod into the cache *optimistically* so the next cycle's snapshot sees
its resources as taken; the informer later confirms (AddPod for the bound pod)
or the TTL expires it (bind failed and nobody told us).

Batched-pop deviation: assume() is called for every pod in a solver batch
before any binding starts — intra-batch contention is already resolved inside
the solver, so assumes cannot conflict (SURVEY §3.1 note).
"""

from __future__ import annotations

import logging
import time
from typing import Mapping

from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

logger = logging.getLogger(__name__)


class SchedulerCache:
    def __init__(self, assumed_pod_ttl: float = 900.0):
        self.nodes: dict[str, NodeInfo] = {}
        # pod key -> (PodInfo, node_name, assumed, finished_binding, deadline)
        self._pod_states: dict[str, dict] = {}
        self.assumed_pod_ttl = assumed_pod_ttl
        self._generation = 0
        # Snapshot bookkeeping: cached NodeInfo clones by name.
        self._snap_nodes: dict[str, NodeInfo] = {}
        # Incremental-snapshot state (the 200k-preset host-prep fix):
        # event handlers mark DIRTY node names; update_snapshot touches
        # only those instead of walking all N nodes per cycle. The
        # stable snapshot-order list + position map let Snapshot
        # construction be pointer copies, and the (generation, index)
        # changed-log hands ops/tensorize its O(changed) delta.
        self._dirty: set[str] = set()
        self._full = True            # first snapshot / node removal
        self._snap_list: list[NodeInfo] = []
        self._snap_pos: dict[str, int] = {}
        self._aff_names: set[str] = set()
        self._anti_names: set[str] = set()
        self._set_epoch = 0          # bumps when the node set changes
        self._spec_seq = 0           # bumps on any node OBJECT update
        self._changed_log: list[tuple[int, int]] = []
        self._log_floor = 0          # gens ≤ floor are out of the log
        self._last_snap: Snapshot | None = None

    def _bump(self, node: NodeInfo) -> None:
        self._generation += 1
        node.generation = self._generation
        self._dirty.add(node.name)

    # -- nodes -------------------------------------------------------------

    def add_node(self, node: Mapping) -> None:
        name = node["metadata"]["name"]
        ni = self.nodes.get(name)
        if ni is None:
            ni = NodeInfo(node)
            self.nodes[name] = ni
        else:
            ni.set_node(node)
        self._spec_seq += 1  # node OBJECT changed: taints/alloc may move
        self._bump(ni)

    def update_node(self, node: Mapping) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        self.nodes.pop(name, None)
        self._snap_nodes.pop(name, None)
        self._generation += 1
        self._spec_seq += 1
        self._full = True  # positions shift: full re-snapshot on deletion

    # -- pods --------------------------------------------------------------

    def assume_pod(self, pi: PodInfo, node_name: str) -> None:
        if pi.key in self._pod_states:
            raise ValueError(f"pod {pi.key} already assumed/added")
        ni = self.nodes.get(node_name)
        if ni is None:
            raise KeyError(f"assume: unknown node {node_name}")
        ni.add_pod(pi)
        self._bump(ni)
        self._pod_states[pi.key] = {
            "pod": pi, "node": node_name, "assumed": True,
            "finished": False, "deadline": None,
        }

    def finish_binding(self, pod_key: str, now: float | None = None) -> None:
        st = self._pod_states.get(pod_key)
        if st is None or not st["assumed"]:
            return
        st["finished"] = True
        if self.assumed_pod_ttl > 0:
            st["deadline"] = (now or time.monotonic()) + self.assumed_pod_ttl

    def forget_pod(self, pod_key: str) -> None:
        """Undo an assume (bind failed)."""
        st = self._pod_states.pop(pod_key, None)
        if st is None:
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pod_key)
            self._bump(ni)

    def add_pod(self, pi: PodInfo) -> None:
        """Informer confirms a bound pod. If it was assumed: confirm (or move
        if the API says a different node than we assumed)."""
        st = self._pod_states.get(pi.key)
        if st is not None and st["assumed"]:
            if st["node"] != pi.node_name:
                logger.warning("pod %s assumed on %s but bound to %s; correcting",
                               pi.key, st["node"], pi.node_name)
                self.forget_pod(pi.key)
                self._add_confirmed(pi)
            else:
                st["assumed"] = False
                st["deadline"] = None
                st["pod"] = pi
            return
        if st is not None:
            return  # duplicate add
        self._add_confirmed(pi)

    def _add_confirmed(self, pi: PodInfo) -> None:
        ni = self.nodes.get(pi.node_name)
        if ni is None:
            # Pod bound to a node we haven't seen yet: create a placeholder
            # (the reference tolerates this ordering with an imaginary node).
            ni = NodeInfo()
            ni.name = pi.node_name
            self.nodes[pi.node_name] = ni
        ni.add_pod(pi)
        self._bump(ni)
        self._pod_states[pi.key] = {
            "pod": pi, "node": pi.node_name, "assumed": False,
            "finished": True, "deadline": None,
        }

    def update_pod(self, pi: PodInfo) -> None:
        st = self._pod_states.get(pi.key)
        if st is None:
            if pi.node_name:
                self.add_pod(pi)
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pi.key)
            self._bump(ni)
        del self._pod_states[pi.key]
        if pi.node_name:
            self.add_pod(pi)

    def remove_pod(self, pod_key: str) -> None:
        st = self._pod_states.pop(pod_key, None)
        if st is None:
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pod_key)
            self._bump(ni)

    def is_assumed(self, pod_key: str) -> bool:
        st = self._pod_states.get(pod_key)
        return bool(st and st["assumed"])

    def cleanup_expired(self, now: float | None = None) -> list[str]:
        """Expire assumed-and-finished pods past their TTL
        (cleanupAssumedPods, run periodically)."""
        now = now or time.monotonic()
        expired = [
            k for k, st in self._pod_states.items()
            if st["assumed"] and st["finished"]
            and st["deadline"] is not None and st["deadline"] <= now
        ]
        for k in expired:
            logger.warning("assumed pod %s expired without confirmation", k)
            self.forget_pod(k)
        return expired

    # -- snapshot ----------------------------------------------------------

    def _clone_into_snap(self, name: str, ni: NodeInfo) -> None:
        clone = ni.clone()
        self._snap_nodes[name] = clone
        pos = self._snap_pos.get(name)
        if pos is None:
            pos = self._snap_pos[name] = len(self._snap_list)
            self._snap_list.append(clone)
            self._set_epoch += 1  # node set grew: tensors re-key
        else:
            self._snap_list[pos] = clone
            self._changed_log.append((clone.generation, pos))
        if clone.pods_with_affinity:
            self._aff_names.add(name)
        else:
            self._aff_names.discard(name)
        if clone.pods_with_required_anti_affinity:
            self._anti_names.add(name)
        else:
            self._anti_names.discard(name)

    def update_snapshot(self) -> Snapshot:
        """Incremental snapshot off the event stream: only DIRTY nodes
        (marked by the informer/assume handlers' `_bump`) are re-cloned —
        O(changed) per cycle, not UpdateSnapshot's O(N) generation walk,
        which at the 200k preset cost more than the scheduling work it
        fed. Node removals fall back to one full rebuild (positions
        shift). The returned Snapshot carries the incremental host-prep
        handles ops/tensorize consumes (set_epoch / spec_seq /
        changed_since)."""
        if not self._full and not self._dirty \
                and self._last_snap is not None:
            # Nothing moved since the last snapshot (generation can only
            # advance through _bump/remove_node, which set dirty/_full):
            # hand back the SAME immutable-by-convention snapshot — the
            # scheduler re-snapshots ~10× per cycle and the no-op calls
            # must not pay two O(N) copies each at 200k nodes.
            return self._last_snap
        self._refresh_clones()
        snap = self._make_snapshot(self._snap_list.copy(),
                                   dict(self._snap_nodes))
        self._last_snap = snap
        return snap

    def light_snapshot(self) -> Snapshot:
        """ZERO-COPY snapshot for the serving fast path: same clone
        maintenance as update_snapshot, but the returned Snapshot WRAPS
        the cache's live list/dict instead of copying them — the two
        O(N) copies were most of the fast path's host wall at 5k nodes,
        paid per lone-pod placement for a one-row change.

        Contract: consume SYNCHRONOUSLY and drop before the next cache
        mutation — any assume/informer event replaces entries beneath
        it (update_snapshot's copies exist precisely for callers that
        hold snapshots across mutations, like the batch pipeline's
        chunked verify). Never cached as _last_snap for the same
        reason."""
        if self._full or self._dirty:
            # This maintenance clears the dirty set, but _last_snap's
            # COPIED lists still hold the pre-mutation clones — without
            # this invalidation the next update_snapshot()'s clean-path
            # guard would hand that stale snapshot back.
            self._last_snap = None
        self._refresh_clones()
        return self._make_snapshot(self._snap_list, self._snap_nodes)

    def _refresh_clones(self) -> None:
        """Shared maintenance: re-clone dirty/removed nodes into the
        stable snapshot list (see update_snapshot)."""
        if self._full:
            self._snap_nodes = {}
            self._snap_list = []
            self._snap_pos = {}
            self._aff_names = set()
            self._anti_names = set()
            self._changed_log = []
            self._log_floor = self._generation
            self._set_epoch += 1
            for name, ni in self.nodes.items():
                self._clone_into_snap(name, ni)
            self._full = False
            self._dirty.clear()
        elif self._dirty:
            for name in self._dirty:
                ni = self.nodes.get(name)
                if ni is None:
                    continue  # removal already forced _full
                cached = self._snap_nodes.get(name)
                if cached is None or cached.generation != ni.generation:
                    self._clone_into_snap(name, ni)
            self._dirty.clear()
            # Bound the log: once it outgrows the node set several times
            # over, one full tensor re-scan is cheaper than carrying it.
            if len(self._changed_log) > 4 * len(self._snap_list) + 65536:
                self._changed_log = []
                self._log_floor = self._generation

    def _make_snapshot(self, nodes: list, by_name: dict) -> Snapshot:
        # Affinity lists in snapshot-position order (deterministic — the
        # unsharded and sharded paths must build identical tables).
        pos = self._snap_pos.get
        snap = Snapshot(nodes, self._generation,
                        by_name=by_name,
                        have_affinity=[self._snap_nodes[n] for n in
                                       sorted(self._aff_names, key=pos)],
                        have_anti_affinity=[self._snap_nodes[n] for n in
                                            sorted(self._anti_names,
                                                   key=pos)])
        snap.set_epoch = self._set_epoch
        snap.spec_seq = self._spec_seq
        log, log_len, floor = self._changed_log, len(self._changed_log), \
            self._log_floor

        def changed_since(gen: int, _log=log, _n=log_len, _floor=floor):
            """Snapshot-order indices changed after `gen`; None when the
            window doesn't reach back that far (caller full-scans).
            Entries are appended per update_snapshot batch, and every
            batch's generations exceed the previous snapshot's, so a
            back-scan terminates exactly at the boundary."""
            if gen < _floor:
                return None
            out = set()
            i = _n - 1
            while i >= 0 and _log[i][0] > gen:
                out.add(_log[i][1])
                i -= 1
            return out

        snap.changed_since = changed_since
        return snap

    def pod_count(self) -> int:
        return len(self._pod_states)
