"""Scheduler cache: in-memory mirror of nodes+pods with assume/confirm/expire
and incremental generation-based snapshots.

Parity target: pkg/scheduler/internal/cache/cache.go (`cacheImpl`:
`AssumePod`, `FinishBinding`, `ForgetPod`, `AddPod`, `RemovePod`,
`AddNode`/`UpdateNode`/`RemoveNode`, `UpdateSnapshot` — generation-numbered
incremental copy; assumed pods expire after a TTL (`durationToExpireAssumedPod`,
default 15 min, 0 = never) unless confirmed by observing the bound pod).

The assume protocol is what lets binding be asynchronous: the cycle writes the
assumed pod into the cache *optimistically* so the next cycle's snapshot sees
its resources as taken; the informer later confirms (AddPod for the bound pod)
or the TTL expires it (bind failed and nobody told us).

Batched-pop deviation: assume() is called for every pod in a solver batch
before any binding starts — intra-batch contention is already resolved inside
the solver, so assumes cannot conflict (SURVEY §3.1 note).
"""

from __future__ import annotations

import logging
import time
from typing import Mapping

from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

logger = logging.getLogger(__name__)


class SchedulerCache:
    def __init__(self, assumed_pod_ttl: float = 900.0):
        self.nodes: dict[str, NodeInfo] = {}
        # pod key -> (PodInfo, node_name, assumed, finished_binding, deadline)
        self._pod_states: dict[str, dict] = {}
        self.assumed_pod_ttl = assumed_pod_ttl
        self._generation = 0
        # Snapshot bookkeeping: cached NodeInfo clones by name + the
        # generation they were copied at.
        self._snap_nodes: dict[str, NodeInfo] = {}
        self._snap_generation = -1

    def _bump(self, node: NodeInfo) -> None:
        self._generation += 1
        node.generation = self._generation

    # -- nodes -------------------------------------------------------------

    def add_node(self, node: Mapping) -> None:
        name = node["metadata"]["name"]
        ni = self.nodes.get(name)
        if ni is None:
            ni = NodeInfo(node)
            self.nodes[name] = ni
        else:
            ni.set_node(node)
        self._bump(ni)

    def update_node(self, node: Mapping) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        self.nodes.pop(name, None)
        self._snap_nodes.pop(name, None)
        self._generation += 1
        self._snap_generation = -1  # force full re-snapshot on deletion

    # -- pods --------------------------------------------------------------

    def assume_pod(self, pi: PodInfo, node_name: str) -> None:
        if pi.key in self._pod_states:
            raise ValueError(f"pod {pi.key} already assumed/added")
        ni = self.nodes.get(node_name)
        if ni is None:
            raise KeyError(f"assume: unknown node {node_name}")
        ni.add_pod(pi)
        self._bump(ni)
        self._pod_states[pi.key] = {
            "pod": pi, "node": node_name, "assumed": True,
            "finished": False, "deadline": None,
        }

    def finish_binding(self, pod_key: str, now: float | None = None) -> None:
        st = self._pod_states.get(pod_key)
        if st is None or not st["assumed"]:
            return
        st["finished"] = True
        if self.assumed_pod_ttl > 0:
            st["deadline"] = (now or time.monotonic()) + self.assumed_pod_ttl

    def forget_pod(self, pod_key: str) -> None:
        """Undo an assume (bind failed)."""
        st = self._pod_states.pop(pod_key, None)
        if st is None:
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pod_key)
            self._bump(ni)

    def add_pod(self, pi: PodInfo) -> None:
        """Informer confirms a bound pod. If it was assumed: confirm (or move
        if the API says a different node than we assumed)."""
        st = self._pod_states.get(pi.key)
        if st is not None and st["assumed"]:
            if st["node"] != pi.node_name:
                logger.warning("pod %s assumed on %s but bound to %s; correcting",
                               pi.key, st["node"], pi.node_name)
                self.forget_pod(pi.key)
                self._add_confirmed(pi)
            else:
                st["assumed"] = False
                st["deadline"] = None
                st["pod"] = pi
            return
        if st is not None:
            return  # duplicate add
        self._add_confirmed(pi)

    def _add_confirmed(self, pi: PodInfo) -> None:
        ni = self.nodes.get(pi.node_name)
        if ni is None:
            # Pod bound to a node we haven't seen yet: create a placeholder
            # (the reference tolerates this ordering with an imaginary node).
            ni = NodeInfo()
            ni.name = pi.node_name
            self.nodes[pi.node_name] = ni
        ni.add_pod(pi)
        self._bump(ni)
        self._pod_states[pi.key] = {
            "pod": pi, "node": pi.node_name, "assumed": False,
            "finished": True, "deadline": None,
        }

    def update_pod(self, pi: PodInfo) -> None:
        st = self._pod_states.get(pi.key)
        if st is None:
            if pi.node_name:
                self.add_pod(pi)
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pi.key)
            self._bump(ni)
        del self._pod_states[pi.key]
        if pi.node_name:
            self.add_pod(pi)

    def remove_pod(self, pod_key: str) -> None:
        st = self._pod_states.pop(pod_key, None)
        if st is None:
            return
        ni = self.nodes.get(st["node"])
        if ni is not None:
            ni.remove_pod(pod_key)
            self._bump(ni)

    def is_assumed(self, pod_key: str) -> bool:
        st = self._pod_states.get(pod_key)
        return bool(st and st["assumed"])

    def cleanup_expired(self, now: float | None = None) -> list[str]:
        """Expire assumed-and-finished pods past their TTL
        (cleanupAssumedPods, run periodically)."""
        now = now or time.monotonic()
        expired = [
            k for k, st in self._pod_states.items()
            if st["assumed"] and st["finished"]
            and st["deadline"] is not None and st["deadline"] <= now
        ]
        for k in expired:
            logger.warning("assumed pod %s expired without confirmation", k)
            self.forget_pod(k)
        return expired

    # -- snapshot ----------------------------------------------------------

    def update_snapshot(self) -> Snapshot:
        """Incremental snapshot: only nodes whose generation advanced since
        the last snapshot are re-cloned (UpdateSnapshot's generation walk)."""
        for name, ni in self.nodes.items():
            cached = self._snap_nodes.get(name)
            if cached is None or cached.generation != ni.generation:
                self._snap_nodes[name] = ni.clone()
        for name in list(self._snap_nodes):
            if name not in self.nodes:
                del self._snap_nodes[name]
        self._snap_generation = self._generation
        return Snapshot(list(self._snap_nodes.values()), self._generation)

    def pod_count(self) -> int:
        return len(self._pod_states)
