"""kube-scheduler analog: `python -m kubernetes_tpu.scheduler`.

Connects to an apiserver (KTPU wire preferred, HTTP fallback), builds the
scheduler from a KubeSchedulerConfiguration file (profiles, plugins,
TPUScorer gate → batched TPU backend), and runs the scheduling loop —
with leader election when the config enables it.

    python -m kubernetes_tpu.scheduler --server http://127.0.0.1:8080 \
        --config scheduler-config.yaml --batch-size 4096

Parity target: cmd/kube-scheduler (SURVEY §2.1).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpu-scheduler", description=__doc__)
    ap.add_argument("--server", default=None,
                    help="HTTP apiserver URL (e.g. http://127.0.0.1:8080)")
    ap.add_argument("--wire", default=None,
                    help="KTPU wire target (host:port or unix:/path) — "
                         "preferred transport when given")
    ap.add_argument("--token", default=None)
    ap.add_argument("--config", default=None,
                    help="KubeSchedulerConfiguration YAML")
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--feature-gates", default="",
                    help='e.g. "TPUScorer=true"')
    return ap


async def serve(args) -> None:
    if args.wire:
        from kubernetes_tpu.apiserver.wire import WireStore
        store = WireStore(args.wire, token=args.token,
                          user_agent="ktpu-scheduler")
    elif args.server:
        from kubernetes_tpu.apiserver.client import RemoteStore
        store = RemoteStore(args.server, token=args.token,
                            user_agent="ktpu-scheduler")
    else:
        raise SystemExit("one of --server / --wire is required")

    from kubernetes_tpu.client import InformerFactory
    from kubernetes_tpu.config.scheduler import build_scheduler
    from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATES
    if args.feature_gates:
        DEFAULT_FEATURE_GATES.set_from_spec(args.feature_gates)
    cfg = None
    if args.config:
        import yaml
        with open(args.config) as f:
            cfg = yaml.safe_load(f)
    sched = build_scheduler(store, cfg,
                            feature_gates=DEFAULT_FEATURE_GATES)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    elector = getattr(sched, "leader_elector", None)
    if elector is not None:
        run_task = asyncio.ensure_future(
            sched.run_with_leader_election(elector,
                                           batch_size=args.batch_size))
    else:
        run_task = asyncio.ensure_future(
            sched.run(batch_size=args.batch_size))
    logging.info("scheduler running (batch=%d)", args.batch_size)
    await stop.wait()
    await sched.stop()
    run_task.cancel()
    factory.stop()
    close = getattr(store, "close", None)
    if close is not None:
        await close()


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    asyncio.run(serve(args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
