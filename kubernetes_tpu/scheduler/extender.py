"""Scheduler extender: the out-of-process HTTP webhook seam.

Parity target: pkg/scheduler/extender.go (`HTTPExtender` —
`Filter`/`Prioritize`/`Bind`, node-cache option, ignorable errors,
managed-resources interest check) with the wire types from
pkg/scheduler/apis/config/types.go:

- ExtenderArgs       {"pod": Pod, "nodes": NodeList | "nodenames": [str]}
- ExtenderFilterResult {"nodes"|"nodenames", "failedNodes": {name: reason},
                        "failedAndUnresolvableNodes": {...}, "error": str}
- HostPriorityList   [{"host": str, "score": int}]   (0..MaxExtenderPriority,
                      multiplied by the extender's weight by the caller)
- ExtenderBindingArgs {"podName","podNamespace","podUID","node"}
- ExtenderBindingResult {"error": str}

This is north-star seam #2 (BASELINE.json): the TPU solver can also be
PACKAGED as one of these — `ExtenderServer` below serves the verbs over
aiohttp, so a stock kube-scheduler can delegate filter/prioritize to this
framework with no in-process integration.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Mapping, Sequence

import aiohttp
from aiohttp import web

from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo

logger = logging.getLogger(__name__)

#: extender.go MaxExtenderPriority.
MAX_EXTENDER_PRIORITY = 10


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """One configured extender webhook (config API `Extender`)."""

    def __init__(self, url_prefix: str, *,
                 filter_verb: str = "",
                 prioritize_verb: str = "",
                 bind_verb: str = "",
                 preempt_verb: str = "",
                 weight: int = 1,
                 node_cache_capable: bool = False,
                 ignorable: bool = False,
                 managed_resources: Sequence[str] = (),
                 timeout: float = 5.0,
                 name: str = ""):
        self.url_prefix = url_prefix.rstrip("/")
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.bind_verb = bind_verb
        self.preempt_verb = preempt_verb
        self.weight = weight
        self.node_cache_capable = node_cache_capable
        self.ignorable = ignorable
        self.managed_resources = set(managed_resources)
        self.timeout = timeout
        self.name = name or url_prefix
        self._session: aiohttp.ClientSession | None = None

    @classmethod
    def from_config(cls, cfg: Mapping) -> "HTTPExtender":
        """Build from a KubeSchedulerConfiguration `extenders:` entry
        (reference YAML field names)."""
        return cls(
            cfg["urlPrefix"],
            filter_verb=cfg.get("filterVerb", ""),
            prioritize_verb=cfg.get("prioritizeVerb", ""),
            bind_verb=cfg.get("bindVerb", ""),
            preempt_verb=cfg.get("preemptVerb", ""),
            weight=cfg.get("weight", 1),
            node_cache_capable=cfg.get("nodeCacheCapable", False),
            ignorable=cfg.get("ignorable", False),
            managed_resources=[
                m["name"] for m in cfg.get("managedResources", [])],
            timeout=_parse_duration(cfg.get("httpTimeout", "5s")),
            name=cfg.get("urlPrefix", ""),
        )

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def is_interested(self, pod: PodInfo) -> bool:
        """extender.go IsInterested: no managedResources = all pods;
        otherwise only pods requesting one of them."""
        if not self.managed_resources:
            return True
        return any(r in self.managed_resources for r in pod.requests)

    async def _post(self, verb: str, payload: dict) -> dict:
        url = f"{self.url_prefix}/{verb}"
        async with self._sess().post(url, json=payload) as resp:
            if resp.status != 200:
                raise ExtenderError(
                    f"extender {self.name}: {verb} returned {resp.status}")
            return await resp.json()

    async def filter(self, pod: PodInfo, nodes: list[NodeInfo]
                     ) -> tuple[list[NodeInfo], dict[str, str],
                                dict[str, str]]:
        """→ (feasible, failed{name: reason}, failed_unresolvable).

        On error: ignorable → all nodes pass; else ExtenderError
        (extender.go findNodesThatPassExtenders).
        """
        if not self.filter_verb or not self.is_interested(pod):
            return nodes, {}, {}
        by_name = {ni.name: ni for ni in nodes}
        args: dict = {"pod": pod.pod}
        if self.node_cache_capable:
            args["nodenames"] = list(by_name)
        else:
            args["nodes"] = {"items": [ni.node for ni in nodes]}
        try:
            res = await self._post(self.filter_verb, args)
        except (ExtenderError, aiohttp.ClientError, asyncio.TimeoutError) as e:
            if self.ignorable:
                logger.warning(
                    "ignoring ignorable extender %s filter error: %s",
                    self.name, e)
                return nodes, {}, {}
            raise ExtenderError(str(e)) from e
        if res.get("error"):
            if self.ignorable:
                return nodes, {}, {}
            raise ExtenderError(res["error"])
        if self.node_cache_capable and res.get("nodenames") is not None:
            keep = [by_name[n] for n in res["nodenames"] if n in by_name]
        elif res.get("nodes") is not None:
            keep = [by_name[o["metadata"]["name"]]
                    for o in res["nodes"].get("items", [])
                    if o["metadata"]["name"] in by_name]
        else:
            keep = nodes
        return (keep, dict(res.get("failedNodes") or {}),
                dict(res.get("failedAndUnresolvableNodes") or {}))

    async def prioritize(self, pod: PodInfo, nodes: list[NodeInfo]
                         ) -> dict[str, float]:
        """→ {node: score × weight}; errors score 0 (prioritizeNodes
        swallows extender priority errors)."""
        if not self.prioritize_verb or not self.is_interested(pod):
            return {}
        args: dict = {"pod": pod.pod}
        if self.node_cache_capable:
            args["nodenames"] = [ni.name for ni in nodes]
        else:
            args["nodes"] = {"items": [ni.node for ni in nodes]}
        try:
            res = await self._post(self.prioritize_verb, args)
        except (ExtenderError, aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("extender %s prioritize error (scored 0): %s",
                           self.name, e)
            return {}
        return {h["host"]: float(h["score"]) * self.weight
                for h in res or []}

    async def bind(self, pod: PodInfo, node_name: str) -> None:
        """ExtenderBindingArgs POST; raises ExtenderError on failure."""
        res = await self._post(self.bind_verb, {
            "podName": pod.name,
            "podNamespace": pod.namespace,
            "podUID": pod.pod.get("metadata", {}).get("uid", ""),
            "node": node_name,
        })
        if res and res.get("error"):
            raise ExtenderError(res["error"])


def _parse_duration(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    s = str(s)
    if s.endswith("ms"):
        return float(s[:-2]) / 1000
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60
    return float(s)


class ExtenderServer:
    """In-repo demo extender: serves the webhook verbs over aiohttp.

    Callbacks get plain wire dicts and return wire results — exactly what a
    real out-of-process extender (e.g. this framework packaged as the TPU
    scoring sidecar for a stock kube-scheduler) would implement.

    filter_fn(pod, nodes|nodenames) -> (kept_names, failed{name: reason})
    prioritize_fn(pod, names) -> {name: score 0..10}
    bind_fn(args) -> None | error string
    """

    def __init__(self, *,
                 filter_fn: Callable | None = None,
                 prioritize_fn: Callable | None = None,
                 bind_fn: Callable | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.filter_fn = filter_fn
        self.prioritize_fn = prioritize_fn
        self.bind_fn = bind_fn
        self.host, self.port = host, port
        self._runner: web.AppRunner | None = None
        self.requests: list[tuple[str, dict]] = []  # observability for tests

        app = web.Application()
        app.router.add_post("/filter", self._filter)
        app.router.add_post("/prioritize", self._prioritize)
        app.router.add_post("/bind", self._bind)
        self.app = app

    @staticmethod
    def _names(args: dict) -> list[str]:
        if args.get("nodenames") is not None:
            return list(args["nodenames"])
        return [o["metadata"]["name"]
                for o in (args.get("nodes") or {}).get("items", [])]

    async def _filter(self, request: web.Request) -> web.Response:
        args = await request.json()
        self.requests.append(("filter", args))
        names = self._names(args)
        if self.filter_fn is None:
            kept, failed = names, {}
        else:
            kept, failed = self.filter_fn(args["pod"], names)
        body: dict = {"failedNodes": failed, "error": ""}
        if args.get("nodenames") is not None:
            body["nodenames"] = kept
        else:
            by_name = {o["metadata"]["name"]: o
                       for o in (args.get("nodes") or {}).get("items", [])}
            body["nodes"] = {"items": [by_name[n] for n in kept]}
        return web.json_response(body)

    async def _prioritize(self, request: web.Request) -> web.Response:
        args = await request.json()
        self.requests.append(("prioritize", args))
        names = self._names(args)
        scores = (self.prioritize_fn(args["pod"], names)
                  if self.prioritize_fn else {})
        return web.json_response(
            [{"host": n, "score": int(scores.get(n, 0))} for n in names])

    async def _bind(self, request: web.Request) -> web.Response:
        args = await request.json()
        self.requests.append(("bind", args))
        err = self.bind_fn(args) if self.bind_fn else None
        return web.json_response({"error": err or ""})

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        server = site._server  # noqa: SLF001
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
