"""The scheduler: framework extension points, 3-tier queue, assume/expire
cache, in-tree plugins, and the batched TPU execution backend."""

from kubernetes_tpu.scheduler.framework import (
    CycleState,
    Framework,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.queue import ClusterEvent, SchedulingQueue
from kubernetes_tpu.scheduler.scheduler import FitError, Scheduler
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Resource, Snapshot

__all__ = [
    "CycleState",
    "Framework",
    "Plugin",
    "Status",
    "SchedulerCache",
    "ClusterEvent",
    "SchedulingQueue",
    "FitError",
    "Scheduler",
    "NodeInfo",
    "PodInfo",
    "Resource",
    "Snapshot",
]
