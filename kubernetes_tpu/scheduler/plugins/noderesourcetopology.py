"""NodeResourceTopologyMatch: NUMA-aware filtering + scoring for pods
requesting device/extended resources (BASELINE config #4).

Parity target: kubernetes-sigs/scheduler-plugins `pkg/noderesourcetopology`
(out-of-tree, like Coscheduling) over the NodeResourceTopology CRD
(`topology.node.k8s.io/v1alpha2`), which mirrors the kubelet's
topologymanager + devicemanager NUMA accounting (SURVEY §2.5 `cm/`).

Object shape (one per node, name == node name):

    apiVersion: topology.node.k8s.io/v1alpha2
    kind: NodeResourceTopology
    metadata: {name: node-0}
    topologyPolicies: [SingleNUMANodeContainerLevel]
    zones:
    - name: node-0            # NUMA node 0
      type: Node
      resources:
      - {name: google.com/tpu, capacity: "4"}
      - {name: cpu, capacity: "4"}

Divergence from the reference plugin, by design: the reference trusts the
CRD's per-zone `available` column, refreshed by a node agent (RTE). This
framework's nodes are KWOK-simulated — there is no agent — so zone usage is
recomputed scheduler-side by deterministically packing the node's resident
pods (sorted by pod key, first-fit in zone order) into zones. That keeps
Filter/Score exact under the batched backend too: the backend's working
snapshot already carries same-batch placements, so the zone accounting sees
them (ops/backend.py `_verify` stateful path).

Filter (single-NUMA policies): some zone must fit ALL of the pod's
zone-tracked requests — resources no zone lists are unconstrained.
Score: LeastAllocated over the best-fitting zone (scoringStrategy arg
accepts LeastAllocated | MostAllocated | BalancedAllocation).
"""

from __future__ import annotations

import statistics

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.scheduler.framework import CycleState, Plugin, Status
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

#: Policies that require single-NUMA alignment (the CRD's values).
SINGLE_NUMA_POLICIES = {
    "SingleNUMANodeContainerLevel",
    "SingleNUMANodePodLevel",
    "single-numa-node",
}

_STATE_KEY = "NodeResourceTopologyMatch/requests"


def _zone_caps(nrt: dict) -> list[tuple[str, dict[str, int]]]:
    """[(zone name, {resource: capacity milli})] in declared order."""
    out = []
    for z in nrt.get("zones") or []:
        caps = {}
        for r in z.get("resources") or []:
            name = r.get("name")
            if name:
                caps[name] = parse_quantity(
                    r.get("allocatable", r.get("capacity", 0)))
        out.append((z.get("name", ""), caps))
    return out


def pack_zones(nrt: dict, node: NodeInfo) -> list[dict[str, int]]:
    """Free capacity per zone after first-fit packing the node's resident
    pods (sorted by key for determinism across host/backend paths)."""
    zones = _zone_caps(nrt)
    free = [dict(caps) for _, caps in zones]
    if not free:
        return free
    tracked = set()
    for caps in free:
        tracked.update(caps)
    for pi in sorted(node.pods, key=lambda p: p.key):
        reqs = {r: v for r, v in pi.requests.items()
                if v > 0 and r in tracked}
        if not reqs:
            continue
        for zf in free:
            if all(zf.get(r, 0) >= v for r, v in reqs.items()):
                for r, v in reqs.items():
                    zf[r] -= v
                break
        # No zone fits → the pod predates topology constraints (or another
        # policy placed it); its usage is already counted node-level by
        # NodeResourcesFit, so it is not charged to any single zone here.
    return free


class NodeResourceTopologyMatch(Plugin):
    NAME = "NodeResourceTopologyMatch"
    EXTENSION_POINTS = ("PreFilter", "Filter", "Score")
    # NRT churn (agent raises a zone's capacity) must requeue pods parked
    # on "cannot align" — EventsToRegister parity with scheduler-plugins.
    EVENTS = ["Pod/Delete", "Node/Add", "Node/Update",
              "NodeResourceTopology/Add", "NodeResourceTopology/Update"]

    def __init__(self, args=None):
        super().__init__(args)
        self.strategy = (self.args.get("scoringStrategy") or {}).get(
            "type", "LeastAllocated")
        self._nrt_informer = None
        #: resources appearing in any zone of any NRT object — the cheap
        #: activity gate the batched backend consults per pod.
        self._zone_resources: set[str] = set()
        #: bumped on every NRT add/update — cache-invalidation handle for
        #: the batched backend's zone tensors (NRT writes don't move the
        #: node snapshot generation).
        self.nrt_seq = 0

    def set_informers(self, factory) -> None:
        self._nrt_informer = factory.informer("noderesourcetopologies")

        def track(obj):
            self.nrt_seq += 1
            for z in obj.get("zones") or []:
                for r in z.get("resources") or []:
                    if r.get("name"):
                        self._zone_resources.add(r["name"])

        from kubernetes_tpu.client import ResourceEventHandler
        self._nrt_informer.add_event_handler(ResourceEventHandler(
            on_add=track, on_update=lambda old, new: track(new)))

    def active_for(self, pi: PodInfo) -> bool:
        if self._nrt_informer is None:
            return False
        return any(v > 0 and r in self._zone_resources
                   for r, v in pi.requests.items())

    def _nrt(self, node_name: str) -> dict | None:
        if self._nrt_informer is None:
            return None
        return self._nrt_informer.indexer.get(node_name)

    # -- PreFilter ---------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not self.active_for(pod):
            return Status.skip()
        state.write(_STATE_KEY, dict(pod.requests))
        return Status.success()

    # -- Filter: single-NUMA alignment ------------------------------------

    def _fit_zones(self, pod: PodInfo, node: NodeInfo
                   ) -> tuple[list[dict[str, int]], list[int]] | None:
        """(zone free list, indexes of zones that fit the pod), or None
        when the node is unconstrained (no NRT / non-single-NUMA policy)."""
        nrt = self._nrt(node.name)
        if nrt is None:
            return None
        policies = set(nrt.get("topologyPolicies") or [])
        if not policies & SINGLE_NUMA_POLICIES:
            return None
        free = pack_zones(nrt, node)
        tracked = set()
        for zf in free:
            tracked.update(zf)
        reqs = {r: v for r, v in pod.requests.items()
                if v > 0 and r in tracked}
        if not reqs:
            return None
        fits = [i for i, zf in enumerate(free)
                if all(zf.get(r, 0) >= v for r, v in reqs.items())]
        return free, fits

    def filter(self, state: CycleState, pod: PodInfo,
               node: NodeInfo) -> Status:
        res = self._fit_zones(pod, node)
        if res is None:
            return Status.success()
        _, fits = res
        if not fits:
            return Status.unschedulable(
                "node(s) cannot align the pod in a single NUMA zone")
        return Status.success()

    # -- Score: zone-level resource strategy -------------------------------

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        res = self._fit_zones(pod, node)
        if res is None:
            return 0.0
        free, fits = res
        if not fits:
            return 0.0
        nrt = self._nrt(node.name)
        caps = _zone_caps(nrt)
        best = 0.0
        for i in fits:
            fracs = []
            for r, v in pod.requests.items():
                cap = caps[i][1].get(r, 0)
                if v > 0 and cap > 0:
                    fracs.append((free[i].get(r, 0) - v) / cap)
            if not fracs:
                continue
            if self.strategy == "MostAllocated":
                s = 100.0 * (1.0 - sum(fracs) / len(fracs))
            elif self.strategy == "BalancedAllocation":
                sd = statistics.pstdev(fracs) if len(fracs) > 1 else 0.0
                s = 100.0 * (1.0 - sd)
            else:  # LeastAllocated
                s = 100.0 * sum(fracs) / len(fracs)
            best = max(best, s)
        return best
