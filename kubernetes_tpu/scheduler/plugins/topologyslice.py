"""TopologySlice: contiguous sub-mesh placement for slice-shaped gangs.

The topology half of shaped gang scheduling (the Coscheduling Permit
barrier is the other half): a PodGroup whose spec carries `sliceShape`
(e.g. [2, 4]) asks for its members to land on a CONTIGUOUS 2x4
sub-mesh of the interconnect (any rotation/reflection, torus
wraparound included), not just any `minMember` nodes.

How the pieces compose (all riding existing machinery, no new solver
entry):

- The first member of a group to reach PreFilter triggers the PLAN:
  the free-cell mask (nodes whose capacity fits the member request,
  minus nodes claimed by other in-flight plans) goes through the
  device kernel (topology/device.py), the winning placement's cells
  map back to node names, and each member pod is pinned to one planned
  node in arrival order.
- Filter then admits exactly the pinned node — on the batched TPU
  path that is an nnz==1 host row, which ops/backend's interning
  routes into the solver's sparse EXCEPTION COLUMNS (`pod_pin`, the
  r14 DRA pin path): the member→coordinate assignment is enforced
  INSIDE the fused solve, conflicts come back infeasible, and
  topology-free pods never see the plugin (`active_for` gate — the
  flat-capacity call graph is untouched).
- Reserve/Unreserve keep the plan ledger honest: any member failing
  downstream drops the whole plan (Coscheduling rejects the siblings,
  all-or-nothing), releasing the claimed nodes for the next attempt.
- `scheduler_slice_fragmentation_pct` is set from each plan's coverage
  scan: the free cells NO feasible placement of the requested shape
  covers — the mesh analog of the flat fragmentation headline.

Everything is inert unless KTPU_TOPOLOGY is on AND the pod belongs to
a group with a sliceShape.
"""

from __future__ import annotations

import logging

import numpy as np

from kubernetes_tpu.scheduler.framework import CycleState, Plugin, Status
from kubernetes_tpu.scheduler.plugins.coscheduling import POD_GROUP_LABEL
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.topology import device as topo_device
from kubernetes_tpu.topology.mesh import (
    MeshSpec,
    node_cell,
    normalize_shape,
    parse_mesh_shape,
)
from kubernetes_tpu.topology.slices import (
    best_placement,
    oracle_scan,
    placement_members,
)
from kubernetes_tpu.utils import flags

logger = logging.getLogger(__name__)

_STATE_KEY = "TopologySlice/node"


def group_slice_shape(pg: dict | None) -> tuple[int, int, int] | None:
    """The group's padded sliceShape, or None for count-only gangs."""
    if pg is None:
        return None
    raw = (pg.get("spec") or {}).get("sliceShape")
    if not raw:
        return None
    try:
        return normalize_shape(raw)
    except (ValueError, TypeError):
        logger.warning("PodGroup %s: bad sliceShape %r ignored",
                       (pg.get("metadata") or {}).get("name"), raw)
        return None


class _Plan:
    """One gang's committed placement: planned node names (placement
    member order) and the pod→node pins handed out so far."""

    __slots__ = ("nodes", "assigned", "bound", "frag")

    def __init__(self, nodes: list[str], frag: int):
        self.nodes = nodes
        self.assigned: dict[str, str] = {}   # pod key -> node name
        self.bound = 0
        self.frag = frag

    def pin_for(self, pod_key: str) -> str | None:
        node = self.assigned.get(pod_key)
        if node is None:
            taken = set(self.assigned.values())
            for n in self.nodes:
                if n not in taken:
                    node = n
                    break
            if node is None:
                return None  # more members than cells: mis-sized gang
            self.assigned[pod_key] = node
        return node


class TopologySlice(Plugin):
    NAME = "TopologySlice"
    EXTENSION_POINTS = ("PreFilter", "Filter", "Reserve", "PostBind")
    #: node churn and slice-gang membership churn both re-open plans.
    EVENTS = ["Node/Add", "Node/Update", "Pod/Delete"]

    def __init__(self, args=None):
        super().__init__(args)
        #: cross-shard reduction width for the winner selection (the
        #: sharded-argmax parity contract; 1 = plain host max).
        self.shards = int(self.args.get("shards", 1))
        self.scheduler = None
        self.pg_informer = None
        self.pod_informer = None
        #: group key -> live plan (in-flight or partially bound).
        self._plans: dict[str, _Plan] = {}
        #: node name -> group key holding it (two planning gangs must
        #: never pick the same node before capacity reflects either).
        self._claims: dict[str, str] = {}

    def set_scheduler(self, scheduler) -> None:
        self.scheduler = scheduler

    def set_informers(self, factory) -> None:
        from kubernetes_tpu.client import ResourceEventHandler

        self.pg_informer = factory.informer("podgroups")
        self.pod_informer = factory.informer("pods")

        def on_pod_delete(obj):
            # A planned member vanishing (gang torn down mid-flight)
            # must free the claimed nodes, or the cells leak forever.
            name = (obj.get("metadata", {}).get("labels") or {}) \
                .get(POD_GROUP_LABEL)
            if not name:
                return
            ns = obj["metadata"].get("namespace", "default")
            gk = f"{ns}/{name}"
            plan = self._plans.get(gk)
            if plan is not None \
                    and f"{ns}/{obj['metadata']['name']}" in plan.assigned:
                self._drop_plan(gk)

        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_delete=on_pod_delete))

    # -- activity gate (the backend's _FILTER_ACTIVE contract) -------------

    def _group_shape(self, pod: PodInfo):
        name = pod.labels.get(POD_GROUP_LABEL)
        if not name or self.pg_informer is None:
            return None, None
        gk = f"{pod.namespace}/{name}"
        return gk, group_slice_shape(self.pg_informer.indexer.get(gk))

    def active_for(self, pi: PodInfo) -> bool:
        """Only slice-shaped gang members under KTPU_TOPOLOGY pay; every
        other pod keeps the exact flat-capacity call graph."""
        if not flags.get("KTPU_TOPOLOGY"):
            return False
        return self._group_shape(pi)[1] is not None

    # -- planning ----------------------------------------------------------

    def _node_fits(self, ni: NodeInfo, pi: PodInfo) -> bool:
        if ni.unschedulable:
            return False
        for r, v in pi.requests.items():
            if v and ni.requested.get(r) + v > ni.allocatable.get(r):
                return False
        return ni.requested.pods + 1 <= ni.allocatable.pods

    def _drop_plan(self, gk: str) -> None:
        if self._plans.pop(gk, None) is not None:
            self._claims = {n: g for n, g in self._claims.items()
                            if g != gk}

    def _make_plan(self, gk: str, shape, pod: PodInfo,
                   snapshot: Snapshot) -> "_Plan | None":
        nodes = snapshot.nodes
        spec: MeshSpec = parse_mesh_shape(
            flags.get("KTPU_MESH_SHAPE"), len(nodes))
        cell_node: dict[int, str] = {}
        free = np.zeros((spec.cells,), dtype=np.bool_)
        for ni in nodes:
            cell = node_cell(ni.name, ni.labels, spec)
            if cell is None or cell in cell_node:
                continue
            cell_node[cell] = ni.name
            other = self._claims.get(ni.name)
            free[cell] = (other is None or other == gk) \
                and self._node_fits(ni, pod)
        scan = topo_device.device_scan(free, spec, shape)
        if scan is not None:
            key, _feas, _frag, covered = scan
            pid, frag = topo_device.decode_key(
                topo_device.best_key(key, self.shards), spec, shape)
        else:  # no orientation fits / key overflow: host oracle answers
            feas, fragv = oracle_scan(free, spec, shape)
            from kubernetes_tpu.topology.slices import coverage
            covered = coverage(feas, spec, shape)
            pid = best_placement(feas, fragv)
            frag = int(fragv[pid]) if pid >= 0 else 0
        if self.scheduler is not None \
                and getattr(self.scheduler, "metrics", None) is not None:
            self.scheduler.metrics.slice_fragmentation_pct.set(
                topo_device.fragmentation_pct(free, covered))
        if pid < 0:
            return None
        members = [cell_node[c] for c in placement_members(pid, spec, shape)]
        plan = _Plan(members, frag)
        self._plans[gk] = plan
        for n in members:
            self._claims[n] = gk
        logger.info("slice plan %s: shape %s on %s (frag=%d)",
                    gk, tuple(shape), members, frag)
        return plan

    # -- extension points --------------------------------------------------

    def pre_filter(self, state: CycleState, pod: PodInfo,
                   snapshot: Snapshot) -> Status:
        if not flags.get("KTPU_TOPOLOGY"):
            return Status.skip()
        gk, shape = self._group_shape(pod)
        if shape is None:
            return Status.skip()
        plan = self._plans.get(gk)
        if plan is None:
            plan = self._make_plan(gk, shape, pod, snapshot)
            if plan is None:
                return Status.unschedulable(
                    f"no contiguous {'x'.join(map(str, shape))} "
                    "sub-mesh is free")
        node = plan.pin_for(pod.key)
        if node is None:
            return Status.unschedulable(
                f"gang {gk} has more members than slice cells",
                resolvable=False)
        state.write(_STATE_KEY, node)
        return Status.success()

    def filter(self, state: CycleState, pod: PodInfo,
               node: NodeInfo) -> Status:
        planned = state.read(_STATE_KEY)
        if planned is None or node.name == planned:
            return Status.success()
        return Status.unschedulable(
            "node is not the planned slice cell")

    def reserve(self, state: CycleState, pod: PodInfo,
                node_name: str) -> Status:
        if not self.active_for(pod):
            return Status.success()
        gk, _shape = self._group_shape(pod)
        plan = self._plans.get(gk)
        if plan is None:
            return Status.success()  # plan dropped: Permit will reject
        if plan.assigned.get(pod.key) != node_name:
            # The solve landed a member off its planned cell (drifted
            # snapshot): tear the plan down rather than bind a bent slice.
            self._drop_plan(gk)
            return Status.unschedulable(
                f"gang {gk}: {node_name} is not the planned cell")
        return Status.success()

    def unreserve(self, state: CycleState, pod: PodInfo,
                  node_name: str) -> None:
        """Any member failing downstream kills the whole plan —
        all-or-nothing, same shape as Coscheduling's gang rejection."""
        gk, _ = self._group_shape(pod)
        if gk is not None and gk in self._plans:
            self._drop_plan(gk)

    def post_bind(self, state: CycleState, pod: PodInfo,
                  node_name: str) -> None:
        gk, _ = self._group_shape(pod)
        plan = self._plans.get(gk) if gk else None
        if plan is None:
            return
        plan.bound += 1
        if plan.bound >= len(plan.nodes):
            # Fully bound: capacity now charges the nodes, the claim
            # ledger's job is done.
            self._drop_plan(gk)
