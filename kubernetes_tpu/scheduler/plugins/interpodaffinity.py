"""InterPodAffinity: required/preferred pod (anti-)affinity.

Parity target: pkg/scheduler/framework/plugins/interpodaffinity/
{plugin.go,filtering.go,scoring.go}:

- Filter (requiredDuringSchedulingIgnoredDuringExecution):
  * anti-affinity: pod may NOT land in a topology domain (same value of
    `topologyKey` on the node) where a pod matching the term's labelSelector
    already runs — checked BOTH ways: incoming pod's terms against existing
    pods, and existing pods' required anti-affinity terms against the
    incoming pod (symmetry).
  * affinity: pod MUST land in a domain where a matching pod runs (unless no
    pod in the whole cluster matches and the pod matches its own terms —
    the "first pod in the group" rule).
- PreFilter precomputes topologyToMatchedTermCount maps (the O(pods×nodes)
  hot spot the reference parallelizes over 16 goroutines — and we tensorize).
- Score: preferred terms weighted sum, plus symmetry (existing pods'
  preferred anti/affinity terms about the incoming pod).

Namespace semantics: a term matches pods in the term's `namespaces` list
∪ the namespaces selected by its `namespaceSelector` (resolved against the
namespaces informer — the reference's GetNamespaceLabelsSnapshot merge in
PreFilter), or the owner pod's namespace when both are unset. An empty
namespaceSelector ({}) selects every namespace.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Mapping

from kubernetes_tpu.api.labels import (
    ALL_NAMESPACES,
    from_label_selector,
    is_empty_label_selector,
    ns_contains,
)
from kubernetes_tpu.scheduler.framework import (
    MAX_NODE_SCORE,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot

_STATE_KEY = "PreFilterInterPodAffinity"


class NamespaceResolver:
    """Resolves an affinity term's effective namespace set, including
    `namespaceSelector` terms, against the live Namespace objects.

    Memoized per (selector, explicit namespaces) and invalidated when any
    namespace changes (epoch). Callable: resolver(term, owner_ns) ->
    tuple of namespace names."""

    def __init__(self):
        self._informer = None
        self._epoch = 0
        self._memo: dict = {}

    def wire(self, factory) -> None:
        from kubernetes_tpu.client import ResourceEventHandler
        self._informer = factory.informer("namespaces")

        def bump(*_a):
            self._epoch += 1
            self._memo.clear()

        def on_update(old, new):
            # Resolution depends only on labels: an annotation/status
            # touch must not invalidate compiled affinity state (the
            # epoch gates an O(cluster) recompile downstream).
            if (old.get("metadata", {}).get("labels") or {}) != \
                    (new.get("metadata", {}).get("labels") or {}):
                bump()

        self._informer.add_event_handler(ResourceEventHandler(
            on_add=bump, on_update=on_update, on_delete=bump))

    @property
    def epoch(self) -> int:
        return self._epoch

    def __call__(self, term: Mapping, owner_ns: str) -> tuple[str, ...]:
        ns_sel = term.get("namespaceSelector")
        explicit = term.get("namespaces") or []
        if ns_sel is None:
            return tuple(explicit) if explicit else (owner_ns,)
        # Empty selector ({}) selects EVERY namespace (reference
        # semantics: it matches any label set, including namespaces with
        # no labels and namespaces with no Namespace object) — no
        # informer needed, and no namespace universe to enumerate.
        if is_empty_label_selector(ns_sel):
            return ALL_NAMESPACES
        key = (repr(ns_sel), tuple(explicit))
        got = self._memo.get(key)
        if got is None:
            names = set(explicit)
            if self._informer is not None:
                sel = from_label_selector(ns_sel)
                for ns_obj in self._informer.indexer.list():
                    labels = ns_obj.get("metadata", {}).get("labels") or {}
                    if sel.matches(labels):
                        names.add(ns_obj["metadata"]["name"])
            got = self._memo[key] = tuple(sorted(names))
        return got


def resolve_term_namespaces(term: Mapping, owner_ns: str,
                            resolver=None) -> tuple[str, ...]:
    """A term's effective namespace set, with or without a resolver.

    The resolver-less path is STATIC and resolver-consistent: an empty
    namespaceSelector ({}) is ALL_NAMESPACES either way; a non-empty
    selector without an informer matches only the term's explicit
    `namespaces` (exactly what an informer-less NamespaceResolver
    resolves to) — so compiled tensor rows and host plugin rows agree
    by construction."""
    if resolver is not None:
        return resolver(term, owner_ns)
    ns_sel = term.get("namespaceSelector")
    explicit = term.get("namespaces") or []
    if ns_sel is None:
        return tuple(explicit) if explicit else (owner_ns,)
    if is_empty_label_selector(ns_sel):
        return ALL_NAMESPACES
    return tuple(explicit)


def _term_matches(term: Mapping, pod_ns: str, other: PodInfo,
                  resolver=None) -> bool:
    """Does `other` match an affinity term owned by a pod in `pod_ns`?"""
    namespaces = resolve_term_namespaces(term, pod_ns, resolver)
    if not ns_contains(namespaces, other.namespace):
        return False
    return from_label_selector(term.get("labelSelector")).matches(other.labels)


class _PreFilterState:
    __slots__ = (
        "affinity_counts", "anti_affinity_counts", "existing_anti_counts",
    )

    def __init__(self):
        # (topologyKey, topologyValue) -> count of matching pods
        self.affinity_counts: dict[tuple[str, str], int] = defaultdict(int)
        self.anti_affinity_counts: dict[tuple[str, str], int] = defaultdict(int)
        # symmetry: existing pods' required anti-affinity terms that match the
        # incoming pod, counted per domain
        self.existing_anti_counts: dict[tuple[str, str], int] = defaultdict(int)


class InterPodAffinity(Plugin):
    NAME = "InterPodAffinity"
    EXTENSION_POINTS = ("PreFilter", "Filter", "PreScore", "Score")
    EVENTS = ["Pod/Add", "Pod/Delete", "Node/Add"]

    def __init__(self, args=None):
        super().__init__(args)
        self.hard_pod_affinity_weight = int(
            self.args.get("hardPodAffinityWeight", 1))
        #: namespaceSelector resolution (reference PreFilter namespace
        #: merge); works informer-less too (selector terms then match
        #: only their explicit namespaces).
        self.ns_resolver = NamespaceResolver()

    def set_informers(self, factory) -> None:
        self.ns_resolver.wire(factory)

    # -- PreFilter ---------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: PodInfo, snapshot: Snapshot) -> Status:
        has_own_terms = bool(pod.required_affinity_terms or pod.required_anti_affinity_terms)
        if not has_own_terms and not snapshot.have_pods_with_required_anti_affinity:
            return Status.skip()
        s = _PreFilterState()
        # Incoming pod's terms vs existing pods.
        for node in snapshot:
            if not node.node:
                continue
            for existing in node.pods:
                for term in pod.required_affinity_terms:
                    tk = term.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(term, pod.namespace, existing, self.ns_resolver):
                        s.affinity_counts[(tk, tv)] += 1
                for term in pod.required_anti_affinity_terms:
                    tk = term.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(term, pod.namespace, existing, self.ns_resolver):
                        s.anti_affinity_counts[(tk, tv)] += 1
            # Symmetry: existing pods' required anti-affinity vs incoming pod.
            for existing in node.pods_with_required_anti_affinity:
                for term in existing.required_anti_affinity_terms:
                    tk = term.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(term, existing.namespace, pod, self.ns_resolver):
                        s.existing_anti_counts[(tk, tv)] += 1
        state.write(_STATE_KEY, s)
        return Status.success()

    # -- Filter ------------------------------------------------------------

    def filter(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> Status:
        s: _PreFilterState | None = state.read(_STATE_KEY)
        if s is None:
            return Status.success()
        # Anti-affinity (incoming pod's own terms).
        for term in pod.required_anti_affinity_terms:
            tk = term.get("topologyKey", "")
            tv = node.labels.get(tk)
            if tv is not None and s.anti_affinity_counts.get((tk, tv), 0) > 0:
                return Status.unschedulable(
                    "node(s) didn't match pod anti-affinity rules")
        # Symmetry: existing pods' anti-affinity forbids this pod here.
        for (tk, tv), count in s.existing_anti_counts.items():
            if count > 0 and node.labels.get(tk) == tv:
                return Status.unschedulable(
                    "node(s) didn't satisfy existing pods anti-affinity rules")
        # Affinity: every term must be satisfiable in this node's domain...
        for term in pod.required_affinity_terms:
            tk = term.get("topologyKey", "")
            tv = node.labels.get(tk)
            if tv is None:
                return Status.unschedulable(
                    "node(s) didn't match pod affinity rules")
            if s.affinity_counts.get((tk, tv), 0) == 0:
                # ...unless NO pod anywhere matches ANY affinity term and the
                # pod matches its own terms (first-pod-in-group rule,
                # filtering.go `satisfyPodAffinity` nomatchingexists check).
                if not any(s.affinity_counts.values()) and all(
                    _term_matches(t, pod.namespace, pod, self.ns_resolver)
                    for t in pod.required_affinity_terms
                ):
                    continue
                return Status.unschedulable(
                    "node(s) didn't match pod affinity rules")
        return Status.success()

    # -- Score -------------------------------------------------------------

    def pre_score(self, state: CycleState, pod: PodInfo, nodes: list[NodeInfo]) -> Status:
        has_preferred = bool(pod.preferred_affinity_terms or pod.preferred_anti_affinity_terms)
        has_existing = any(n.pods_with_affinity for n in nodes)
        if not has_preferred and not has_existing:
            return Status.skip()
        # domain -> accumulated weight for the incoming pod
        scores: dict[tuple[str, str], float] = defaultdict(float)
        for node in nodes:
            for existing in node.pods:
                for term in pod.preferred_affinity_terms:
                    t = term.get("podAffinityTerm") or {}
                    tk = t.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(t, pod.namespace, existing, self.ns_resolver):
                        scores[(tk, tv)] += term.get("weight", 1)
                for term in pod.preferred_anti_affinity_terms:
                    t = term.get("podAffinityTerm") or {}
                    tk = t.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(t, pod.namespace, existing, self.ns_resolver):
                        scores[(tk, tv)] -= term.get("weight", 1)
            # Symmetry: existing pods' preferred terms about the incoming pod.
            for existing in node.pods_with_affinity:
                for term in existing.preferred_affinity_terms:
                    t = term.get("podAffinityTerm") or {}
                    tk = t.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(t, existing.namespace, pod, self.ns_resolver):
                        scores[(tk, tv)] += term.get("weight", 1)
                for term in existing.preferred_anti_affinity_terms:
                    t = term.get("podAffinityTerm") or {}
                    tk = t.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(t, existing.namespace, pod, self.ns_resolver):
                        scores[(tk, tv)] -= term.get("weight", 1)
                # Hard-affinity symmetry weighted by hardPodAffinityWeight.
                for t in existing.required_affinity_terms:
                    tk = t.get("topologyKey", "")
                    tv = node.labels.get(tk)
                    if tv is not None and _term_matches(t, existing.namespace, pod, self.ns_resolver):
                        scores[(tk, tv)] += self.hard_pod_affinity_weight
        state.write(_STATE_KEY + "/score", dict(scores))
        return Status.success()

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        scores: dict[tuple[str, str], float] = state.read(_STATE_KEY + "/score") or {}
        total = 0.0
        for (tk, tv), w in scores.items():
            if node.labels.get(tk) == tv:
                total += w
        return total

    def normalize_scores(self, state: CycleState, pod: PodInfo,
                         scores: dict[str, float]) -> None:
        if not scores:
            return
        mx, mn = max(scores.values()), min(scores.values())
        spread = mx - mn
        for k, v in scores.items():
            scores[k] = MAX_NODE_SCORE * (v - mn) / spread if spread else 0.0
