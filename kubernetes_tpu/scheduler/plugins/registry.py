"""In-tree plugin registry (plugins/registry.go `NewInTreeRegistry`)."""

from __future__ import annotations

from typing import Callable, Mapping

from kubernetes_tpu.scheduler.plugins.core import (
    DefaultBinder,
    ImageLocality,
    PrioritySort,
    SchedulingGates,
)
from kubernetes_tpu.scheduler.plugins.defaultpreemption import DefaultPreemption
from kubernetes_tpu.scheduler.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.scheduler.plugins.nodeaffinity import (
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    TaintToleration,
)
from kubernetes_tpu.scheduler.plugins.noderesources import (
    BalancedAllocation,
    NodeResourcesFit,
)
from kubernetes_tpu.scheduler.plugins.coscheduling import Coscheduling
from kubernetes_tpu.scheduler.plugins.dynamicresources import (
    DynamicResources,
)
from kubernetes_tpu.scheduler.plugins.noderesourcetopology import (
    NodeResourceTopologyMatch,
)
from kubernetes_tpu.scheduler.plugins.podtopologyspread import PodTopologySpread
from kubernetes_tpu.scheduler.plugins.topologyslice import TopologySlice
from kubernetes_tpu.scheduler.plugins.volumebinding import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
)

#: name -> factory(args) (framework/runtime Registry). Coscheduling is
#: registered but not default-enabled (out-of-tree in the reference).
IN_TREE: dict[str, Callable] = {
    "Coscheduling": Coscheduling,
    "TopologySlice": TopologySlice,
    "DynamicResources": DynamicResources,
    "NodeResourceTopologyMatch": NodeResourceTopologyMatch,
    "PrioritySort": PrioritySort,
    "SchedulingGates": SchedulingGates,
    "NodeResourcesFit": NodeResourcesFit,
    "NodeResourcesBalancedAllocation": BalancedAllocation,
    "NodeAffinity": NodeAffinity,
    "NodeName": NodeName,
    "NodeUnschedulable": NodeUnschedulable,
    "TaintToleration": TaintToleration,
    "NodePorts": NodePorts,
    "VolumeBinding": VolumeBinding,
    "VolumeRestrictions": VolumeRestrictions,
    "VolumeZone": VolumeZone,
    "NodeVolumeLimits": NodeVolumeLimits,
    "InterPodAffinity": InterPodAffinity,
    "PodTopologySpread": PodTopologySpread,
    "ImageLocality": ImageLocality,
    "DefaultPreemption": DefaultPreemption,
    "DefaultBinder": DefaultBinder,
}

#: Default enabled set (the reference's default-plugins profile).
DEFAULT_PLUGINS = [
    "PrioritySort",
    "SchedulingGates",
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "VolumeBinding",
    "VolumeRestrictions",
    "VolumeZone",
    "NodeVolumeLimits",
    "NodeResourcesFit",
    "NodeResourcesBalancedAllocation",
    "InterPodAffinity",
    "PodTopologySpread",
    "ImageLocality",
    "DynamicResources",
    "DefaultPreemption",
    "DefaultBinder",
]

#: Default score weights (defaults.go: NodeResourcesFit=1, Balanced=1,
#: InterPodAffinity=1 (hard weight separate), PodTopologySpread=2, ...).
DEFAULT_SCORE_WEIGHTS = {
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "NodeAffinity": 2,
    "InterPodAffinity": 2,
    "PodTopologySpread": 2,
    "TaintToleration": 3,
    "ImageLocality": 1,
}


def build_plugins(
    enabled: list[str] | None = None,
    plugin_config: Mapping[str, Mapping] | None = None,
    store=None,
) -> list:
    """Instantiate plugins by name with per-plugin args
    (KubeSchedulerConfiguration pluginConfig)."""
    enabled = enabled or DEFAULT_PLUGINS
    plugin_config = plugin_config or {}
    out = []
    for name in enabled:
        factory = IN_TREE.get(name)
        if factory is None:
            raise KeyError(f"unknown plugin {name!r}")
        args = plugin_config.get(name)
        if name == "DefaultBinder":
            out.append(factory(args, store=store))
        else:
            out.append(factory(args))
    return out
