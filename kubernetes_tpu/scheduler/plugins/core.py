"""Queue-order, gating, image-locality and binding plugins.

Parity targets: pkg/scheduler/framework/plugins/{queuesort/priority_sort.go,
schedulinggates/scheduling_gates.go, imagelocality/image_locality.go,
defaultbinder/default_binder.go}.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import make_binding
from kubernetes_tpu.scheduler.framework import (
    MAX_NODE_SCORE,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo


class PrioritySort(Plugin):
    """QueueSort: priority desc, then queue-entry time (FIFO)."""

    NAME = "PrioritySort"
    EXTENSION_POINTS = ("QueueSort",)

    def less(self, a: PodInfo, b: PodInfo) -> bool:
        if a.priority != b.priority:
            return a.priority > b.priority
        return a.queued_at < b.queued_at

    def key(self, pi: PodInfo) -> tuple:
        """Heap key equivalent of less() for the queue's heap."""
        return (-pi.priority, pi.queued_at)


class SchedulingGates(Plugin):
    """PreEnqueue: pods with non-empty spec.schedulingGates stay out of the
    queue until the gates are removed."""

    NAME = "SchedulingGates"
    EXTENSION_POINTS = ("PreEnqueue",)
    EVENTS = ["Pod/Update"]

    def pre_enqueue(self, pod: PodInfo) -> Status:
        if pod.scheduling_gates:
            return Status.unschedulable(
                f"waiting for scheduling gates: {pod.scheduling_gates}",
                resolvable=False)
        return Status.success()


class ImageLocality(Plugin):
    """Score: prefer nodes that already hold the pod's images, scaled by how
    widely the image is spread (image_locality.go `calculatePriority`:
    sumScores clamped to [23MB, 1000MB] mapped to 0..100; we use presence
    fraction × spread factor since we don't track image sizes)."""

    NAME = "ImageLocality"
    EXTENSION_POINTS = ("Score",)

    def score(self, state: CycleState, pod: PodInfo, node: NodeInfo) -> float:
        images = [
            c.get("image", "") for c in pod.pod.get("spec", {}).get("containers", [])
            if c.get("image")
        ]
        if not images or not node.image_names:
            return 0.0
        present = sum(1 for img in images if img in node.image_names)
        return MAX_NODE_SCORE * present / len(images)


class DefaultBinder(Plugin):
    """Bind: POST the Binding subresource (defaultbinder/default_binder.go:
    `b.handle.ClientSet().CoreV1().Pods(ns).Bind(...)`)."""

    NAME = "DefaultBinder"
    EXTENSION_POINTS = ("Bind",)

    def __init__(self, args=None, store=None):
        super().__init__(args)
        self.store = store

    async def bind(self, state: CycleState, pod: PodInfo, node_name: str) -> Status:
        if self.store is None:
            return Status.error("DefaultBinder has no store client")
        from kubernetes_tpu.store.mvcc import StoreError
        try:
            await self.store.subresource(
                "pods", pod.key, "binding", make_binding(pod.pod, node_name))
        except StoreError as e:
            return Status.error(f"binding rejected: {e}")
        return Status.success()
